"""Benchmark: GPT-2-small causal-LM training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu"}.

Baseline: BASELINE.json config 1 ("HF GPT-2-small, ZeRO-1, single host").
The reference publishes no single-chip GPT-2 tokens/sec number, so
vs_baseline is computed against model-FLOPs utilisation: reference Ulysses
sustains >54% of peak on A100s (blogs/deepspeed-ulysses/README.md:82);
we report achieved MFU / 0.54 as the ratio.

Round-2 profiling notes (jax profiler, per-fusion, on the tunneled v5e):
- MLP/vocab matmuls run at 164-190 TF/s (83-96% of the 197 TF/s peak);
  HBM streams at ~700 GB/s — the chip itself is near spec.
- Attention is the bottleneck: the XLA softmax path is at its two-pass
  traffic floor (write scores + two fused re-reads, ~2.4 GB/layer); fwd
  3.8 ms + bwd 12.2 ms per layer = ~190 of the 377 ms step.
- Pallas/Mosaic kernels CANNOT fix it on this rig: Mosaic matmuls measure
  1-15 TF/s through the axon AOT compile path (a VMEM-resident looped
  512^3 matmul hits 1 TF/s; the repo flash kernel and jax's own
  pallas flash/splash kernels are all slower than the XLA path here).
  attention_impl="flash" therefore stays off for this bench; the kernel
  remains the right choice for non-virtualized TPUs.
- Also measured: triangle-chunked causal attention (skips masked blocks)
  is ~neutral (op-count overhead eats the 37% traffic saving); remat
  named-saves of softmax stats are net negative; batch 16/32/64 and
  unrolled-vs-scan layer loops are all within noise.
- Round-2 wins: flash-style custom VJP in pure XLA
  (ops/xla_attention.py — lse residual, delta from dO*O, single-exp probs
  recompute) + a remat policy saving attn_out/attn_lse:
  83.0k -> 95.7k tok/s (+15%). Batch 40 regresses, 48 OOMs.
  Then block-causal decomposition (8 q-blocks, each attending only its
  visible key prefix — upper-triangle block quadrants never computed):
  95.7k -> 105.9k tok/s (46.2% MFU, vs_baseline 0.856).

Round-3 wins (hlo_stats per-fusion profile led here):
- UNROLL THE LAYER SCAN (scan_unroll=12): the profile showed ~60 ms/step
  of bitcast_dynamic-update-slice fusions — scan-carry writes of stacked
  grad accumulators + remat-saved activations — NOT attention. Full
  unroll removes them: 106.2k -> 117.9k (+11%). Partial unroll=4 is
  WORSE than scan (97k): the DUS machinery stays but bodies replicate.
- remat OFF (activations stored, no recompute): +3.5% -> 122.0k. With
  the unrolled graph batch 32 fits; 40/24 are both slower.
- Pairwise block-causal backward (dk/dv accumulate per (q,k) block pair,
  written once per key block; pair blocks S/4): +1% over the prefix-RMW
  form under unroll (and the RMW form's 8x fp32 prefix adds are gone).
- Fused-QKV concat matmul: tried, REGRESSES (117.9 -> 107.1k) — the
  concat + split backward costs more than one wider matmul saves.
- Residual floor: vocab head ~49 ms/step (matmuls at ~178 TF/s = 90%
  peak, lse read at HBM floor), attention elementwise ~remaining HBM
  time. Profile: 263.6 ms/step self-time, 141 Compute + 114 HBM-bound.

Round-4 decode floor analysis (tools/profile_decode8b.py hlo_stats,
measured 2026-07-31 on the v5e):
- Round 3's 8B decode (243 ms/token EMA) was NOT weight-bandwidth-bound
  as claimed: (a) the einsum-form projections kept the int8 dequant from
  fusing into the matmul (bf16 materialization ~40 GB/forward), and
  (b) the first timed burst carried a context-bucket recompile that
  seeded the EMA.  The _mm 2D-matmul refactor (inference/model.py:191)
  fixed (a) structurally: the top fusions now read s8 weights DIRECTLY
  at 677-685 GiB/s ("Bound by HBM" at int8 byte count, profile row 1-3
  = wi/wg/wo_mlp at 20.8 ms/burst each); a second settle burst fixed
  (b).  Budget per 64-token burst: 128 ms device self-time (97 ms conv
  fusions ~= 1.17x the 83 ms int8-weight floor, 24 ms loop fusions =
  attention/elementwise, 6 ms formatting) + ~100 ms host/tunnel gap.
  Result: 25.1 ms/token EMA = 1.8x the ~12-14 ms written-down floor
  (56 GB int8 weights + ~9 GB KV prefix per burst at 700 GB/s), vs
  243 ms in round 3.  All three FastGen SLA tiers (prompt >=512
  tok/s/seq + EMA 2/4/6 tok/s) are met at 1.15 QPS on one v5e chip
  (goodput saturates between 2 and 4 QPS arrival rate).

Round-3 llama legs (measured 2026-07-31 on the v5e):
- llama-0.7B train (seq 2048, ZeRO-3): 24.1k tok/s, 57.9% MFU
  (full four-leg run; 23.75k standalone).
- llama3-8b int8 serving (8 seqs x 512-tok prompts, budget 512):
  first measurement prompt 891 tok/s / TTFT 2.58 s / decode 19.2 tok/s;
  the burst profile showed the GROUPED-FLAT dequant chain dominating
  (int8 -> f32 convert -> grouped reshape -> LAYOUT COPY -> f32 matmul
  + a materialized scale broadcast, ~6x the int8 bytes per use).
  Switching serving weights to the ROW-WISE weight-shaped int8 layout
  (quant.quantize_rowwise: per-row scales, data in the weight's own
  shape, dequant computed in bf16 so it fuses into the matmul operand)
  gave prompt 1807 tok/s, TTFT p50 1.27 s, decode 74.6 tok/s
  (265 ms/token EMA) — 2-4x across the board (full four-leg run:
  1761 / 1.31 s / 80.9); prefill budget 1024 then lifts prompt
  throughput to 2244 tok/s / TTFT 1.14 s. Decode remains
  weight-traffic-bound; the next step is a mixed-input Pallas GEMM
  (dequant in VMEM tiles), blocked on Mosaic through this tunnel.
  W8A8 (int8 x int8 -> int32 MXU dots) was probed and is NOT a win on
  this rig: int8 dots time ~1.6x SLOWER than bf16 dots through the
  axon path (11.4 vs 7.1 ms at 512x4096x14336), so dynamic activation
  quantization would add error for negative throughput.
  Getting 8B serving to run at all required two structural fixes: the
  quant tree must ride the jit as ARGUMENTS (a closure bakes 7.5 GB of
  HLO constants -> remote compile death) and the engine must accept
  pre-built quant trees (InferenceEngine(quant_tree=...)) because a
  dense 8B init/quantize pass takes >1 h on this 1-core host.
"""

import json
import sys
import time

import numpy as np


def _serving_device():
    """First device of the default backend — falling back to CPU when
    the configured platform cannot initialize (every BENCH_r0* on a
    TPU-less container died rc=1 with JaxRuntimeError right here at
    jax.devices(); a bench that cannot measure the accelerator should
    still measure the code).  The platform actually used is recorded in
    the result JSON."""
    import os

    import jax
    try:
        return jax.devices()[0]
    except Exception as e:
        print(  # tpulint: disable=print — CLI diagnostic on stderr
            f"bench: default JAX backend unavailable "
            f"({type(e).__name__}: {str(e).splitlines()[0][:120]}); "
            f"falling back to JAX_PLATFORMS=cpu", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"   # children / late imports
        jax.config.update("jax_platforms", "cpu")
        try:
            import jax.extend.backend as jeb
            jeb.clear_backends()
        except Exception:  # tpulint: disable=silent-except — API probe
            jax.clear_backends()    # pre-0.4.34 spelling
        return jax.devices()[0]


def main(trace_path=None, profile_dir=None):
    """``trace_path``: export a Chrome trace (Perfetto-loadable) of the
    pipelined serving leg's depth-2 run (``--trace out.json``).
    ``profile_dir``: additionally arm a deep-capture window on that leg
    and emit a MERGED host+device timeline via tools/tracemerge.py
    (``--profile out/``)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model

    dev = _serving_device()
    on_tpu = dev.platform == "tpu"

    seq = 1024 if on_tpu else 128
    batch = 32 if on_tpu else 2
    model = build_model("gpt2", max_seq_len=seq, remat=False,
                        scan_unroll=12,
                        attention_impl="xla_flash",
                        **({} if on_tpu else
                           dict(num_layers=2, d_model=128, num_heads=4,
                                vocab_size=1024)))
    cfg = model.config
    config = {
        "train_micro_batch_size_per_device": batch,
        "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": -1},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
        # device telemetry (docs/OBSERVABILITY.md): per-program
        # cost_analysis + memory gauges embedded in train_metrics —
        # the probe's duplicate compile lands in the warmup, outside
        # every timed window
        "telemetry": {"device": True},
    }
    engine = ds.initialize(model=model, config=config)
    from deepspeed_tpu.runtime.dataloader import (DataLoader,
                                                  PrefetchingLoader,
                                                  synthetic_lm_data)

    n = 10 if on_tpu else 3
    windows = 3 if on_tpu else 1
    data = synthetic_lm_data(cfg.vocab_size,
                             engine.train_batch_size * (n * windows + 4),
                             seq)
    loader = PrefetchingLoader(
        DataLoader(data, engine.train_batch_size), engine)
    it = iter(loader)
    for _ in range(2):                      # compile + steady state
        m = engine.train_batch(next(it))
    float(m["loss"])                        # drain warmup before timing
    engine.metrics.reset()                  # telemetry covers the timed
    #                                         window only, not the compile
    # median of several windows — shared/tunneled chips are noisy; each
    # window ends with a host fetch of a step-output scalar, the only
    # reliable completion barrier (block_until_ready is advisory here)
    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        m = None
        for _ in range(n):
            m = engine.train_batch(next(it))
        float(m["loss"])
        rates.append(time.perf_counter() - t0)
    dt = sorted(rates)[len(rates) // 2]

    tokens_per_step = engine.train_batch_size * (seq - 1)
    tok_s = n * tokens_per_step / dt
    # host-phase telemetry of the timed window (docs/OBSERVABILITY.md):
    # per-phase ms counters + the host-wall histogram summary
    train_metrics = engine.metrics_snapshot()
    # compiler/device view: train-step cost_analysis + memory poll
    train_device = engine.devtel.snapshot() if engine.devtel else None

    # model FLOPs: 6 * n_params * tokens (fwd+bwd), attention extra term
    from deepspeed_tpu.runtime import param_count
    n_params = param_count(model.params)
    attn_flops = 12 * cfg.num_layers * cfg.d_model * (seq - 1)  # per token
    flops_per_token = 6 * n_params + attn_flops
    achieved = tok_s * flops_per_token
    # bf16 peak per chip by generation; CPU fallback has no meaningful peak
    peaks = {"v4": 275e12, "v5 lite": 197e12, "v5e": 197e12,
             "v5p": 459e12, "v5": 459e12, "v6e": 918e12, "v6": 918e12}
    kind = getattr(dev, "device_kind", "").lower()
    peak = next((v for k, v in peaks.items() if k in kind), 197e12) \
        if on_tpu else 1e12
    mfu = achieved / peak
    vs_baseline = mfu / 0.54 if on_tpu else 0.0

    # free each leg's HBM before the next: the engines' donated state and
    # compiled executables stay alive through main()'s locals otherwise
    # (the llama train leg OOMed behind the GPT-2 engine's 2.5 GB)
    import gc
    import traceback
    del engine, loader, it, data, model

    # each secondary leg is fail-soft: a single leg's OOM/compile failure
    # must never cost the whole bench capture (the headline gpt2s number
    # above is already measured by this point)
    def leg(fn, *a):
        gc.collect()
        try:
            return fn(*a)
        except Exception as e:
            traceback.print_exc()
            name = getattr(fn, "__name__", "leg")
            return {f"{name}_error": f"{type(e).__name__}: "
                    f"{(str(e).splitlines() or [''])[0][:120]}"}

    serve = leg(serving_bench, on_tpu)
    pipe = leg(pipeline_serving_bench, on_tpu, trace_path, profile_dir)
    prefix = leg(shared_prefix_serving_bench, on_tpu)
    spec = leg(spec_decode_serving_bench, on_tpu)
    overload = leg(overload_serving_bench, on_tpu)
    chaos = leg(chaos_serving_bench, on_tpu)
    fleet = leg(fleet_serving_bench, on_tpu)
    tiered = leg(tiered_kv_serving_bench, on_tpu)
    disagg = leg(disagg_serving_bench, on_tpu)
    autoscale = leg(autoscale_serving_bench, on_tpu)
    http = leg(http_serving_bench, on_tpu)
    llama_train = leg(llama_train_bench, on_tpu, peak)
    llama_serve = leg(llama8b_serving_bench, on_tpu)
    moe = leg(moe_train_bench, on_tpu, peak)
    comm = leg(comm_overlap_bench, on_tpu)

    out = {
        "metric": "gpt2s_train_tokens_per_sec_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "platform": dev.platform,
        "vs_baseline": round(vs_baseline, 4),
        "mfu": round(mfu, 4) if on_tpu else 0.0,
        # engine version + a digest of the benchmark-relevant config
        # DEFAULTS: successive BENCH_r* files are only comparable when
        # these match — a PR that changes a default shifts every leg,
        # and the hash makes that visible instead of silently skewing
        # the trajectory (bench_fingerprint())
        **bench_fingerprint(),
        "train_metrics": train_metrics,
        "train_device_metrics": train_device,
    }
    out.update(serve)
    print(json.dumps({**out, **pipe, **prefix, **spec, **overload,  # tpulint: disable=print — the bench's one JSON output line
                      **chaos, **fleet, **tiered, **disagg, **autoscale,
                      **http, **llama_train,
                      **llama_serve, **moe, **comm}))


def bench_fingerprint():
    """Version + config-default fingerprint recorded in every BENCH
    JSON capture: ``engine_version`` and a short digest over the
    serving/overload/failure config defaults (the knobs whose defaults
    PRs keep evolving — pipeline depth, donation, prefix cache, spec
    decode, shed policy, watchdog...).  Two BENCH files with different
    hashes measured different default engines; compare legs only
    within a hash — which is exactly how ``tools/benchdiff.py`` gates:
    matching hash => hard per-leg thresholds, changed hash =>
    report-only.  ONE implementation, shared with the flight
    recorder's post-mortems (telemetry/flight.py), so BENCH captures
    and black-box dumps join on the same key."""
    from deepspeed_tpu.telemetry import config_fingerprint

    return config_fingerprint()


def comm_overlap_bench(on_tpu: bool):
    """Overlapped-vs-serial collective microbench (T3 arxiv 2401.16677
    tile decomposition + EQuARX arxiv 2506.17615 quantized wire;
    docs/SERVING.md "Overlapped & quantized collectives").

    Four comm plans over the same row-parallel GEMM: serial psum,
    tile-decomposed psum (bitwise-exact), ppermute ring, int8 quantized
    wire — numerics cross-checked inside the leg before timing.  On a
    real multi-chip backend it measures the actual fabric in-process;
    with one local device it runs in a CHILD process on an 8-device
    virtual CPU mesh (the MULTICHIP driver's trick — the parent's
    backend stays untouched).  The headline ``comm_*_ms`` /
    ``comm_*_speedup`` metrics land top-level in the BENCH JSON, where
    ``tools/benchdiff.py``'s existing direction rules gate them."""
    import os
    import subprocess

    import jax

    if len(jax.devices()) > 1:
        from deepspeed_tpu.comm.bench import overlap_bench

        rec = overlap_bench(trials=10, warmups=3)
    else:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=8")
        if not any("concurrency_optimized_scheduler" in f for f in flags):
            flags.append(
                "--xla_cpu_enable_concurrency_optimized_scheduler=false")
        env["XLA_FLAGS"] = " ".join(flags)
        here = os.path.dirname(os.path.abspath(__file__))
        env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.comm.bench",
             "--overlap", "--trials", "10"],
            capture_output=True, text=True, env=env, check=True, cwd=here)
        rec = json.loads(out.stdout.strip().splitlines()[-1])
    return {"comm_overlap_bench": rec,
            "comm_serial_ms": rec["comm_serial_ms"],
            "comm_overlapped_ms": rec["comm_overlapped_ms"],
            "comm_overlap_speedup": rec["comm_overlap_speedup"],
            "comm_quant_speedup": rec["comm_quant_speedup"]}


def chaos_serving_bench(on_tpu: bool):
    """Fault-tolerance leg (docs/SERVING.md "Failure domains &
    recovery"): the loadgen chaos smoke — injected crash + watchdog
    expiry + a uid-targeted poison request + a mid-traffic
    snapshot/restore warm restart, across greedy/seeded sampling and
    prefix cache on/off — run as a bench capture.  The acceptance
    asserts run inside (never deadlocks, never leaks, exactly one
    terminal status each, unaffected requests token-identical to a
    fault-free run); the JSON records the per-variant recovery
    telemetry (retries, failed, restarts, steps)."""
    from tools.loadgen import chaos_smoke

    out = chaos_smoke(seed=0)
    return {"chaos_serving": {
        "ok": out["ok"],
        "variants": out["variants"],
    }}


def fleet_serving_bench(on_tpu: bool):
    """Replica-fleet leg (docs/SERVING.md "Fleet: routing, failover,
    migration"): the loadgen fleet sweep — one shared-prefix workload
    through 1 replica, then a 3-replica fleet under cache-affinity
    placement with a mid-sweep replica KILL, then the same fleet under
    round-robin (the affinity bar's baseline).  The headline metrics
    land top-level so ``tools/benchdiff.py``'s existing direction
    rules gate them: ``*_goodput_tok_s`` / ``*_hit_rate`` up-is-better,
    ``*_ttft_*_ms`` down-is-better.  The affinity acceptance bar —
    cache-affinity placement beats round-robin's measured prefix hit
    rate on this workload — is asserted by tests/test_router.py; the
    JSON records the margin."""
    from tools.loadgen import fleet_bench

    out = fleet_bench(seed=0)
    return {"fleet_serving": out,
            "fleet_goodput_tok_s": out["affinity"]["goodput_tok_s"],
            "fleet_single_goodput_tok_s": out["single"]["goodput_tok_s"],
            "fleet_affinity_hit_rate": out["affinity"]["hit_rate"],
            "fleet_round_robin_hit_rate": out["round_robin"]["hit_rate"],
            "fleet_ttft_p95_prekill_ms":
                out["affinity"]["ttft_p95_prekill_ms"],
            "fleet_ttft_p95_postkill_ms":
                out["affinity"]["ttft_p95_postkill_ms"],
            # fleet observability diagnostics (docs/OBSERVABILITY.md
            # "Fleet observability"): fleet + per-replica anomaly
            # tallies (benchdiff REPORTS their deltas, never gates)
            # and the aggregated fleet device metrics
            "fleet_serving_anomalies": out["affinity"]["anomalies"],
            "fleet_device_metrics": out["affinity"]["device_metrics"]}


def tiered_kv_serving_bench(on_tpu: bool):
    """Tiered-KV leg (docs/KV_TIERING.md): a revisit-heavy prefix
    workload whose working set is >4x the KV pool, through
    discard-on-evict / tiered / all-HBM arms at identical shapes, plus
    the fleet remote-restage-vs-re-prefill arm.  Token parity across
    arms and tier-counter consistency (revives never outrun demotions,
    zero verify failures) are asserted inside before anything is
    recorded.  The headline metrics land top-level for
    ``tools/benchdiff.py``'s direction rules: ``tiered_kv_hit_rate``
    up-is-better, the ``*_ttft_*`` keys down-is-better — including
    ``tiered_kv_ttft_vs_allhbm``, the 1.25x acceptance bar (tiered p95
    TTFT over the all-HBM ceiling) — and
    ``tiered_kv_remote_restage_speedup`` (re-prefill TTFT over
    cross-replica restage TTFT) up-is-better."""
    from tools.loadgen import tiered_kv_bench

    out = tiered_kv_bench(seed=0)
    return {"tiered_kv": out,
            "tiered_kv_hit_rate": out["tiered"]["hit_rate"],
            "tiered_kv_ttft_p95_ms": out["tiered"]["ttft_ms_p95"],
            "tiered_kv_baseline_ttft_p95_ms":
                out["baseline"]["ttft_ms_p95"],
            "tiered_kv_allhbm_ttft_p95_ms": out["allhbm"]["ttft_ms_p95"],
            "tiered_kv_ttft_vs_allhbm": out["ttft_vs_allhbm"],
            "tiered_kv_remote_restage_speedup":
                out["remote_restage_speedup"]}


def disagg_serving_bench(on_tpu: bool):
    """Disaggregation leg (docs/SERVING.md "Disaggregated pools &
    elasticity"): ONE seeded mixed-SLO trace through a 3-mixed-replica
    colocated fleet (chunked prefill — the strongest colocated
    baseline) and a 2-prefill + 1-decode disaggregated fleet at EQUAL
    replica count.  The headline metrics land top-level so
    ``tools/benchdiff.py``'s existing direction rules gate them:
    ``disagg_interactive_speedup`` (colocated p95 TTFT rounds over
    disaggregated — the acceptance bar is > 1.0: pools win at
    identical hardware) up-is-better, the ``disagg_*_ttft_*_ms`` pair
    down-is-better, ``disagg_goodput_tok_s`` up-is-better."""
    from tools.loadgen import disagg_bench

    out = disagg_bench(seed=0)
    return {"disagg_serving": out,
            "disagg_interactive_speedup":
                out["disagg_interactive_speedup"],
            "disagg_ttft_p95_interactive_ms":
                out["disagg"]["ttft_p95_interactive_ms"],
            "disagg_colocated_ttft_p95_interactive_ms":
                out["colocated"]["ttft_p95_interactive_ms"],
            "disagg_goodput_tok_s": out["disagg"]["goodput_tok_s"],
            "disagg_colocated_goodput_tok_s":
                out["colocated"]["goodput_tok_s"]}


def autoscale_serving_bench(on_tpu: bool):
    """Elasticity leg (docs/SERVING.md "Disaggregated pools &
    elasticity"): the loadgen scaling chaos smoke — a seeded load
    swing through a disaggregated fleet with the signal-driven
    actuator attached — run as a bench capture.  The acceptance
    asserts run inside (pool scales up AND back down, zero lost
    requests, exact token parity, handoff journeys); the JSON records
    the decision log and swing telemetry."""
    from tools.loadgen import scale_chaos_smoke

    out = scale_chaos_smoke(seed=0)
    return {"autoscale_serving": {
        "ok": out["ok"],
        "variants": out["variants"],
    }}


def http_serving_bench(on_tpu: bool):
    """Sockets-to-tokens leg (docs/SERVING.md "Network gateway"): the
    same seeded bursty trace through the in-process ``replay`` driver
    and through real loopback sockets against a spawned gateway, with
    token parity asserted inside before anything is recorded.  The
    headline metrics land top-level so ``tools/benchdiff.py``'s
    existing direction rules gate them: ``http_goodput_tok_s`` /
    ``inproc_goodput_tok_s`` up-is-better, ``http_ttft_p95_ms`` /
    ``inproc_ttft_p95_ms`` down-is-better, and the measured wire
    overhead ``http_ttft_overhead_ratio`` (client-wall p95 over
    in-process engine-record p95) is gated down-is-better too — a PR
    that makes the gateway slower relative to the engine fails the
    same-config compare even when both got faster in absolute terms."""
    from tools.loadgen import http_bench

    out = http_bench(seed=0)
    return {"http_serving": out,
            "http_goodput_tok_s": out["http_goodput_tok_s"],
            "inproc_goodput_tok_s": out["inproc_goodput_tok_s"],
            "http_ttft_p95_ms": out["http_ttft_p95_ms"],
            "inproc_ttft_p95_ms": out["inproc_ttft_p95_ms"],
            "http_ttft_overhead_ratio": out["http_ttft_overhead_ratio"]}


def moe_train_bench(on_tpu: bool, peak: float):
    """8-expert MoE training on one chip (BASELINE config 4 is Mixtral
    EP x SP; EP multichip correctness is witnessed by the driver dryrun's
    expert=2 leg — this leg gives MoE its real-TPU perf signal).  Times
    BOTH dispatch modes at the same shapes: 'ragged' (dropless
    lax.ragged_dot grouped GEMM, parallel/moe.py:215 megablox analog) vs
    'scatter' (capacity-bounded index dispatch)."""
    import gc
    import time

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.runtime import param_count
    from deepspeed_tpu.runtime.dataloader import (DataLoader,
                                                  PrefetchingLoader,
                                                  synthetic_lm_data)

    seq = 1024 if on_tpu else 128
    batch = 8 if on_tpu else 2
    out = {}
    for mode in ("ragged", "scatter"):
        model = build_model(
            "gpt2", max_seq_len=seq, num_experts=8, moe_top_k=2,
            moe_dispatch=mode,
            **(dict(num_layers=6, d_model=768, num_heads=12,
                    scan_unroll=6, remat=False,
                    attention_impl="xla_flash") if on_tpu else
               dict(num_layers=2, d_model=128, num_heads=4,
                    vocab_size=1024)))
        cfg = model.config
        engine = ds.initialize(model=model, config={
            "train_micro_batch_size_per_device": batch,
            "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "mesh": {"data": -1},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
            "telemetry": {"device": True},
        })
        data = synthetic_lm_data(cfg.vocab_size,
                                 engine.train_batch_size * 12, seq)
        loader = PrefetchingLoader(
            DataLoader(data, engine.train_batch_size), engine)
        it = iter(loader)
        for _ in range(2):
            m = engine.train_batch(next(it))
        float(m["loss"])
        engine.metrics.reset()              # exclude compile from telemetry
        n = 5 if on_tpu else 2
        t0 = time.perf_counter()
        for _ in range(n):
            m = engine.train_batch(next(it))
        float(m["loss"])
        dt = time.perf_counter() - t0
        tok_s = n * engine.train_batch_size * (seq - 1) / dt
        if mode == "ragged":
            # active-param MFU: top-k of num_experts per token
            n_params = param_count(model.params)
            expert_params = param_count(model.params["blocks"]["experts"])
            active = n_params - expert_params \
                * (cfg.num_experts - cfg.moe_top_k) // cfg.num_experts
            fpt = 6 * active + 12 * cfg.num_layers * cfg.d_model * (seq - 1)
            out["moe8x_train_mfu_active"] = round(
                tok_s * fpt / peak, 4) if on_tpu else 0.0
        out[f"moe8x_train_tok_s_{mode}"] = round(tok_s, 1)
        out[f"moe8x_train_metrics_{mode}"] = engine.metrics_snapshot()
        out[f"moe8x_train_device_metrics_{mode}"] = \
            engine.devtel.snapshot() if engine.devtel else None
        del engine, loader, it, data, model
        gc.collect()
    return out


def llama_train_bench(on_tpu: bool, peak: float):
    """Llama-architecture training on one chip (BASELINE configs 2-3 are
    llama-class): ~0.7B llama (RoPE/GQA/SwiGLU/RMSNorm, seq 2048) under
    ZeRO-3.  cpu optimizer offload is deliberately NOT configured here:
    through the axon tunnel the in-jit host<->device transfers of the
    host-compute update KILL the remote TPU worker (asynchronously — the
    engine's catch-and-fall-back never sees the error), measured
    2026-07-30.  On bare-metal TPU add offload_optimizer back."""
    import time

    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.runtime import param_count
    from deepspeed_tpu.runtime.dataloader import (DataLoader,
                                                  PrefetchingLoader,
                                                  synthetic_lm_data)

    seq = 2048 if on_tpu else 128
    batch = 2 if on_tpu else 2
    model = build_model(
        "llama-tiny",
        **(dict(vocab_size=32000, num_layers=12, d_model=2048,
                num_heads=16, num_kv_heads=8, d_ff=5504, max_seq_len=seq,
                scan_unroll=12, remat=True, remat_policy="xla_flash",
                attention_impl="xla_flash") if on_tpu else
           dict(vocab_size=512, num_layers=2, d_model=128, num_heads=4,
                num_kv_heads=2, d_ff=352, max_seq_len=seq)))
    cfg = model.config
    engine = ds.initialize(model=model, config={
        "train_micro_batch_size_per_device": batch,
        "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
        "mesh": {"data": -1},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
        "telemetry": {"device": True},
    })
    data = synthetic_lm_data(cfg.vocab_size,
                             engine.train_batch_size * 16, seq)
    loader = PrefetchingLoader(
        DataLoader(data, engine.train_batch_size), engine)
    it = iter(loader)
    for _ in range(2):
        m = engine.train_batch(next(it))
    float(m["loss"])
    engine.metrics.reset()                  # exclude compile from telemetry
    n = 5 if on_tpu else 2
    t0 = time.perf_counter()
    for _ in range(n):
        m = engine.train_batch(next(it))
    float(m["loss"])
    dt = time.perf_counter() - t0
    tok_s = n * engine.train_batch_size * (seq - 1) / dt
    n_params = param_count(model.params)
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.d_model \
        * (seq - 1)
    mfu = tok_s * flops_per_token / peak if on_tpu else 0.0
    return {
        "llama07b_train_tok_s": round(tok_s, 1),
        "llama07b_train_mfu": round(mfu, 4),
        "llama07b_train_metrics": engine.metrics_snapshot(),
        "llama07b_train_device_metrics":
            engine.devtel.snapshot() if engine.devtel else None,
    }


def _synthetic_int8_llama(cfg):
    """Build (dense_remainder, quant_tree) for a llama config DIRECTLY in
    the quantized representation — no fp32 init, no host-side
    quantization pass (what a quantized-checkpoint loader would produce;
    this bench measures serving throughput, not model quality).  Arrays
    are tile-filled (memcpy speed) and device_put once."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.models.transformer import init_params
    from deepspeed_tpu.ops.quant import QuantizedTensor

    shapes = jax.eval_shape(lambda k: init_params(cfg, k)[0],
                            jax.random.PRNGKey(0))
    tile_i8 = np.frombuffer(np.random.RandomState(0).bytes(1 << 20),
                            np.int8)
    tile_f = (np.frombuffer(np.random.RandomState(1).bytes(1 << 22),
                            np.uint8).astype(np.float32) - 127.5) / 2900.0

    def fill_i8(shape):
        n = int(np.prod(shape))
        return jax.device_put(np.resize(tile_i8, n).reshape(shape))

    def fill_f(shape, dtype=jnp.bfloat16):
        n = int(np.prod(shape))
        return jax.device_put(
            np.resize(tile_f, n).reshape(shape).astype(dtype))

    quantizable = ("wq", "wk", "wv", "wo", "wi", "wg")

    def build(tree):
        out = {}
        for name, sub in tree.items():
            if isinstance(sub, dict):
                out[name] = build(sub)
            else:
                out[name] = (jnp.ones(sub.shape, jnp.bfloat16)
                             if name in ("scale", "bias")
                             else fill_f(sub.shape))
        return out

    dense = {}
    quant = {"blocks": {}}
    for top, sub in shapes.items():
        if top == "blocks":
            dense["blocks"] = {}
            for gname, grp in sub.items():
                dgrp, qgrp = {}, {}
                for name, sds in grp.items():
                    if name in quantizable and len(sds.shape) >= 3:
                        # row-wise weight-shaped int8 (see
                        # quant.quantize_rowwise): dequant fuses into the
                        # matmul, no grouped-flat relayout
                        L, d0 = sds.shape[0], sds.shape[1]
                        sc = (L, d0) + (1,) * (len(sds.shape) - 2)
                        qgrp[name] = QuantizedTensor(
                            fill_i8(sds.shape),
                            jax.device_put(np.full(sc, 0.004, np.float32)),
                            None, 8, tuple(sds.shape), jnp.bfloat16)
                    else:
                        dgrp[name] = (jnp.ones(sds.shape, jnp.bfloat16)
                                      if "ln" in gname
                                      else fill_f(sds.shape))
                dense["blocks"][gname] = dgrp
                if qgrp:
                    quant["blocks"][gname] = qgrp
        elif top == "embed":
            tab = sub["table"]
            quant["embed"] = {"table": QuantizedTensor(
                fill_i8(tab.shape),
                jax.device_put(np.full((tab.shape[0], 1), 0.004,
                                       np.float32)),
                None, 8, tuple(tab.shape), jnp.bfloat16)}
            dense["embed"] = {}
        else:
            dense[top] = build(sub)
    return dense, quant


def llama8b_serving_bench(on_tpu: bool):
    """ZeRO-Inference serving of Llama-3-8B int8 on ONE chip — the
    llama-class serving leg the reference headlines (FastGen README:133
    SLA-style numbers: prompt tok/s + per-token generation latency EMA).

    The dense model (16 GB bf16) cannot materialize anywhere on this
    rig's budget: the engine is built PRE-QUANTIZED
    (``InferenceEngine(..., quant_tree=...)`` — the quantized-checkpoint
    flow) so only int8 payloads ever exist, and the quant tree rides the
    step as jit ARGUMENTS (a closure capture baked 7.5 GB of constants
    into the HLO and killed the remote compile — measured 2026-07-30)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                         SamplingParams)
    from deepspeed_tpu.models.presets import PRESETS
    from deepspeed_tpu.models.transformer import Model, TransformerConfig

    n_seqs, prompt_len = (8, 512) if on_tpu else (2, 8)
    decode_rounds = 4 if on_tpu else 2

    preset = dict(PRESETS["llama3-8b" if on_tpu else "llama-tiny"])
    preset["max_seq_len"] = 2048
    if not on_tpu:
        preset.update(vocab_size=512, num_layers=2, d_model=128,
                      num_heads=4, num_kv_heads=2, d_ff=352)
    cfg = TransformerConfig(**preset)
    dense, quant = _synthetic_int8_llama(cfg)
    model = Model.from_params(cfg, dense)
    # budget 1024 = two 512-token prompts per step: each full-model
    # weight pass amortizes over 2x the prompt tokens (prompt 1761 ->
    # 2189 tok/s measured; budget 2048 OOMs the 8B compile)
    # int8 paged KV (per-vector scales): halves the KV HBM stream that
    # competes with the int8 weights for decode bandwidth at long context
    eng = InferenceEngine(model, InferenceConfig(
        token_budget=1024 if on_tpu else 16, max_seqs=n_seqs,
        kv_block_size=64 if on_tpu else 16,
        num_kv_blocks=128 if on_tpu else 32,
        kv_quant="int8",
        decode_burst=8 if on_tpu else 2,
        device_telemetry="on"), quant_tree=quant)

    r = np.random.RandomState(0)
    vocab = model.config.vocab_size
    sp = SamplingParams(temperature=0.0, max_new_tokens=1 << 30)

    # warm compile caches (prompt-sized bucket) outside the timed region
    eng.put(-1, list(r.randint(0, vocab, prompt_len)))
    while eng.step(sampling=sp).get(-1) is None:
        pass
    eng.flush(-1)
    eng.reset_metrics()     # warmup compile must not contaminate the
    #                         reported request-lifecycle aggregate

    # --- prefill: prompt throughput + TTFT
    for uid in range(n_seqs):
        eng.put(uid, list(r.randint(0, vocab, prompt_len)))
    t0 = time.perf_counter()
    ttft = {}
    while len(ttft) < n_seqs:
        out = eng.step(sampling=sp)
        now = time.perf_counter() - t0
        for uid in out:
            ttft.setdefault(uid, now * 1e3)
    prefill_dt = time.perf_counter() - t0
    prompt_tok_s = n_seqs * prompt_len / prefill_dt
    ttft_p50 = float(np.median(list(ttft.values())))

    # --- decode: device-side bursts; per-token latency EMA (FastGen's
    # generation SLA is an exponential moving average per token)
    for uid in range(n_seqs):
        eng.put(uid, [1])
    out = eng.decode_burst(sampling=sp)          # compile + settle
    for uid in out:
        eng.put(uid, [out[uid][-1]])
    # second settle: the first burst pushes context past a power-of-two
    # bucket boundary, recompiling the NEXT burst — that compile must not
    # land inside the timed region (it seeded a 245 ms/token EMA once)
    out = eng.decode_burst(sampling=sp)
    produced = 0
    ema = None
    t0 = time.perf_counter()
    t_last = t0
    for _ in range(decode_rounds):
        for uid in out:
            eng.put(uid, [out[uid][-1]])
        out = eng.decode_burst(sampling=sp)
        now = time.perf_counter()
        toks = sum(len(v) for v in out.values())
        per_tok_ms = (now - t_last) / max(toks // n_seqs, 1) * 1e3
        ema = per_tok_ms if ema is None else 0.9 * ema + 0.1 * per_tok_ms
        t_last = now
        produced += toks
    decode_tok_s = produced / (t_last - t0)
    name = "llama8b_int8" if on_tpu else "llama_tiny_int8"
    for uid in list(out):
        eng.flush(uid)
    sla = sla_goodput_sweep(eng, on_tpu, prompt_len)
    return {
        f"{name}_prompt_tok_s": round(prompt_tok_s, 1),
        f"{name}_ttft_p50_ms": round(ttft_p50, 1),
        f"{name}_decode_tok_s": round(decode_tok_s, 1),
        f"{name}_decode_ms_per_tok_ema": round(ema, 2),
        f"{name}_request_metrics": eng.request_metrics()["aggregate"],
        # the 8B leg is where utilization matters most: the burst
        # program's cost_analysis prices the int8 weight stream the
        # decode floor argument is built on (tools/profile_decode8b.py
        # reads the same numbers)
        f"{name}_device_metrics": eng.device_snapshot(),
        **{f"{name}_{k}": v for k, v in sla.items()},
    }


def sla_goodput_sweep(eng, on_tpu: bool, prompt_len: int):
    """FastGen-style SLA goodput curve (reference:
    blogs/deepspeed-fastgen/README.md:133-139 — 'effective throughput':
    QPS of requests meeting BOTH the prompt SLA (>=512 tok/s/seq, i.e.
    TTFT <= prompt_len/512 s) and a generation SLA tier (per-token EMA
    latency <= 1/2, 1/4, 1/6 s for the 2/4/6 tok/s tiers).

    Poisson arrivals at each swept rate drive the SplitFuse engine's
    continuous batching; per-request TTFT and inter-token gaps are
    measured at the step boundary (the scheduler's own granularity).
    Reports, per tier, the best observed goodput (met-SLA requests/sec)
    across the sweep."""
    import time

    import numpy as np

    from deepspeed_tpu.inference import SamplingParams

    gen_tokens = 32 if on_tpu else 4
    n_req = 16 if on_tpu else 4
    rates = (0.5, 1.0, 2.0, 4.0) if on_tpu else (8.0,)
    tiers = {"sla2": 0.5, "sla4": 0.25, "sla6": 1.0 / 6.0}
    ttft_limit = prompt_len / 512.0
    sp = SamplingParams(temperature=0.0, max_new_tokens=1 << 30)
    r = np.random.RandomState(7)
    vocab = eng.cfg.vocab_size
    best = {k: 0.0 for k in tiers}
    curve = {}
    for rate in rates:
        arrivals = np.cumsum(r.exponential(1.0 / rate, n_req))
        reqs = {}          # uid -> dict(t_arrive, t_first, gaps, n)
        next_uid = 1000
        done = []
        t0 = time.perf_counter()
        def finish_tokens(uid, q, toks, t_step, n_new):
                if q["t_first"] is None:
                    q["t_first"] = t_step
                    n_new -= 1
                if n_new > 0:
                    gap = (t_step - q["t_last"]) / n_new
                    q["gaps"] += [gap] * n_new
                q["t_last"] = t_step
                q["n"] += len(toks) if isinstance(toks, list) else 1
                if q["n"] >= gen_tokens:
                    eng.flush(uid)
                    done.append((uid, q))
                    del reqs[uid]
                else:
                    last = toks[-1] if isinstance(toks, list) else toks
                    eng.put(uid, [int(last)])

        while len(done) < n_req:
            now = time.perf_counter() - t0
            while next_uid - 1000 < n_req and \
                    arrivals[next_uid - 1000] <= now:
                uid = next_uid
                eng.put(uid, list(r.randint(0, vocab, prompt_len)))
                reqs[uid] = {"t_arrive": arrivals[uid - 1000],
                             "t_first": None, "gaps": [], "n": 0,
                             "t_last": None}
                next_uid += 1
            if not reqs:
                if next_uid - 1000 >= n_req:
                    break               # everything arrived and finished
                time.sleep(min(0.01, max(0.0,
                               arrivals[next_uid - 1000] - now)))
                continue

            in_prefill = any(q["t_first"] is None for q in reqs.values())
            if not in_prefill and eng.icfg.decode_burst > 1:
                # decode-only phase: device-side bursts (the engine's
                # steady-state decode path; new arrivals re-enter the
                # SplitFuse step on the next loop iteration)
                out = eng.decode_burst(sampling=sp)
                t_step = time.perf_counter() - t0
                for uid, toks in out.items():
                    q = reqs.get(uid)
                    if q is not None:
                        finish_tokens(uid, q, list(toks), t_step,
                                      len(toks))
            else:
                out = eng.step(sampling=sp)
                t_step = time.perf_counter() - t0
                for uid, tok in out.items():
                    q = reqs.get(uid)
                    if q is not None:
                        finish_tokens(uid, q, int(tok), t_step, 1)
        elapsed = time.perf_counter() - t0
        for tier, limit in tiers.items():
            met = 0
            for uid, q in done:
                ttft = q["t_first"] - q["t_arrive"]
                ema = None
                for g in q["gaps"]:
                    ema = g if ema is None else 0.9 * ema + 0.1 * g
                if ttft <= ttft_limit and (ema or 0.0) <= limit:
                    met += 1
            goodput = met / elapsed
            best[tier] = max(best[tier], goodput)
            curve[f"r{rate}_{tier}"] = round(goodput, 3)
    return {**{f"goodput_qps_{k}": round(v, 3) for k, v in best.items()},
            "goodput_curve": curve}


def pipeline_serving_bench(on_tpu: bool, trace_path=None,
                           profile_dir=None):
    """Pipelined vs strict-sync serving loop at identical shapes: decode
    tokens/s for pipeline_depth 1 vs 2 plus the engine's per-step
    host-overhead breakdown (schedule / stage / device / readback ms)
    and the request-lifecycle aggregate (TTFT/TPOT histograms) of the
    timed run.  With ``trace_path``, the depth-2 leg runs with span
    tracing on and exports a Chrome trace of the timed region (open in
    Perfetto: one track per pipeline stage, the dispatch-ahead overlap
    visible directly).  With ``profile_dir`` (``--profile out/``), the
    depth-2 timed leg additionally arms a deep-capture window
    (telemetry/profiler.py) and emits a MERGED host+device timeline
    via tools/tracemerge.py — host stages and device/XLA activity on
    one Perfetto timeline, the ROADMAP-3 "track it before you can
    trigger it" bar.
    The pipeline's win is the host work it moves off the critical path:
    schedule+stage of step N+1 and the token readback of step N overlap
    step N/N+1's device compute, so the per-token host overhead
    (schedule+stage+readback) drops vs the synchronous baseline while
    outputs stay token-for-token identical."""
    import numpy as np

    from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                         SamplingParams)
    from deepspeed_tpu.models import build_model

    n_seqs, prompt_len = (16, 64) if on_tpu else (8, 8)
    gen_tokens = 64 if on_tpu else 24
    model = build_model(
        "gpt2",
        **(dict(max_seq_len=1024) if on_tpu else
           dict(num_layers=2, d_model=128, num_heads=4, vocab_size=1024,
                max_seq_len=64)))
    r = np.random.RandomState(0)
    vocab = model.config.vocab_size
    prompts = {uid: list(r.randint(0, vocab, prompt_len))
               for uid in range(n_seqs)}
    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_tokens)

    out = {}
    breakdown = {}
    for depth in (1, 2):
        eng = InferenceEngine(model, InferenceConfig(
            token_budget=1024 if on_tpu else 64, max_seqs=n_seqs,
            kv_block_size=64 if on_tpu else 16,
            num_kv_blocks=1024 if on_tpu else 64,
            pipeline_depth=depth,
            trace=bool(trace_path) and depth == 2,
            device_telemetry="on", anomaly="on"))
        # warm the compile caches (probe + both context buckets) outside
        # the timed region
        eng.generate({u: list(p) for u, p in prompts.items()}, sp)
        # full telemetry reset: timings counters, request records, AND
        # the span ring, so every exported number covers the timed
        # region only
        eng.reset_metrics()
        if profile_dir and depth == 2:
            # deep capture over the head of the timed region: a
            # bounded jax.profiler window whose merged host+device
            # timeline shows the dispatch-ahead overlap for real
            eng.capture(steps=8, reason="bench_pipe2",
                        out_dir=profile_dir)
        t0 = time.perf_counter()
        toks = eng.generate({u: list(p) for u, p in prompts.items()}, sp)
        dt = time.perf_counter() - t0
        produced = sum(len(v) for v in toks.values())
        tl = eng.timings
        steps = max(tl["steps"], 1)
        out[f"pipe{depth}_decode_tok_s"] = round(produced / dt, 1)
        out[f"pipe{depth}_request_metrics"] = \
            eng.request_metrics()["aggregate"]
        out[f"pipe{depth}_device_metrics"] = eng.device_snapshot()
        out[f"pipe{depth}_anomalies"] = eng.anomaly_summary()
        if trace_path and depth == 2:
            out["trace_file"] = eng.tracer.export_chrome_trace(trace_path)
        if profile_dir and depth == 2 and eng.capture_dirs:
            from tools.tracemerge import merge_capture
            out["merged_trace_file"] = merge_capture(eng.capture_dirs[-1])
        breakdown[f"pipe{depth}"] = {
            "schedule_ms": round(tl["schedule_ms"] / steps, 3),
            "stage_ms": round(tl["stage_ms"] / steps, 3),
            "device_ms": round(tl["device_ms"] / steps, 3),
            "wait_ms": round(tl["wait_ms"] / steps, 3),
            "readback_ms": round(tl["readback_ms"] / steps, 3),
            "wall_ms_per_step": round(dt * 1e3 / steps, 3),
            "steps": tl["steps"],
        }
    # host overhead left ON THE CRITICAL PATH per step: wall minus the
    # device-busy time.  Device busy is taken from the strict-sync run
    # (same model/shapes, measured serially: its jit call + result wait
    # IS the device step, unperturbed by overlap) so both depths are
    # charged the same device cost and the difference is purely the
    # schedule/stage/readback work the pipeline hides behind compute.
    dev_busy = (breakdown["pipe1"]["device_ms"]
                + breakdown["pipe1"]["wait_ms"])
    for d in (1, 2):
        b = breakdown[f"pipe{d}"]
        b["host_crit_ms_per_step"] = round(
            max(0.0, b["wall_ms_per_step"] - dev_busy), 3)
    h1 = breakdown["pipe1"]["host_crit_ms_per_step"]
    h2 = breakdown["pipe2"]["host_crit_ms_per_step"]
    out["pipeline_host_overhead_ratio"] = round(h2 / h1, 3) if h1 else 0.0
    out["pipeline_step_breakdown_ms"] = breakdown
    return out


def shared_prefix_serving_bench(on_tpu: bool):
    """Prefix-cache serving leg: N requests sharing a 64-token system
    prompt (the few-shot/system-prompt traffic shape prefix caching
    targets), arriving one after another — each admitted after the
    previous request produced its first token, so later requests can
    alias the registered prompt blocks.  The token budget is set BELOW
    the prompt length: with SplitFuse's fixed-shape steps the cache's
    win is fewer prefill steps (a cache-hit request starts prefill at
    the first uncached token), which is both prefill-token throughput
    and TTFT.  Reports tok/s for prefix_cache on vs off at identical
    shapes, the speedup, and the engine's hit-rate counters."""
    import numpy as np

    from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                         SamplingParams)
    from deepspeed_tpu.models import build_model

    n_req = 8
    shared_len = 64
    tail_len = 64 if on_tpu else 32
    budget = 64 if on_tpu else 32
    model = build_model(
        "gpt2",
        **(dict(max_seq_len=1024) if on_tpu else
           dict(num_layers=2, d_model=128, num_heads=4, vocab_size=1024,
                max_seq_len=256)))
    r = np.random.RandomState(0)
    vocab = model.config.vocab_size
    shared = list(r.randint(0, vocab, shared_len))
    prompts = {uid: shared + list(r.randint(0, vocab, tail_len))
               for uid in range(n_req)}
    sp = SamplingParams(temperature=0.0, max_new_tokens=1)
    out = {}
    for mode in ("off", "on"):
        # device telemetry on BOTH arms: the speedup must compare
        # engines differing in ONE knob (any probe cost lands
        # symmetrically, outside the timed region anyway)
        eng = InferenceEngine(model, InferenceConfig(
            token_budget=budget, max_seqs=4,
            kv_block_size=64 if on_tpu else 16,
            num_kv_blocks=64 if on_tpu else 48,
            prefix_cache=mode,
            device_telemetry="on", anomaly="on"))
        # warm the compile caches with an unrelated prompt (both modes
        # pay it; its blocks never match the shared prefix)
        eng.generate({-1: list(r.randint(0, vocab,
                                         shared_len + tail_len))}, sp)
        eng.reset_metrics()
        t0 = time.perf_counter()
        for uid, p in prompts.items():
            eng.generate({uid: list(p)}, sp)
        dt = time.perf_counter() - t0
        total_prompt = n_req * (shared_len + tail_len)
        out[f"shared_prefix_prefill_tok_s_{mode}"] = \
            round(total_prompt / dt, 1)
        if mode == "on":
            tm = eng.timings
            out["shared_prefix_cached_tokens"] = tm["cached_tokens"]
            out["shared_prefix_hit_rate"] = round(
                tm["cached_tokens"] / max(tm["prompt_tokens"], 1), 3)
            out["shared_prefix_request_metrics"] = \
                eng.request_metrics()["aggregate"]
            out["shared_prefix_device_metrics"] = eng.device_snapshot()
            out["shared_prefix_anomalies"] = eng.anomaly_summary()
    out["shared_prefix_speedup"] = round(
        out["shared_prefix_prefill_tok_s_on"]
        / max(out["shared_prefix_prefill_tok_s_off"], 1e-9), 2)
    return out


def spec_decode_serving_bench(on_tpu: bool):
    """Model-free speculative decoding leg (docs/SERVING.md
    "Speculative decoding"): decode throughput with ``spec_decode`` on
    vs off at identical shapes on the repetitive/code-like traffic
    prompt-lookup targets — each prompt is a short token motif repeated
    (the shape of templated code, quoted RAG context, or structured
    logs), and the decoded stream itself falls into cycles the n-gram
    proposer locks onto.  Outputs are token-identical by construction
    (the verify step is exact); the win is steps: an accepted window
    emits up to 1 + spec_max_draft tokens per dispatch.  Both modes run
    the strict-sync driver (pipeline_depth=1): a verify window's next
    fed token depends on host-side acceptance, so drafting rows cannot
    ride the depth-2 feedback marker anyway — speculation's natural
    home is the sync loop, where every saved step is pure wall-clock
    (measured here: depth-1 spec beats depth-2 spec, which trades each
    window for a pipeline bubble).  Reports decode tok/s both ways, the
    speedup, the acceptance_rate, and the mean accepted draft length —
    the measured signals ROADMAP item 4's autotuner needs to drive
    ``spec_decode="auto"`` from data."""
    import numpy as np

    from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                         SamplingParams)
    from deepspeed_tpu.models import build_model

    n_seqs = 8 if on_tpu else 4
    prompt_len = 64 if on_tpu else 24
    gen_tokens = 96
    model = build_model(
        "gpt2",
        **(dict(max_seq_len=1024) if on_tpu else
           dict(num_layers=2, d_model=128, num_heads=4, vocab_size=1024,
                max_seq_len=256)))
    r = np.random.RandomState(0)
    vocab = model.config.vocab_size
    prompts = {}
    for uid in range(n_seqs):
        motif = list(r.randint(0, vocab, 4 + uid % 3))
        reps = -(-prompt_len // len(motif))
        prompts[uid] = (motif * reps)[:prompt_len]
    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_tokens)
    out = {}
    for mode in ("off", "on"):
        # device telemetry on BOTH arms — the on/off speedup must
        # isolate spec_decode, not spec_decode + telemetry
        eng = InferenceEngine(model, InferenceConfig(
            token_budget=256 if on_tpu else 64, max_seqs=n_seqs,
            kv_block_size=64 if on_tpu else 16,
            num_kv_blocks=256 if on_tpu else 96,
            pipeline_depth=1,
            spec_decode=mode, spec_max_draft=4,
            device_telemetry="on", anomaly="on"))
        # warm the compile caches; generate() flushes everything, so the
        # proposer history starts cold again for the timed run
        eng.generate({u: list(p) for u, p in prompts.items()}, sp)
        eng.reset_metrics()
        t0 = time.perf_counter()
        toks = eng.generate({u: list(p) for u, p in prompts.items()}, sp)
        dt = time.perf_counter() - t0
        produced = sum(len(v) for v in toks.values())
        out[f"spec_decode_tok_s_{mode}"] = round(produced / dt, 1)
        out[f"spec_decode_steps_{mode}"] = eng.timings["steps"]
        if mode == "on":
            tm = eng.timings
            out["spec_acceptance_rate"] = round(
                tm["spec_accepted_tokens"]
                / max(tm["spec_drafted_tokens"], 1), 3)
            out["spec_mean_accepted_draft_len"] = round(
                tm["spec_accepted_tokens"] / max(tm["spec_windows"], 1),
                3)
            out["spec_request_metrics"] = \
                eng.request_metrics()["aggregate"]
            out["spec_device_metrics"] = eng.device_snapshot()
            out["spec_anomalies"] = eng.anomaly_summary()
    out["spec_decode_speedup"] = round(
        out["spec_decode_tok_s_on"]
        / max(out["spec_decode_tok_s_off"], 1e-9), 2)
    return out


def overload_serving_bench(on_tpu: bool):
    """Overload-policy leg (docs/SERVING.md "Surviving overload"): the
    loadgen harness replays a seeded bursty trace at offered rates
    below and beyond capacity — with faults injected — and the SLO
    summaries (terminal-status mix, preemptions, TTFT/TPOT percentiles,
    deterministic step-indexed queue delays) land in the BENCH JSON as
    TTFT/TPOT-vs-load curves.  Every leg re-asserts token parity and
    the allocator partition; the replay raises rather than hangs if the
    engine wedges, so a scheduling regression fails the bench loudly."""
    from tools.loadgen import run_sweep

    qps = (2.0, 8.0, 32.0)
    sweep = run_sweep(qps, n_requests=24 if on_tpu else 16,
                      arrival="bursty", seed=0,
                      shed_policy="evict-lowest")
    curve = {str(q): {k: leg[k] for k in
                      ("statuses", "preemptions", "steps",
                       "ttft_ms_p50", "ttft_ms_p95",
                       "tpot_ms_p50", "tpot_ms_p95",
                       "ttft_steps_p95", "ttft_steps_hi_p95")}
             for q, leg in ((q, sweep["legs"][str(q)]) for q in qps)}
    return {"overload_slo_curve": curve,
            "overload_qps_axis": list(qps)}


def serving_bench(on_tpu: bool):
    """FastGen-style serving numbers (BASELINE.json metric: p50 TTFT +
    decode tok/s): 16 concurrent prompts of 128 tokens through the
    SplitFuse engine (token budget 256), then steady-state decode."""
    import numpy as np

    from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                         SamplingParams)
    from deepspeed_tpu.models import build_model

    n_seqs, prompt_len = (32, 128) if on_tpu else (2, 8)
    model = build_model(
        "gpt2",
        **(dict(max_seq_len=1024) if on_tpu else
           dict(num_layers=2, d_model=128, num_heads=4, vocab_size=1024,
                max_seq_len=64)))
    # large prefill budget: on high-RTT links TTFT is dispatch-bound, so
    # fewer, bigger SplitFuse chunks win (599 vs 1678 ms p50 measured at
    # 1024 vs 256); decode latency is governed by the bursts, not the
    # prefill budget
    eng = InferenceEngine(model, InferenceConfig(
        token_budget=1024 if on_tpu else 16, max_seqs=n_seqs,
        kv_block_size=64 if on_tpu else 16,
        num_kv_blocks=1024 if on_tpu else 32,
        decode_burst=8 if on_tpu else 2,
        device_telemetry="on", anomaly="on", slo="on"))
    r = np.random.RandomState(0)
    sp = SamplingParams(temperature=0.0, max_new_tokens=1 << 30)
    vocab = model.config.vocab_size

    # warm the compile caches (probe + the prompt-sized context bucket)
    # outside the timed region
    eng.put(-1, list(r.randint(0, vocab, prompt_len)))
    while eng.step(sampling=sp).get(-1) is None:
        pass
    eng.flush(-1)
    eng.reset_metrics()     # the warmup request's compile-dominated TTFT
    #                         must not contaminate the reported aggregate

    # --- TTFT: enqueue all prompts, time each seq's first sampled token
    # (alternating SLO classes so the embedded scorecard is per-class)
    for uid in range(n_seqs):
        eng.put(uid, list(r.randint(0, vocab, prompt_len)),
                slo_class="interactive" if uid % 2 == 0 else "batch")
    t0 = time.perf_counter()
    ttft = {}
    while len(ttft) < n_seqs:
        out = eng.step(sampling=sp)
        now = time.perf_counter() - t0
        for uid in out:
            ttft.setdefault(uid, now * 1e3)
    ttft_p50_ms = float(np.median(list(ttft.values())))

    # --- steady-state decode throughput: all seqs live, device-side
    # decode bursts (K forwards per dispatch — the sampled token feeds
    # the next forward on-device)
    rounds = 6 if on_tpu else 2
    for uid in range(n_seqs):           # feed the sampled token back
        eng.put(uid, [1])
    out = eng.decode_burst(sampling=sp)          # compile + settle
    produced = 0
    t0 = time.perf_counter()
    for _ in range(rounds):
        for uid in out:
            eng.put(uid, [out[uid][-1]])
        out = eng.decode_burst(sampling=sp)
        produced += sum(len(v) for v in out.values())
    dt = time.perf_counter() - t0
    # flush everything so per-request TPOT (observed at finish) lands in
    # the histograms, then report the leg's lifecycle aggregate
    for uid in range(n_seqs):
        eng.flush(uid)
    req = eng.request_metrics()["aggregate"]
    return {"serving_ttft_p50_ms": round(ttft_p50_ms, 1),
            "serving_decode_tok_s": round(produced / dt, 1),
            "serving_request_metrics": req,
            # device-telemetry capture (docs/OBSERVABILITY.md "Device &
            # compiler telemetry"): per-program cost_analysis, derived
            # MFU / HBM-bandwidth utilization over the timed window,
            # and peak memory_stats — BENCH_r06+ records utilization,
            # not just tok/s (absent fields = backend can't say)
            "serving_device_metrics": eng.device_snapshot(),
            # streaming-detector tally of the leg (anomaly counts are
            # report-only in benchdiff — a noisy rig fires latency
            # detectors without being a regression)
            "serving_anomalies": eng.anomaly_summary(),
            # per-class SLO scorecard (docs/OBSERVABILITY.md "SLOs &
            # error budgets"); benchdiff reports attainment/budget
            # deltas report-only, same policy as the anomaly counts
            "serving_slo": eng.slo_scorecard()}


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", metavar="OUT.json", default=None,
                    help="export a Chrome trace (Perfetto-loadable) of "
                    "the pipelined serving leg's depth-2 timed run")
    ap.add_argument("--profile", metavar="OUT_DIR", default=None,
                    help="arm a deep-capture window on the depth-2 "
                    "timed leg and emit a merged host+device Perfetto "
                    "timeline (tools/tracemerge.py) under OUT_DIR")
    args = ap.parse_args()
    main(trace_path=args.trace, profile_dir=args.profile)
