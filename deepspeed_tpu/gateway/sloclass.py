"""SLO classes: the wire's name for a (priority, deadline) pair.

The overload policy (inference/overload.py) has spoken ``priority`` /
``deadline_ms`` since PR 6, but nothing on the outside ever produced
them — callers passed raw integers.  The gateway closes that loop: a
client names a *class* (``x-slo-class: interactive``) and the class map
supplies the admission defaults, so the wire contract is "what kind of
request is this", not "which scheduler knob do I turn".  Explicit
``priority`` / ``deadline_ms`` fields in the request body still win —
the class only fills what the client left unsaid
(docs/SERVING.md "Network gateway").
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

# the request header naming the class (case-insensitive, like all
# HTTP header names; values are matched case-sensitively — classes
# are identifiers, not prose)
SLO_CLASS_HEADER = "x-slo-class"


@dataclasses.dataclass(frozen=True)
class SloClass:
    """One wire-visible service class -> admission defaults.

    ``priority``: nice-level semantics (lower = more important),
    handed to ``engine.put(priority=)`` verbatim.  ``deadline_ms``:
    relative deadline from arrival (None = no deadline) — the producer
    ``OverloadConfig`` always wanted and never had."""
    name: str
    priority: int
    deadline_ms: Optional[float]


def default_slo_classes() -> Dict[str, SloClass]:
    """The stock three-tier map (override via
    ``GatewayConfig.slo_classes``): ``interactive`` — human-waiting
    traffic, top tier, tight deadline so an overloaded engine sheds it
    honestly instead of serving it late; ``standard`` — the default
    tier; ``batch`` — background tier, no deadline, first to be
    preempted/degraded under pressure."""
    return {
        "interactive": SloClass("interactive", priority=0,
                                deadline_ms=30_000.0),
        "standard": SloClass("standard", priority=1, deadline_ms=None),
        "batch": SloClass("batch", priority=2, deadline_ms=None),
    }


def resolve_slo(header_value: Optional[str],
                classes: Dict[str, SloClass],
                default_class: str,
                priority: Optional[int],
                deadline_ms: Optional[float],
                ) -> Tuple[int, Optional[float], str]:
    """Fold the ``x-slo-class`` header and the body's explicit fields
    into the ``(priority, deadline_ms)`` pair ``engine.put`` takes.

    Resolution order: explicit body field > class default.  An unknown
    class name is a client error (the caller maps the raised
    ``KeyError`` to HTTP 400) — silently serving an unknown class at
    some default tier would hide client-side typos forever.  Returns
    ``(priority, deadline_ms, class_name)``."""
    name = header_value if header_value is not None else default_class
    if name not in classes:
        raise KeyError(name)
    cls = classes[name]
    return (cls.priority if priority is None else int(priority),
            cls.deadline_ms if deadline_ms is None else float(deadline_ms),
            name)
