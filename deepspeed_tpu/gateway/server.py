"""The network gateway: streaming tokens to real sockets.

A stdlib-asyncio HTTP/1.1 front-end (no new dependencies) over either
a single :class:`~deepspeed_tpu.inference.InferenceEngine` or a
:class:`~deepspeed_tpu.serving.FleetRouter` — both already speak the
same engine-shaped seam (``put``/``step``/``flush``/``cancel``/
``query``), so the gateway fronts either without knowing which
(docs/SERVING.md "Network gateway").

Wire surface:

* ``POST /v1/completions`` — OpenAI-style body (token-id prompts; the
  stack is tokenizer-free), ``stream: true`` for SSE token streaming.
* ``GET /healthz`` — the PR-8 health ladder as status codes.
* ``GET /metrics`` — the Prometheus exposition that already exists
  (engine registry, or the fleet's one merged exposition).
* ``SIGTERM`` — graceful drain: in-flight streams finish, new
  arrivals get 503 + Retry-After, the backend's ``drain()`` settles
  leftovers, the process exits clean.

Concurrency contract: the engine is synchronous and NOT thread-safe,
so every backend call — steps, puts, cancels, health probes, metric
scrapes — runs on ONE single-worker executor thread via
:meth:`Gateway._call`; the event loop never blocks on the engine and
the engine never sees two concurrent callers.  The ``async-blocking``
lint rule (docs/TPULINT.md) holds this file to that discipline.

Backpressure is a translation, not new policy: a non-admitted
:class:`AdmissionVerdict` becomes 429/503 with a computed Retry-After
(protocol.shed_decision), and a slow SSE *reader* stalls its own
stream — the driver stops feeding that uid's continuation token back
to the engine until the client drains its bounded queue, so one slow
client costs itself, never the batch.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import json
import signal
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from functools import partial
from typing import Callable, Dict, List, Optional, Tuple

from ..inference import EngineDeadError, SamplingParams
from ..utils.logging import logger
from . import protocol
from .sloclass import (SLO_CLASS_HEADER, SloClass, default_slo_classes,
                       resolve_slo)


class GatewayError(RuntimeError):
    """Gateway-level refusal (e.g. starting on a dead engine)."""


@dataclasses.dataclass
class GatewayConfig:
    host: str = "127.0.0.1"
    port: int = 0                    # 0 = ephemeral (read Gateway.port)
    model_name: str = "deepspeed-tpu"

    # completions defaults/caps
    max_tokens_default: int = 16
    max_tokens_cap: int = 512

    # per-stream backpressure: the driver stops feeding a stream's
    # continuation token back to the engine while more than this many
    # tokens sit undelivered to the client (docs/SERVING.md table)
    stream_queue: int = 8

    # SLO-class header map (sloclass.py); the default class applies
    # when the header is absent
    slo_classes: Optional[Dict[str, SloClass]] = None
    default_slo_class: str = "standard"

    # Retry-After math (protocol.retry_after_s)
    est_ms_per_request: float = 250.0
    max_retry_after_s: int = 30
    drain_retry_after_s: int = 5

    # SIGTERM drain budget: in-flight streams get this long to finish
    # before the backend drain sheds the remainder
    drain_deadline_ms: float = 30_000.0

    # sampling is per-SERVER: one compiled step serves the whole
    # ragged batch, so temperature/top_k/stop are engine-level knobs;
    # per-request knobs are max_tokens / priority / deadline_ms
    sampling: Optional[SamplingParams] = None
    seed: Optional[int] = None       # base key for temperature > 0

    # driver pacing + wire timeouts
    idle_s: float = 0.002
    head_timeout_s: float = 10.0

    install_signals: bool = True     # SIGTERM -> drain (main thread only)
    check_invariants: bool = False   # allocator/record checks per pump
    journey_retention: int = 256     # wire journeys kept (ring)

    # ops plane (docs/OBSERVABILITY.md "SLOs & error budgets"): the
    # ``GET /debug/*`` surface — "auto"|"on"|"off", auto resolves OFF
    # (exposing internals on the wire is an operator opt-in, never
    # ambient).  ops_token guards the MUTATING endpoints (``POST
    # /debug/dump`` / ``/debug/capture``): with no token configured
    # they refuse (403) even when the read surface is on.
    ops: str = "auto"
    ops_token: Optional[str] = None


class _Finish:
    """Queue sentinel: the stream ended with ``reason``."""

    __slots__ = ("reason",)

    def __init__(self, reason: str):
        self.reason = reason


@dataclasses.dataclass
class _Stream:
    """Server-side state of one wire request (streaming or not)."""
    uid: int
    rid: str
    max_tokens: int
    want_stream: bool
    queue: asyncio.Queue
    tokens: List[int] = dataclasses.field(default_factory=list)
    emitted: int = 0
    stalled: Optional[int] = None    # token held back by backpressure
    finished: bool = False
    finish_reason: Optional[str] = None
    disconnected: bool = False


def _query_params(query: str) -> Dict[str, Optional[str]]:
    """Minimal ``k=v&flag`` query parsing for the ops routes (no
    percent-decoding — ops values are ints and bare flags)."""
    params: Dict[str, Optional[str]] = {}
    for part in query.split("&"):
        if not part:
            continue
        k, sep, v = part.partition("=")
        params[k] = v if sep else None
    return params


def _jsonable(obj):
    """Config objects -> JSON-safe trees for ``GET /debug/config``:
    dataclasses expand field-by-field, anything non-primitive falls
    back to ``repr`` (a resolved config must always serialize — an
    exotic field value can't take the route down)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    return repr(obj)


# engine-side terminal statuses -> the finish_reason the wire reports
_STATUS_REASON = {"finished": "stop", "cancelled": "cancelled",
                  "deadline_exceeded": "deadline_exceeded",
                  "shed": "shed", "failed": "failed",
                  "context_exhausted": "length", "released": "released",
                  "migrated": "migrated", "handed_off": "handed_off"}


class Gateway:
    """One gateway over one backend (engine or fleet router).

    Use :func:`spawn_gateway` for the run-it-in-a-thread form tests
    and the load harness use; a real deployment runs
    :meth:`start` + :meth:`wait_stopped` on its own loop
    (``python -m deepspeed_tpu.gateway``)."""

    def __init__(self, backend, cfg: Optional[GatewayConfig] = None):
        self.cfg = cfg or GatewayConfig()
        self.backend = backend
        # duck-typed: the router is the thing that can grow replicas
        self._is_fleet = hasattr(backend, "add_replica")
        self._sampling = self.cfg.sampling or SamplingParams(
            max_new_tokens=1 << 30)
        self._rng = None
        if self.cfg.seed is not None:
            import jax  # deferred: greedy gateways never touch the key API
            self._rng = jax.random.PRNGKey(self.cfg.seed)
        self._slo = self.cfg.slo_classes or default_slo_classes()
        if self.cfg.default_slo_class not in self._slo:
            raise GatewayError(
                f"default_slo_class {self.cfg.default_slo_class!r} is not "
                f"in the class map {sorted(self._slo)}")
        if self.cfg.ops not in ("auto", "on", "off"):
            raise GatewayError(
                f"ops={self.cfg.ops!r}: expected 'auto', 'on', or 'off'")
        self._ops_on = self.cfg.ops == "on"

        # ONE engine thread: every backend touch is serialized here
        self._exec = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="gateway-engine")
        self._streams: Dict[int, _Stream] = {}  # tpulint: live-set
        self._uid_iter = itertools.count(1)
        self._journeys: Dict[int, List[Dict]] = {}
        # _journeys is written on the event loop but read from the
        # engine thread (_reaped_statuses) and from test/main threads
        # (wire_journey*): one lock covers every cross-domain touch
        self._jlock = threading.Lock()
        self._t0 = time.perf_counter()
        self._wake = asyncio.Event()
        self._stopped = asyncio.Event()
        self._draining = False
        self._dead = False
        self._stop_driver = False
        self._shutting = False
        self._server: Optional[asyncio.AbstractServer] = None
        self._driver_task: Optional[asyncio.Task] = None
        self.port: Optional[int] = None
        self.final_snapshot: Optional[Dict] = None
        self._setup_metrics()

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _setup_metrics(self) -> None:
        """Gateway-scope counters, registered into the BACKEND's
        registry so one scrape carries engine + wire truth
        (docs/OBSERVABILITY.md "Gateway counters")."""
        reg = self.backend.metrics
        self._c_conns = reg.counter(
            "serving_gateway_connections_total",
            "TCP connections accepted", int_valued=True)
        self._c_requests = reg.counter(
            "serving_gateway_requests_total",
            "HTTP requests by route", int_valued=True)
        self._c_streams = reg.counter(
            "serving_gateway_streams_total",
            "SSE streams opened", int_valued=True)
        self._c_sheds = reg.counter(
            "serving_gateway_sheds_total",
            "wire-level sheds by HTTP status code", int_valued=True)
        self._c_disc = reg.counter(
            "serving_gateway_disconnect_cancels_total",
            "client disconnects that cancelled an open request",
            int_valued=True)
        self._c_sse_bytes = reg.counter(
            "serving_gateway_sse_bytes_total",
            "SSE payload bytes written", int_valued=True)
        self._g_open = reg.gauge(
            "serving_gateway_open_streams",
            "wire requests currently open")

    def _journey(self, uid: int, phase: str, **info) -> None:
        stamp = {"phase": phase,
                 "t_ms": round((time.perf_counter() - self._t0) * 1e3, 3)}
        stamp.update(info)
        with self._jlock:
            j = self._journeys.get(uid)
            if j is None:
                while len(self._journeys) >= self.cfg.journey_retention:
                    self._journeys.pop(next(iter(self._journeys)))
                j = self._journeys[uid] = []
            j.append(stamp)

    def wire_journey(self, uid: int) -> Optional[List[Dict]]:
        """The wire-phase stamps of one request (received -> admitted/
        shed -> first_token -> closed, plus disconnects), the gateway's
        analogue of the router's request journeys."""
        with self._jlock:
            j = self._journeys.get(uid)
            return None if j is None else list(j)

    def wire_journeys(self) -> Dict[int, List[Dict]]:
        with self._jlock:
            return {u: list(j) for u, j in self._journeys.items()}

    # ------------------------------------------------------------------
    # the one seam onto the blocking backend
    # ------------------------------------------------------------------
    async def _call(self, fn, *args, **kwargs):
        """Run a blocking backend call on the single engine thread —
        the ONLY way gateway coroutines touch the engine."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec, partial(fn, *args, **kwargs))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    async def start(self) -> None:
        """Bind + start serving.  Refuses a DEAD backend loudly: a
        gateway that accepts connections only to shed 100% of them
        turns a visible outage into a silent one — restore/replace the
        engine (``load_snapshot``/``add_replica``) and start again."""
        state = await self._call(self._backend_state)
        if state == "dead":
            raise GatewayError(
                "refusing to start: backend engine is DEAD — the "
                "gateway would accept-then-shed every request; "
                "warm-restart the engine (snapshot/load_snapshot) or "
                "point the gateway at a live replica first")
        self._server = await asyncio.start_server(
            self._handle_conn, self.cfg.host, self.cfg.port,
            limit=protocol.MAX_BODY_BYTES + protocol.MAX_HEAD_BYTES)
        self.port = self._server.sockets[0].getsockname()[1]
        self._driver_task = asyncio.get_running_loop().create_task(
            self._drive())
        if self.cfg.install_signals:
            try:
                asyncio.get_running_loop().add_signal_handler(
                    signal.SIGTERM, self._on_sigterm)
            except (NotImplementedError, RuntimeError, ValueError) as e:
                # non-main-thread loops (spawn_gateway) cannot install
                # signal handlers; drains are triggered via shutdown()
                logger.debug("gateway: no SIGTERM handler (%s)", e)
        logger.info("gateway listening on %s:%d (backend=%s)",
                    self.cfg.host, self.port,
                    "fleet" if self._is_fleet else "engine")

    def _on_sigterm(self) -> None:
        logger.warning("gateway: SIGTERM — draining (deadline %.0f ms)",
                       self.cfg.drain_deadline_ms)
        asyncio.get_running_loop().create_task(self.shutdown())

    async def wait_stopped(self) -> None:
        await self._stopped.wait()

    async def shutdown(self, deadline_ms: Optional[float] = None) -> None:
        """Graceful drain (the SIGTERM path, also callable directly):
        stop admitting (new completions get 503 + Retry-After), keep
        the driver pumping until every in-flight stream finishes or
        the deadline elapses, then hand leftovers to the backend's own
        drain contract (``engine.drain`` sheds them and emits the
        final snapshot -> ``self.final_snapshot``), close the listener
        and the engine thread, and release :meth:`wait_stopped`."""
        if self._shutting:
            await self._stopped.wait()
            return
        self._shutting = True
        self._draining = True
        dl = self.cfg.drain_deadline_ms if deadline_ms is None \
            else float(deadline_ms)
        t0 = time.perf_counter()
        # phase 1: finish in-flight streams (the driver is still
        # pumping; continuations still land at the engine)
        while self._streams \
                and (time.perf_counter() - t0) * 1e3 < dl:
            await asyncio.sleep(0.005)
        # phase 2: stop the driver, settle leftovers via the backend
        self._stop_driver = True
        self._wake.set()
        if self._driver_task is not None:
            await self._driver_task
        leftovers = [s for s in self._streams.values() if not s.finished]
        rem = max(0.0, dl - (time.perf_counter() - t0) * 1e3)
        if not self._dead:
            try:
                if self._is_fleet:
                    # deliberately NOT router.drain(): that ends the
                    # FLEET's serving life (every replica drains and
                    # its breaker dies), but replicas outlive one
                    # gateway's shutdown; leftover wire requests are
                    # shed here and stay re-placeable on the fleet
                    for s in leftovers:
                        await self._call(self.backend.cancel, s.uid)
                else:
                    self.final_snapshot = await self._call(
                        self.backend.drain, rem, self._sampling,
                        self._rng)
            except EngineDeadError:
                logger.error("gateway: backend died during drain")
                self._dead = True
        for s in leftovers:
            self._close_stream(s, "shed")
            self._journey(s.uid, "drain_shed")
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        # give handlers a moment to flush their final frames
        t1 = time.perf_counter()
        while self._streams and time.perf_counter() - t1 < 2.0:
            await asyncio.sleep(0.005)
        self._exec.shutdown(wait=True)
        self._stopped.set()
        logger.info("gateway: drained and stopped "
                    "(%d streams shed at deadline)", len(leftovers))

    # ------------------------------------------------------------------
    # backend probes (run on the engine thread)
    # ------------------------------------------------------------------
    def _backend_state(self) -> str:
        if self._dead:
            return "dead"
        # both backend shapes expose the same cheap ladder read:
        # engine.health_state() / FleetRouter.health_state()
        return self.backend.health_state()

    def _health_probe(self) -> Tuple[str, Dict]:
        state = self._backend_state()
        payload = self.backend.health()
        return state, payload

    def _metrics_text(self) -> str:
        if self._is_fleet:
            return self.backend.fleet_registry.prometheus_text()
        return self.backend.metrics.prometheus_text()

    def _reaped_statuses(self) -> Dict[int, str]:
        be = self.backend
        reaped = be.drain_reaped() if self._is_fleet \
            else be._drain_reaped()
        # include journeyed uids whose stream is already torn down
        # (disconnect path): their journey still needs its terminal
        # "closed" stamp even though no queue is left to feed
        with self._jlock:
            journeyed = set(self._journeys)
        return {uid: be.query(uid).get("status", "released")
                for uid in reaped
                if uid in self._streams or uid in journeyed}

    def _pump(self) -> Tuple[Dict[int, int], Dict[int, str]]:
        outs = self.backend.step(rng=self._rng, sampling=self._sampling)
        reaped = self._reaped_statuses()
        self._g_open.set(len(self._streams))
        if self.cfg.check_invariants:
            self._assert_backend_invariants()
        return outs, reaped

    def _assert_backend_invariants(self) -> None:
        """The chaos bar, run after every pump when armed: allocator
        partition intact and no lifecycle record leaked, on every live
        engine behind this gateway."""
        engines = [rep.engine for rep in self.backend._reps.values()
                   if not rep.dead] if self._is_fleet else [self.backend]
        for eng in engines:
            eng.state.allocator.assert_invariants()
            for uid in eng.requests.open:
                assert uid in eng.state.seqs or eng._pending.get(uid) \
                    or uid in eng._meta, \
                    f"gateway: leaked open record for uid {uid}"

    def _apply(self, feedbacks: List[Tuple[int, int]],
               flushes: List[int]) -> None:
        for uid, tok in feedbacks:
            s = self._streams.get(uid)
            if s is None or s.finished or s.disconnected:
                # STALE feedback: the stream closed (or its client
                # vanished and a cancel() is queued behind us) between
                # token routing and this apply.  Feeding the token
                # would RE-ADMIT the terminally-closed uid as a fresh
                # one-token prompt — a resurrected request no driver
                # owns, generating forever.  Ordering matters: the
                # disconnect path sets ``s.disconnected`` before it
                # enqueues the cancel, so this check can never skip a
                # continuation the cancel wouldn't have killed anyway.
                continue
            self.backend.put(uid, [tok])
        for uid in flushes:
            self.backend.flush(uid)

    # ------------------------------------------------------------------
    # the driver: pumps the engine off the event loop
    # ------------------------------------------------------------------
    async def _drive(self) -> None:
        try:
            while not self._stop_driver:
                fb: List[Tuple[int, int]] = []
                fl: List[int] = []
                self._resume_stalled(fb, fl)
                if fb or fl:
                    await self._call(self._apply, fb, fl)
                if not any(not s.finished
                           for s in self._streams.values()):
                    try:
                        await asyncio.wait_for(self._wake.wait(),
                                               timeout=0.05)
                    except asyncio.TimeoutError:
                        pass
                    self._wake.clear()
                    continue
                try:
                    outs, reaped = await self._call(self._pump)
                except EngineDeadError:
                    self._mark_dead()
                    break
                fb, fl = [], []
                self._route_tokens(outs, reaped, fb, fl)
                if fb or fl:
                    await self._call(self._apply, fb, fl)
                if not outs:
                    # idle/backoff round: don't hot-spin the engine
                    await asyncio.sleep(self.cfg.idle_s)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("gateway: driver crashed — failing open "
                             "streams and going dead")
            self._mark_dead()

    def _resume_stalled(self, fb: List[Tuple[int, int]],
                        fl: List[int]) -> None:
        """Backpressure release: a stalled stream whose client drained
        below the queue bound gets its held token delivered and its
        continuation fed back to the engine."""
        for s in self._streams.values():
            if s.stalled is None or s.finished:
                continue
            if s.queue.qsize() < self.cfg.stream_queue:
                tok, s.stalled = s.stalled, None
                self._deliver(s, tok, fb, fl)

    def _route_tokens(self, outs: Dict[int, int],
                      reaped: Dict[int, str],
                      fb: List[Tuple[int, int]], fl: List[int]) -> None:
        for uid, tok in outs.items():
            s = self._streams.get(uid)
            if s is None or s.finished:
                continue
            if s.queue.qsize() >= self.cfg.stream_queue:
                # slow reader: hold the token, DON'T feed the engine —
                # this stream stops consuming step budget until the
                # client catches up
                s.stalled = int(tok)
                continue
            self._deliver(s, int(tok), fb, fl)
        for uid, status in reaped.items():
            s = self._streams.get(uid)
            reason = _STATUS_REASON.get(status, status)
            if s is not None and not s.finished:
                self._close_stream(s, reason)
                continue
            # stream already gone (a disconnected handler tears down
            # before the engine's cancel reap comes back): write the
            # journey close _close_stream would have written, so every
            # journey terminates in exactly one "closed" stamp
            j = self._journeys.get(uid)
            if j is not None and not any(st["phase"] == "closed"
                                         for st in j):
                self._journey(uid, "closed", reason=reason)

    def _deliver(self, s: _Stream, tok: int,
                 fb: List[Tuple[int, int]], fl: List[int]) -> None:
        s.emitted += 1
        if s.emitted == 1:
            self._journey(s.uid, "first_token")
        s.tokens.append(tok)
        stop = self._sampling.stop_token
        finish = None
        if stop is not None and tok == stop:
            finish = "stop"
        elif s.emitted >= s.max_tokens:
            finish = "length"
        s.queue.put_nowait(tok)
        if finish is not None:
            self._close_stream(s, finish)
            fl.append(s.uid)
        else:
            fb.append((s.uid, tok))

    def _close_stream(self, s: _Stream, reason: str) -> None:  # tpulint: close-out
        if s.finished:
            return
        s.finished = True
        s.finish_reason = reason
        s.queue.put_nowait(_Finish(reason))
        self._journey(s.uid, "closed", reason=reason)

    def _mark_dead(self) -> None:
        self._dead = True
        for s in list(self._streams.values()):
            if not s.finished:
                self._close_stream(s, "failed")
        logger.error("gateway: backend engine is dead — open streams "
                     "closed 'failed', new arrivals get 503")

    # ------------------------------------------------------------------
    # connection handling
    # ------------------------------------------------------------------
    async def _send(self, writer: asyncio.StreamWriter, data: bytes,
                    sse: bool = False) -> None:
        writer.write(data)
        await writer.drain()
        if sse:
            self._c_sse_bytes.inc(len(data))

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        self._c_conns.inc()
        watcher: Optional[asyncio.Task] = None
        try:
            try:
                head = await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"),
                    timeout=self.cfg.head_timeout_s)
            except (asyncio.IncompleteReadError, ConnectionError,
                    asyncio.TimeoutError):
                return          # client gave up before a full request
            except asyncio.LimitOverrunError:
                # no blank line within the stream limit: an oversized
                # head is the client's error, not ours
                raise protocol.ProtocolError(
                    400, "head_too_large",
                    "request head exceeds the size limit")
            method, target, headers = protocol.parse_request_head(
                head[:-4])
            try:
                n_body = int(headers.get("content-length", "0") or 0)
            except ValueError:
                raise protocol.ProtocolError(
                    400, "bad_content_length",
                    f"malformed Content-Length "
                    f"{headers['content-length']!r}")
            if n_body < 0:
                raise protocol.ProtocolError(
                    400, "bad_content_length",
                    "negative Content-Length")
            if n_body > protocol.MAX_BODY_BYTES:
                raise protocol.ProtocolError(
                    413, "body_too_large",
                    f"body exceeds {protocol.MAX_BODY_BYTES} bytes")
            # the body read is bounded like the head read — a client
            # that promises bytes and stalls must not pin a handler
            # (and its fd) forever
            body = await asyncio.wait_for(
                reader.readexactly(n_body),
                timeout=self.cfg.head_timeout_s) if n_body else b""
            if method == "GET" and target == "/healthz":
                self._c_requests.inc(route="healthz")
                await self._route_healthz(writer)
            elif method == "GET" and target == "/metrics":
                self._c_requests.inc(route="metrics")
                await self._route_metrics(writer)
            elif target == "/v1/completions" and method == "POST":
                self._c_requests.inc(route="completions")
                watcher = await self._route_completions(
                    reader, writer, headers, body)
            elif self._ops_on \
                    and target.partition("?")[0].startswith("/debug/"):
                # ops OFF intentionally skips this branch: the whole
                # surface 404s below, indistinguishable from absent
                self._c_requests.inc(route="debug")
                await self._route_debug(method, target, headers, writer)
            elif target in ("/healthz", "/metrics", "/v1/completions"):
                await self._send_error(writer, protocol.ProtocolError(
                    405, "method_not_allowed",
                    f"{method} not supported on {target}"))
            else:
                await self._send_error(writer, protocol.ProtocolError(
                    404, "not_found", f"no route {target!r}"))
        except protocol.ProtocolError as e:
            await self._send_error(writer, e)
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass                # client went away mid-exchange
        except Exception:
            logger.exception("gateway: connection handler failed")
            await self._send_error(writer, protocol.ProtocolError(
                500, "internal", "internal gateway error"))
        finally:
            if watcher is not None:
                watcher.cancel()
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _send_error(self, writer: asyncio.StreamWriter,
                          e: protocol.ProtocolError,
                          extra: Optional[Dict[str, str]] = None) -> None:
        try:
            await self._send(writer, protocol.http_response(
                e.status, protocol.error_body(e.status, e.code, str(e)),
                extra_headers=extra))
        except (ConnectionError, OSError):
            pass

    # ------------------------------------------------------------------
    # routes
    # ------------------------------------------------------------------
    async def _route_healthz(self, writer) -> None:
        state, payload = await self._call(self._health_probe)
        if self._draining:
            state = "draining"
        code = protocol.health_status_code(state)
        extra = {}
        if code != 200:
            extra["Retry-After"] = str(self.cfg.drain_retry_after_s)
        body = json.dumps({"state": state,
                           "gateway": {
                               "draining": self._draining,
                               "dead": self._dead,
                               "open_streams": len(self._streams)},
                           "backend": payload}).encode("utf-8")
        await self._send(writer, protocol.http_response(
            code, body, extra_headers=extra))

    async def _route_metrics(self, writer) -> None:
        text = await self._call(self._metrics_text)
        await self._send(writer, protocol.http_response(
            200, text.encode("utf-8"),
            content_type="text/plain; version=0.0.4"))

    # ------------------------------------------------------------------
    # ops plane: /debug/* (docs/OBSERVABILITY.md "SLOs & error
    # budgets").  Read-only routes are gated by GatewayConfig.ops;
    # the mutators additionally by the ops token.  Every backend
    # touch still rides the single-executor _call seam.
    # ------------------------------------------------------------------
    @staticmethod
    def _require_method(method: str, want: str, path: str) -> None:
        if method != want:
            raise protocol.ProtocolError(
                405, "method_not_allowed",
                f"{method} not supported on {path}")

    def _check_ops_token(self, headers: Dict[str, str]) -> None:
        """Mutating-endpoint gate: no configured token refuses outright
        (403 — a deployment opts into remote dump/capture by setting
        one); a missing header is 401 (client never authenticated), a
        mismatched one 403."""
        if not self.cfg.ops_token:
            raise protocol.ProtocolError(
                403, "ops_mutations_disabled",
                "mutating /debug/* requires GatewayConfig.ops_token "
                "to be configured")
        got = headers.get("x-ops-token")
        if got is None:
            raise protocol.ProtocolError(
                401, "missing_ops_token",
                "x-ops-token header required")
        if got != self.cfg.ops_token:
            raise protocol.ProtocolError(
                403, "bad_ops_token", "x-ops-token mismatch")

    async def _send_json(self, writer, obj) -> None:
        await self._send(writer, protocol.http_response(
            200, json.dumps(obj).encode("utf-8")))

    async def _route_debug(self, method: str, target: str,
                           headers: Dict[str, str], writer) -> None:
        path, _, query = target.partition("?")
        if path == "/debug/slo":
            self._require_method(method, "GET", path)
            await self._send_json(
                writer, await self._call(self.backend.slo_scorecard))
        elif path.startswith("/debug/journeys/"):
            self._require_method(method, "GET", path)
            await self._route_debug_journey(path, writer)
        elif path == "/debug/anomalies":
            self._require_method(method, "GET", path)
            params = _query_params(query)
            if "tail" in params:
                await self._anomaly_tail(writer, params.get("tail"))
            else:
                await self._send_json(
                    writer, await self._call(self._ops_anomalies))
        elif path == "/debug/config":
            self._require_method(method, "GET", path)
            await self._send_json(writer,
                                  await self._call(self._ops_config))
        elif path == "/debug/dump":
            self._require_method(method, "POST", path)
            self._check_ops_token(headers)
            d = await self._call(self.backend.ops_dump)
            await self._send_json(writer, {"ok": d is not None,
                                           "dump": d})
        elif path == "/debug/capture":
            self._require_method(method, "POST", path)
            self._check_ops_token(headers)
            got = await self._call(self.backend.arm_budgeted_capture,
                                   "ops")
            await self._send_json(writer, {"ok": got is not None,
                                           "capture": got})
        else:
            raise protocol.ProtocolError(
                404, "not_found", f"no ops route {path!r}")

    async def _route_debug_journey(self, path: str, writer) -> None:
        tail = path[len("/debug/journeys/"):]
        try:
            uid = int(tail)
        except ValueError:
            raise protocol.ProtocolError(
                400, "bad_uid",
                f"journey uid must be an int, got {tail!r}")
        wire = self.wire_journey(uid)
        fleet = await self._call(self.backend.request_journey, uid) \
            if self._is_fleet else None
        if wire is None and fleet is None:
            raise protocol.ProtocolError(
                404, "unknown_uid",
                f"no journey recorded for uid {uid}")
        await self._send_json(writer, {"uid": uid, "wire": wire,
                                       "fleet": fleet})

    # ---- ops probes (run on the engine thread) -----------------------
    def _ops_anomalies(self) -> Dict:
        summ = self.backend.anomaly_summary()
        if summ is None:
            return {"enabled": False}
        return {"enabled": True, **summ}

    def _anomaly_ring(self) -> Tuple[int, List[Dict]]:
        """(total fires, full event ring) — the tail's polling read."""
        if self._is_fleet:
            ftel = self.backend._ftel
            mon = None if ftel is None else ftel.monitor
        else:
            mon = self.backend._anom
        if mon is None:
            return 0, []
        return mon.total(), [e.as_dict() for e in list(mon.events)]

    def _ops_config(self) -> Dict:
        from ..telemetry import config_fingerprint
        be = self.backend
        bcfg = be.cfg if self._is_fleet else be.icfg
        gw = _jsonable(self.cfg)
        # never serve the secret back over the surface it guards
        gw["ops_token"] = "<set>" if self.cfg.ops_token else None
        return {"fingerprint": config_fingerprint(),
                "gateway": gw, "backend": _jsonable(bcfg),
                "slo_classes": _jsonable(self._slo)}

    async def _anomaly_tail(self, writer,
                            limit_raw: Optional[str]) -> None:
        """SSE live tail of anomaly fires (``GET /debug/anomalies?
        tail``): replay the recent ring, then poll the monitor on the
        engine thread and emit each new fire as one frame.  ``?tail=N``
        closes after N frames (the deterministic form tests and
        one-shot CLIs use); bare ``?tail`` follows until the client
        disconnects or the gateway drains."""
        limit: Optional[int] = None
        if limit_raw:
            try:
                limit = max(int(limit_raw), 0)
            except ValueError:
                raise protocol.ProtocolError(
                    400, "bad_tail", f"tail must be an int, "
                    f"got {limit_raw!r}")
        await self._send(writer, protocol.sse_head(), sse=True)
        sent = 0
        total, ring = await self._call(self._anomaly_ring)
        try:
            for ev in ring[-8:]:
                if limit is not None and sent >= limit:
                    break
                await self._send(writer, protocol.sse_event(ev),
                                 sse=True)
                sent += 1
            seen = total
            while not (self._shutting or self._dead) \
                    and (limit is None or sent < limit):
                await asyncio.sleep(0.05)
                total, ring = await self._call(self._anomaly_ring)
                new = min(total - seen, len(ring))
                seen = total
                for ev in ring[len(ring) - new:] if new > 0 else ():
                    if limit is not None and sent >= limit:
                        break
                    await self._send(writer, protocol.sse_event(ev),
                                     sse=True)
                    sent += 1
            await self._send(writer, protocol.SSE_DONE, sse=True)
        except (ConnectionError, OSError):
            pass                 # tail reader went away — that's fine

    def _wire_depth(self) -> int:
        return sum(1 for s in self._streams.values() if not s.finished)

    async def _shed_response(self, writer, uid: int, status: str,
                             reason: str) -> None:
        code, ra, slug = protocol.shed_decision(
            status, reason, self._wire_depth(),
            self.cfg.est_ms_per_request, self.cfg.max_retry_after_s,
            self.cfg.drain_retry_after_s)
        self._c_sheds.inc(code=str(code))
        self._journey(uid, "shed", http=code, retry_after_s=ra)
        await self._send_error(
            writer,
            protocol.ProtocolError(code, slug,
                                   f"request shed: {reason or status}"),
            extra={"Retry-After": str(ra)})

    async def _next_uid(self) -> int:
        while True:
            uid = next(self._uid_iter)
            if uid in self._streams:
                continue
            st = (await self._call(self.backend.query, uid))["status"]
            if st in ("unknown", "forgotten"):
                return uid

    async def _route_completions(self, reader, writer,
                                 headers: Dict[str, str],
                                 body: bytes) -> Optional[asyncio.Task]:
        req = protocol.parse_completion_body(
            body, self.cfg.max_tokens_default, self.cfg.max_tokens_cap)
        try:
            priority, deadline_ms, cls = resolve_slo(
                headers.get(SLO_CLASS_HEADER), self._slo,
                self.cfg.default_slo_class, req.priority, req.deadline_ms)
        except KeyError as e:
            raise protocol.ProtocolError(
                400, "unknown_slo_class",
                f"unknown {SLO_CLASS_HEADER}: {e} (have "
                f"{sorted(self._slo)})")
        if req.uid is not None:
            uid = req.uid
            if uid in self._streams:
                raise protocol.ProtocolError(
                    409, "uid_in_use",
                    f"uid {uid} already has an open wire request")
        else:
            uid = await self._next_uid()
            while uid in self._streams:
                # an explicit-uid request grabbed this number while
                # _next_uid was off awaiting the engine thread
                uid = await self._next_uid()
        # RESERVE the uid synchronously — no await between the
        # membership check above and this insert, so two concurrent
        # same-uid requests cannot both pass the 409 guard and race
        # their puts into the engine's continuation branch (the
        # second put would silently append onto the first's prompt)
        s = _Stream(uid=uid, rid=f"cmpl-{uid}",
                    max_tokens=req.max_tokens,
                    want_stream=req.stream, queue=asyncio.Queue())
        # happens-before: the event loop is _streams' ONLY writer (this
        # insert + unreserve's del); the engine thread only performs
        # GIL-atomic point lookups (.get/membership/len) and never
        # iterates-while-mutating, and every executor read of a record
        # inserted here is ordered after the insert by the run_in_executor
        # submission that carries the uid across
        self._streams[uid] = s  # tpulint: disable=shared-state-race

        def unreserve() -> None:
            if self._streams.get(uid) is s:
                del self._streams[uid]

        self._journey(uid, "received", slo=cls, stream=req.stream,
                      prompt_tokens=len(req.prompt))
        if self._draining or self._dead:
            unreserve()
            await self._shed_response(
                writer, uid, "shed",
                "engine is dead" if self._dead else "engine is draining")
            return None
        if req.uid is not None:
            st = (await self._call(self.backend.query, uid))["status"]
            if st not in ("unknown", "forgotten"):
                unreserve()
                raise protocol.ProtocolError(
                    409, "uid_in_use",
                    f"uid {uid} is already known to the engine "
                    f"(status {st!r})")
        try:
            # both backends take the class: the fleet router routes by
            # it (interactive arrivals land on the prefill pool, batch
            # on decode) and either backend's SLO tracker evaluates
            # the request under it (telemetry/slo.py)
            verdict = await self._call(
                self.backend.put, uid, req.prompt,
                priority=priority, deadline_ms=deadline_ms,
                slo_class=cls)
        except Exception:
            unreserve()
            raise
        if not verdict.admitted:
            unreserve()
            await self._shed_response(writer, uid, verdict.status,
                                      verdict.reason)
            return None
        self._journey(uid, "admitted", status=verdict.status,
                      replica=verdict.replica)
        self._wake.set()
        watcher = asyncio.get_running_loop().create_task(
            self._watch_disconnect(reader, s))
        try:
            if req.stream:
                await self._stream_response(writer, s)
            else:
                await self._plain_response(writer, s, req)
        finally:
            unreserve()
        return watcher

    async def _watch_disconnect(self, reader: asyncio.StreamReader,
                                s: _Stream) -> None:
        """EOF on the read side means the client is gone (connections
        are one-request); an open request rides the engine's existing
        ``cancel()`` path — KV released, terminal status ``cancelled``,
        exactly the mid-flight-abort contract PR 6 built."""
        try:
            while True:
                data = await reader.read(4096)
                if not data:
                    break
        except (ConnectionError, OSError):
            pass
        if not s.finished and not s.disconnected:
            await self._client_gone(s)

    async def _client_gone(self, s: _Stream) -> None:
        if s.disconnected:
            return
        s.disconnected = True
        self._journey(s.uid, "disconnect", emitted=s.emitted)
        self._c_disc.inc()
        await self._call(self.backend.cancel, s.uid)

    async def _stream_response(self, writer, s: _Stream) -> None:
        self._c_streams.inc()
        created = int(time.time())
        try:
            await self._send(writer, protocol.sse_head(
                {"x-request-id": s.rid}))
            self._journey(s.uid, "sse_open")
            while True:
                item = await s.queue.get()
                if isinstance(item, _Finish):
                    frame = protocol.sse_event(protocol.completion_chunk(
                        s.rid, created, self.cfg.model_name,
                        finish_reason=item.reason)) + protocol.SSE_DONE
                    await self._send(writer, frame, sse=True)
                    break
                await self._send(writer, protocol.sse_event(
                    protocol.completion_chunk(
                        s.rid, created, self.cfg.model_name,
                        token=item)), sse=True)
        except (ConnectionError, OSError):
            if not s.finished and not s.disconnected:
                await self._client_gone(s)

    async def _plain_response(self, writer, s: _Stream,
                              req: protocol.CompletionRequest) -> None:
        created = int(time.time())
        while True:
            item = await s.queue.get()
            if isinstance(item, _Finish):
                break
        body = json.dumps(protocol.completion_response(
            s.rid, created, self.cfg.model_name, s.tokens,
            s.finish_reason or "stop", prompt_tokens=len(req.prompt),
            echo_prompt=req.prompt if req.echo else None)).encode("utf-8")
        try:
            await self._send(writer, protocol.http_response(
                200, body, extra_headers={"x-request-id": s.rid}))
        except (ConnectionError, OSError):
            pass                # response computed but client gone


# --------------------------------------------------------------------------
# run-in-a-thread helper (tests, loadgen, notebooks)
# --------------------------------------------------------------------------

class GatewayHandle:
    """A gateway running on its own event-loop thread.  ``port`` is
    bound and live on return from :func:`spawn_gateway`; call
    :meth:`begin_drain` for the programmatic SIGTERM-equivalent and
    :meth:`stop` to drain-and-join."""

    def __init__(self, gateway: Gateway, loop: asyncio.AbstractEventLoop,
                 thread: threading.Thread):
        self.gateway = gateway
        self._loop = loop
        self._thread = thread

    @property
    def host(self) -> str:
        return self.gateway.cfg.host

    @property
    def port(self) -> int:
        return self.gateway.port

    def submit(self, coro, timeout: float = 60.0):
        """Run a coroutine on the gateway loop, blocking for its
        result (the cross-thread control channel)."""
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(timeout)

    def begin_drain(self, deadline_ms: Optional[float] = None) -> None:
        """Trigger the drain WITHOUT waiting — exactly what the
        SIGTERM handler does in-process."""
        asyncio.run_coroutine_threadsafe(
            self.gateway.shutdown(deadline_ms), self._loop)

    def stop(self, deadline_ms: Optional[float] = None,
             timeout: float = 120.0) -> None:
        fut = asyncio.run_coroutine_threadsafe(
            self.gateway.shutdown(deadline_ms), self._loop)
        fut.result(timeout)
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise GatewayError("gateway loop thread did not exit")


def spawn_gateway(backend, cfg: Optional[GatewayConfig] = None,
                  start_timeout_s: float = 120.0) -> GatewayHandle:
    """Start a :class:`Gateway` on a fresh event loop in a daemon
    thread and return once the socket is bound.  Startup errors (e.g.
    the dead-engine refusal) re-raise in the caller."""
    box: Dict[str, object] = {}
    ready = threading.Event()

    def run() -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        try:
            gw = Gateway(backend, cfg)
            loop.run_until_complete(gw.start())
        except BaseException as e:  # startup failure -> caller
            logger.error("gateway: startup failed: %s", e)
            box["error"] = e
            ready.set()
            loop.close()
            return
        box["gw"] = gw
        box["loop"] = loop
        ready.set()
        try:
            loop.run_until_complete(gw.wait_stopped())
        finally:
            pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
            for t in pending:
                t.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
            loop.close()

    thread = threading.Thread(target=run, name="gateway-loop",
                              daemon=True)
    thread.start()
    if not ready.wait(start_timeout_s):
        raise GatewayError("gateway did not start within "
                           f"{start_timeout_s}s")
    if "error" in box:
        raise box["error"]
    return GatewayHandle(box["gw"], box["loop"], thread)
