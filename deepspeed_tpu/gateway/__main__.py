"""``python -m deepspeed_tpu.gateway`` — serve a demo engine over HTTP.

The real-SIGTERM drill: run it, point a client at
``POST /v1/completions``, then ``kill -TERM`` the pid and watch
in-flight streams finish while new arrivals get 503.  Production
deployments construct their own engine/fleet and call
``Gateway.start()``; this entry point exists so the wire surface is
drivable without writing any code (and so the drain contract can be
exercised with a real signal, not just the programmatic
``shutdown()`` the tests use).
"""

from __future__ import annotations

import argparse
import asyncio


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="serve a tiny demo engine over HTTP (SSE streaming)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--seed", type=int, default=None,
                    help="base sampling key (temperature sampling)")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--max-queued", type=int, default=32,
                    help="admission queue bound (shed policy: reject)")
    ap.add_argument("--drain-ms", type=float, default=30_000.0)
    args = ap.parse_args(argv)

    from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                         SamplingParams)
    from deepspeed_tpu.inference.overload import OverloadConfig
    from deepspeed_tpu.models import build_model

    from .server import Gateway, GatewayConfig

    model = build_model("llama-tiny", vocab_size=256, num_layers=2,
                        d_model=64, num_heads=4, num_kv_heads=2,
                        d_ff=128, max_seq_len=256)
    eng = InferenceEngine(model, InferenceConfig(
        token_budget=64, max_seqs=8, kv_block_size=8, num_kv_blocks=96,
        max_seq_len=256,
        overload=OverloadConfig(max_queued_requests=args.max_queued,
                                shed_policy="reject")))
    gw = Gateway(eng, GatewayConfig(
        host=args.host, port=args.port, seed=args.seed,
        sampling=SamplingParams(temperature=args.temperature,
                                max_new_tokens=1 << 30),
        drain_deadline_ms=args.drain_ms))

    async def serve() -> None:
        await gw.start()
        await gw.wait_stopped()

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
