"""Wire protocol: HTTP/1.1 + SSE framing + shed-to-status translation.

Everything here is a pure function over bytes/dicts — no sockets, no
event loop, no engine — so the whole wire surface unit-tests without a
server (tests/test_gateway.py).  ``server.py`` owns the asyncio side
and calls down into these.

Design rule (docs/SERVING.md "Network gateway"): wire semantics are
*translations* of contracts the engine already exposes, never new
policy.  The one place that looks like policy — which HTTP status a
shed maps to, and what ``Retry-After`` promises — is derived entirely
from the :class:`~deepspeed_tpu.inference.overload.AdmissionVerdict`
and the queue depth the gateway can see (:func:`shed_decision`).
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, List, Optional, Tuple

# bounded request head + body: a gateway that reads unbounded client
# bytes before admission control is a memory DoS ahead of the engine's
# own shed policy
MAX_HEAD_BYTES = 16 * 1024
MAX_BODY_BYTES = 1024 * 1024

_REASONS = {
    200: "OK", 400: "Bad Request", 401: "Unauthorized",
    403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 409: "Conflict",
    413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}

SSE_DONE = b"data: [DONE]\n\n"


class ProtocolError(Exception):
    """A client-attributable wire error -> one HTTP error response.
    ``status`` is the HTTP code, ``code`` a machine-readable slug the
    error body carries (OpenAI-style ``{"error": {...}}``)."""

    def __init__(self, status: int, code: str, message: str):
        super().__init__(message)
        self.status = int(status)
        self.code = code


# --------------------------------------------------------------------------
# request head
# --------------------------------------------------------------------------

def parse_request_head(head: bytes) -> Tuple[str, str, Dict[str, str]]:
    """Parse the request line + headers (everything before the blank
    line).  Header names are lowercased (HTTP headers are
    case-insensitive); duplicate headers keep the LAST value —
    none of the headers this surface reads are list-valued.  Raises
    :class:`ProtocolError` (400) on anything malformed."""
    if len(head) > MAX_HEAD_BYTES:
        raise ProtocolError(400, "head_too_large",
                            f"request head exceeds {MAX_HEAD_BYTES} bytes")
    try:
        text = head.decode("ascii")
    except UnicodeDecodeError as e:
        raise ProtocolError(400, "bad_head",
                            f"non-ASCII bytes in request head: {e}")
    lines = text.split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
        raise ProtocolError(400, "bad_request_line",
                            f"malformed request line {lines[0]!r}")
    method, target, _version = parts
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        name, sep, value = line.partition(":")
        if not sep or not name or name != name.strip():
            raise ProtocolError(400, "bad_header",
                                f"malformed header line {line!r}")
        headers[name.lower()] = value.strip()
    return method.upper(), target, headers


# --------------------------------------------------------------------------
# completion request body
# --------------------------------------------------------------------------

@dataclasses.dataclass
class CompletionRequest:
    """A validated ``POST /v1/completions`` body.  The stack is
    tokenizer-free end to end (models speak token ids), so ``prompt``
    is a list of ints — exactly what ``engine.put`` takes.  ``uid`` is
    optional: the gateway assigns one when absent; an explicit uid is
    how seeded clients pin the (uid, position)-folded sampling keys for
    reproducible streams."""
    prompt: List[int]
    max_tokens: int
    stream: bool
    uid: Optional[int] = None
    priority: Optional[int] = None
    deadline_ms: Optional[float] = None
    echo: bool = False


def parse_completion_body(raw: bytes, max_tokens_default: int,
                          max_tokens_cap: int) -> CompletionRequest:
    """Validate a completions body.  Unknown fields are ignored
    (OpenAI clients send ``model``/``temperature``/... we don't act
    on); wrong *types* on fields we do act on are 400s — a silently
    coerced prompt is a corrupted request."""
    if len(raw) > MAX_BODY_BYTES:
        raise ProtocolError(413, "body_too_large",
                            f"body exceeds {MAX_BODY_BYTES} bytes")
    try:
        body = json.loads(raw.decode("utf-8") or "{}")
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(400, "bad_json", f"body is not JSON: {e}")
    if not isinstance(body, dict):
        raise ProtocolError(400, "bad_json", "body must be a JSON object")

    prompt = body.get("prompt")
    if not isinstance(prompt, list) or not prompt \
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       for t in prompt):
        raise ProtocolError(
            400, "bad_prompt",
            "'prompt' must be a non-empty list of token ids (ints) — "
            "this serving stack is tokenizer-free; clients tokenize")

    max_tokens = body.get("max_tokens", max_tokens_default)
    if not isinstance(max_tokens, int) or isinstance(max_tokens, bool) \
            or max_tokens < 1:
        raise ProtocolError(400, "bad_max_tokens",
                            "'max_tokens' must be a positive int")
    max_tokens = min(max_tokens, max_tokens_cap)

    stream = body.get("stream", False)
    if not isinstance(stream, bool):
        raise ProtocolError(400, "bad_stream", "'stream' must be a bool")

    uid = body.get("uid")
    if uid is not None and (not isinstance(uid, int)
                            or isinstance(uid, bool) or uid < 0):
        raise ProtocolError(400, "bad_uid",
                            "'uid' must be a non-negative int")

    priority = body.get("priority")
    if priority is not None and (not isinstance(priority, int)
                                 or isinstance(priority, bool)):
        raise ProtocolError(400, "bad_priority",
                            "'priority' must be an int (lower = more "
                            "important)")

    deadline_ms = body.get("deadline_ms")
    if deadline_ms is not None:
        if not isinstance(deadline_ms, (int, float)) \
                or isinstance(deadline_ms, bool) or deadline_ms <= 0:
            raise ProtocolError(400, "bad_deadline",
                                "'deadline_ms' must be a positive number")
        deadline_ms = float(deadline_ms)

    return CompletionRequest(prompt=[int(t) for t in prompt],
                             max_tokens=max_tokens, stream=stream,
                             uid=uid, priority=priority,
                             deadline_ms=deadline_ms,
                             echo=bool(body.get("echo", False)))


# --------------------------------------------------------------------------
# responses
# --------------------------------------------------------------------------

def http_response(status: int, body: bytes,
                  content_type: str = "application/json",
                  extra_headers: Optional[Dict[str, str]] = None) -> bytes:
    """One complete non-streaming HTTP/1.1 response.  Connections are
    one-request (``Connection: close``): the gateway's unit of
    admission is the request, and close-per-request keeps disconnect
    detection unambiguous — EOF on the read side always means the
    client is gone."""
    reason = _REASONS.get(status, "Unknown")
    lines = [f"HTTP/1.1 {status} {reason}",
             f"Content-Type: {content_type}",
             f"Content-Length: {len(body)}",
             "Connection: close"]
    for k, v in (extra_headers or {}).items():
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode("ascii") + body


def sse_head(extra_headers: Optional[Dict[str, str]] = None) -> bytes:
    """The response head that opens an SSE stream (no Content-Length —
    the connection closes when the stream does)."""
    lines = ["HTTP/1.1 200 OK",
             "Content-Type: text/event-stream",
             "Cache-Control: no-store",
             "Connection: close"]
    for k, v in (extra_headers or {}).items():
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode("ascii")


def sse_event(obj: Dict) -> bytes:
    """One ``data: <json>\\n\\n`` frame."""
    return b"data: " + json.dumps(obj, separators=(",", ":")).encode(
        "utf-8") + b"\n\n"


def completion_chunk(rid: str, created: int, model: str,
                     token: Optional[int] = None,
                     finish_reason: Optional[str] = None) -> Dict:
    """One streamed completion chunk (OpenAI-shaped; ``token`` carries
    the raw token id where a tokenizer'd server would put text)."""
    return {"id": rid, "object": "text_completion.chunk",
            "created": created, "model": model,
            "choices": [{"index": 0, "token": token,
                         "finish_reason": finish_reason}]}


def completion_response(rid: str, created: int, model: str,
                        tokens: List[int], finish_reason: str,
                        prompt_tokens: int,
                        echo_prompt: Optional[List[int]] = None) -> Dict:
    """The non-streaming completion body."""
    choice = {"index": 0, "tokens": list(tokens),
              "finish_reason": finish_reason}
    if echo_prompt is not None:
        choice["prompt"] = list(echo_prompt)
    return {"id": rid, "object": "text_completion", "created": created,
            "model": model, "choices": [choice],
            "usage": {"prompt_tokens": int(prompt_tokens),
                      "completion_tokens": len(tokens)}}


def error_body(status: int, code: str, message: str) -> bytes:
    return json.dumps({"error": {"message": message, "type": "api_error"
                                 if status >= 500 else "client_error",
                                 "code": code, "status": status}}).encode(
        "utf-8")


# --------------------------------------------------------------------------
# shed translation: AdmissionVerdict -> (HTTP status, Retry-After)
# --------------------------------------------------------------------------

def retry_after_s(queue_depth: int, est_ms_per_request: float,
                  max_retry_after_s: int) -> int:
    """Computed backoff for a load shed: the time the current backlog
    needs to drain at the gateway's estimated per-request service time,
    clamped to ``[1, max_retry_after_s]``.  Deterministic in its inputs
    — the SLO tests pin the math."""
    est = math.ceil(max(queue_depth, 1) * max(est_ms_per_request, 0.0)
                    / 1e3)
    return max(1, min(int(est), int(max_retry_after_s)))


def shed_decision(verdict_status: str, verdict_reason: str,
                  queue_depth: int, est_ms_per_request: float,
                  max_retry_after_s: int,
                  drain_retry_after_s: int) -> Tuple[int, int, str]:
    """Map a non-admitted :class:`AdmissionVerdict` onto
    ``(http_status, retry_after_s, code)``.

    The split follows the verdict's own semantics, not the gateway's
    mood: a shed from the *shed policy* (bounded queue ``reject`` /
    ``evict-lowest``, or the fleet-saturation verdict — every routable
    replica's own bound rejected it, retrying after the backlog drains
    CAN help) is load -> **429** with the computed backoff; a shed
    because the engine is ``draining``/``dead`` or the fleet has **no
    routable replica at all** is availability — retrying THIS endpoint
    sooner won't help -> **503** with the drain's own horizon.  (The
    match is the phrase ``"no routable"``: the saturation reason also
    contains the word "routable" — "every routable replica shed..." —
    and that one is genuinely load.)  Anything else non-admitted
    (future verdict vocabulary) conservatively maps to 503 so clients
    back off."""
    reason = (verdict_reason or "").lower()
    if "dead" in reason or "draining" in reason \
            or "no routable" in reason:
        return 503, max(1, int(drain_retry_after_s)), "unavailable"
    if verdict_status == "shed":
        return (429,
                retry_after_s(queue_depth, est_ms_per_request,
                              max_retry_after_s),
                "overloaded")
    return 503, max(1, int(drain_retry_after_s)), "unavailable"


# --------------------------------------------------------------------------
# health ladder -> status code
# --------------------------------------------------------------------------

def health_status_code(state: str) -> int:
    """PR-8 health ladder -> ``/healthz`` status: ``healthy`` and
    ``degraded`` still serve (load balancers must not eject a replica
    for a transient degraded window — the body says which it is);
    ``draining`` and ``dead`` are 503 so upstreams stop routing."""
    return 200 if state in ("healthy", "degraded") else 503
