"""Network gateway: the wire tier above the engine/fleet
(docs/SERVING.md "Network gateway").

A stdlib-asyncio HTTP/1.1 server with an OpenAI-style
``POST /v1/completions`` surface (SSE token streaming), built as a
thin translation layer: admission verdicts -> 429/503 + Retry-After,
``x-slo-class`` header -> priority/deadline defaults, client
disconnect -> ``cancel()``, ``/healthz`` -> the health ladder,
``/metrics`` -> the Prometheus exposition, SIGTERM -> ``drain()``.
"""

from .protocol import (CompletionRequest, ProtocolError,
                       health_status_code, parse_completion_body,
                       parse_request_head, retry_after_s, shed_decision,
                       sse_event)
from .server import (Gateway, GatewayConfig, GatewayError, GatewayHandle,
                     spawn_gateway)
from .sloclass import (SLO_CLASS_HEADER, SloClass, default_slo_classes,
                       resolve_slo)

__all__ = ["Gateway", "GatewayConfig", "GatewayError", "GatewayHandle",
           "spawn_gateway", "SloClass", "SLO_CLASS_HEADER",
           "default_slo_classes", "resolve_slo", "CompletionRequest",
           "ProtocolError", "parse_request_head", "parse_completion_body",
           "sse_event", "retry_after_s", "shed_decision",
           "health_status_code"]
