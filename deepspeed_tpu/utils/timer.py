"""Wall-clock and throughput timers.

TPU-native analog of the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` utils/timer.py:44, ``ThroughputTimer`` :199).
Device synchronization uses ``jax.block_until_ready`` tokens rather than CUDA
events: callers pass the arrays whose computation a timer should fence on, or
rely on ``jax.effects_barrier()``.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .logging import log_dist


def _sync() -> None:
    try:
        import jax

        jax.effects_barrier()
    # best-effort barrier, called on every timer stop — never spam
    except Exception:  # tpulint: disable=silent-except
        pass


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0
        self._records: List[float] = []

    def start(self, sync: bool = False) -> None:
        assert not self.started, f"timer {self.name} already started"
        if sync:
            _sync()
        self._start = time.perf_counter()
        self.started = True

    def stop(self, sync: bool = False, record: bool = True) -> None:
        assert self.started, f"timer {self.name} not started"
        if sync:
            _sync()
        dt = time.perf_counter() - self._start
        self._elapsed += dt
        if record:
            self._records.append(dt)
        self.started = False

    def reset(self) -> None:
        self.started = False
        self._elapsed = 0.0
        self._records = []

    def elapsed(self, reset: bool = True) -> float:
        """Total accumulated seconds; optionally reset."""
        stopped_mid = False
        if self.started:
            self.stop()
            stopped_mid = True
        out = self._elapsed
        if reset:
            self._elapsed = 0.0
        if stopped_mid:
            self.start()
        return out

    def mean(self) -> float:
        return sum(self._records) / len(self._records) if self._records else 0.0


class SynchronizedWallClockTimer:
    """Group of named timers (reference: utils/timer.py:44)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    @staticmethod
    def memory_usage() -> str:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0) / (1024**3)
            peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
            return f"device mem: in_use={in_use:.2f}GB peak={peak:.2f}GB"
        # the fallback string itself surfaces in the timer log line
        except Exception:  # tpulint: disable=silent-except
            return "device mem: unavailable"

    def log(self, names: Optional[List[str]] = None, normalizer: float = 1.0,
            reset: bool = True, memory_breakdown: bool = False, ranks=None) -> None:
        assert normalizer > 0.0
        names = names if names is not None else list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}ms")
        msg = "time (ms) | " + " | ".join(parts)
        if memory_breakdown:
            msg += " | " + self.memory_usage()
        log_dist(msg, ranks=ranks)

    def get_mean(self, names: List[str], normalizer: float = 1.0) -> Dict[str, float]:
        assert normalizer > 0.0
        return {
            n: self.timers[n].mean() * 1000.0 / normalizer
            for n in names if n in self.timers
        }


class ThroughputTimer:
    """Samples/sec + TFLOPS tracking across steps (reference: utils/timer.py:199)."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: Optional[int] = None, monitor_memory: bool = False):
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.enabled = True
        self.reset()

    def reset(self) -> None:
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self._start = 0.0

    def start(self) -> None:
        if not self.enabled:
            return
        self._start = time.perf_counter()

    def stop(self, global_step: bool = True, report_speed: bool = True) -> None:
        if not self.enabled or self._start == 0.0:
            return
        duration = time.perf_counter() - self._start
        self._start = 0.0
        self.step_elapsed_time += duration
        if not global_step:
            return
        self.global_step_count += 1
        if self.global_step_count > self.start_step:
            self.total_elapsed_time += self.step_elapsed_time
        if (report_speed and self.steps_per_output
                and self.global_step_count % self.steps_per_output == 0):
            log_dist(
                f"step={self.global_step_count}, "
                f"samples/sec={self.avg_samples_per_sec():.2f}, "
                f"step_time={self.step_elapsed_time:.3f}s")
        self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        counted = self.global_step_count - self.start_step
        if counted > 0 and self.total_elapsed_time > 0:
            return self.batch_size * counted / self.total_elapsed_time
        return 0.0
