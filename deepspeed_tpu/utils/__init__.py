from .logging import logger, log_dist, warning_once
from .timer import SynchronizedWallClockTimer, ThroughputTimer

__all__ = ["logger", "log_dist", "warning_once",
           "SynchronizedWallClockTimer", "ThroughputTimer"]
