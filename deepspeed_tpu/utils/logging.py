"""Logging utilities.

TPU-native analog of the reference's ``deepspeed/utils/logging.py``: a package
logger plus rank-aware helpers (``log_dist``).  On TPU the "rank" is the JAX
process index (one process per host), not a per-device rank.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

LOG_LEVEL = os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper()

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


@functools.lru_cache(None)
def _create_logger(name: str = "deepspeed_tpu", level: str = LOG_LEVEL) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(getattr(logging, level, logging.INFO))
    lg.propagate = False
    handler = logging.StreamHandler(stream=sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%Y-%m-%d %H:%M:%S"))
    lg.addHandler(handler)
    return lg


logger = _create_logger()


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    # logging bootstrap: this helper runs inside the logger itself
    except Exception:  # tpulint: disable=silent-except
        return 0


def log_dist(message: str, ranks=None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (default: rank 0).

    Mirrors the reference's ``log_dist`` (deepspeed/utils/logging.py) with JAX
    process indices standing in for torch.distributed ranks.
    """
    ranks = ranks if ranks is not None else [0]
    me = _process_index()
    if -1 in ranks or me in ranks:
        logger.log(level, message)


def warning_once(message: str) -> None:
    _warn_once(message)


@functools.lru_cache(None)
def _warn_once(message: str) -> None:
    logger.warning(message)
