"""Stable-Diffusion-class UNet + VAE (diffusers serving family).

TPU-native analog of the reference's diffusers model implementations
(``deepspeed/model_implementations/diffusers/unet.py:8`` — DSUNet
wrapping the HF UNet2DConditionModel forward under cuda graphs;
``vae.py:8`` DSVAE; injection containers
``module_inject/containers/unet.py:13``, ``vae.py:10``).  The reference
accelerates torch modules with fused kernels + graph replay; here the
models are implemented natively on the spatial op suite
(``ops/spatial.py`` — NHWC group norm, fused bias/residual adds,
latent-token attention, GEGLU transformer block) so the whole denoise
step is ONE jitted XLA program.

TPU-first notes: every conv is channels-last (NHWC, TPU-native conv
layout — the channel dim rides the 128-lane axis); GroupNorm + SiLU
chains fuse into the conv epilogues; attention flattens H·W into the
sequence dim and reuses the language-model attention path (non-causal).
Shapes are static per (resolution, batch) — the compiled program replays
exactly like the reference's cuda graph, but by construction.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..ops.spatial import (diffusers_transformer_block, nhwc_group_norm,
                           spatial_attention)

silu = jax.nn.silu


# --------------------------------------------------------------------------
# shared building blocks
# --------------------------------------------------------------------------

def conv2d(x, p, stride: int = 1):
    """NHWC conv, weights [kh, kw, cin, cout] (+ bias [cout])."""
    y = jax.lax.conv_general_dilated(
        x, p["kernel"], window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y + p["bias"] if "bias" in p else y


def _conv_init(key, kh, kw, cin, cout, scale=None):
    scale = scale if scale is not None else 1.0 / math.sqrt(kh * kw * cin)
    return {"kernel": jax.random.normal(key, (kh, kw, cin, cout)) * scale,
            "bias": jnp.zeros((cout,))}


def _dense_init(key, cin, cout):
    return {"kernel": jax.random.normal(key, (cin, cout))
            / math.sqrt(cin), "bias": jnp.zeros((cout,))}


def dense(x, p):
    return x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep features [B, dim] (diffusers get_timestep_
    embedding convention: half cos, half sin)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


def resblock(x, p, temb=None, num_groups: int = 32, eps: float = 1e-5):
    """UNet/VAE ResnetBlock2D: GN→SiLU→conv → (+time proj) → GN→SiLU→
    conv, residual (1x1 shortcut when channels change)."""
    h = silu(nhwc_group_norm(x, p["gn1"]["scale"], p["gn1"]["bias"],
                             num_groups=num_groups, eps=eps))
    h = conv2d(h, p["conv1"])
    if temb is not None and "time" in p:
        h = h + dense(silu(temb), p["time"])[:, None, None, :]
    h = silu(nhwc_group_norm(h, p["gn2"]["scale"], p["gn2"]["bias"],
                             num_groups=num_groups, eps=eps))
    h = conv2d(h, p["conv2"])
    skip = conv2d(x, p["shortcut"]) if "shortcut" in p else x
    return skip + h


def _resblock_init(key, cin, cout, temb_dim: Optional[int],
                   num_groups: int = 32):
    k = jax.random.split(key, 4)
    p = {"gn1": {"scale": jnp.ones((cin,)), "bias": jnp.zeros((cin,))},
         "conv1": _conv_init(k[0], 3, 3, cin, cout),
         "gn2": {"scale": jnp.ones((cout,)), "bias": jnp.zeros((cout,))},
         "conv2": _conv_init(k[1], 3, 3, cout, cout, scale=1e-3)}
    if temb_dim is not None:
        p["time"] = _dense_init(k[2], temb_dim, cout)
    if cin != cout:
        p["shortcut"] = _conv_init(k[3], 1, 1, cin, cout)
    return p


def _attn_params_init(key, c, ctx_dim=None):
    k = jax.random.split(key, 4)
    kv = ctx_dim if ctx_dim is not None else c
    return {"wq": jax.random.normal(k[0], (c, c)) / math.sqrt(c),
            "wk": jax.random.normal(k[1], (kv, c)) / math.sqrt(kv),
            "wv": jax.random.normal(k[2], (kv, c)) / math.sqrt(kv),
            "wo": jax.random.normal(k[3], (c, c)) / math.sqrt(c),
            "bo": jnp.zeros((c,))}


def _txblock_init(key, c, num_heads, ctx_dim, ff_mult: int = 4):
    k = jax.random.split(key, 5)
    ln = lambda: {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
    return {"ln1": ln(), "ln2": ln(), "ln3": ln(),
            "attn1": _attn_params_init(k[0], c),
            "attn2": _attn_params_init(k[1], c, ctx_dim),
            "ff": {"wi": jax.random.normal(k[2], (c, 2 * ff_mult * c))
                   / math.sqrt(c),
                   "bi": jnp.zeros((2 * ff_mult * c,)),
                   "wo": jax.random.normal(k[3], (ff_mult * c, c))
                   / math.sqrt(ff_mult * c),
                   "bo": jnp.zeros((c,))}}


def spatial_transformer(x, p, num_heads, context=None, num_groups=32,
                        eps: float = 1e-5):
    """Transformer2DModel: GN → 1x1 proj-in → N GEGLU blocks (over H·W
    tokens) → 1x1 proj-out, residual."""
    h = nhwc_group_norm(x, p["gn"]["scale"], p["gn"]["bias"],
                        num_groups=num_groups, eps=1e-6)
    h = conv2d(h, p["proj_in"])
    for bp in p["blocks"]:
        h = diffusers_transformer_block(h, bp, num_heads,
                                        context=context, eps=eps)
    h = conv2d(h, p["proj_out"])
    return x + h


def _spatial_tx_init(key, c, num_heads, ctx_dim, depth: int = 1):
    k = jax.random.split(key, depth + 2)
    return {"gn": {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))},
            "proj_in": _conv_init(k[0], 1, 1, c, c),
            "blocks": [_txblock_init(k[2 + i], c, num_heads, ctx_dim)
                       for i in range(depth)],
            "proj_out": _conv_init(k[1], 1, 1, c, c, scale=1e-3)}


# --------------------------------------------------------------------------
# UNet2DCondition (SD-1.x shape)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class UNetConfig:
    """SD-1.x defaults; shrink the channel tuple for tests."""
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    # SD-1.x quirk: diffusers' attention_head_dim=8 acts as the HEAD
    # COUNT (head dim = C/8), constant across stages
    attention_head_dim: int = 8
    num_groups: int = 32
    tx_depth: int = 1

    def heads(self, c: int) -> int:
        n = self.attention_head_dim
        assert c % n == 0, (
            f"stage channels {c} must divide by attention_head_dim={n} "
            "(SD quirk: that field is the HEAD COUNT)")
        return n


class UNet2DCondition:
    """Conditional denoising UNet: conv-in → down stages (res+tx,
    downsample) → mid (res, tx, res) → up stages (skip-cat res+tx,
    upsample) → GN/SiLU/conv-out.  ``__call__(latents [B,H,W,Cin],
    timesteps [B], context [B,T,ctx]) -> eps [B,H,W,Cout]``."""

    def __init__(self, config: UNetConfig = None, seed: int = 0,
                 dtype=jnp.float32):
        cfg = self.config = config or UNetConfig()
        key = jax.random.PRNGKey(seed)
        ks = iter(jax.random.split(key, 256))
        ch = cfg.block_out_channels
        temb = ch[0] * 4
        g = cfg.num_groups
        p: Dict[str, Any] = {
            "time_mlp": [_dense_init(next(ks), ch[0], temb),
                         _dense_init(next(ks), temb, temb)],
            "conv_in": _conv_init(next(ks), 3, 3, cfg.in_channels, ch[0]),
        }
        downs: List[Dict] = []
        c = ch[0]
        self._skip_chs = [c]
        for si, cout in enumerate(ch):
            stage: Dict[str, Any] = {"res": [], "tx": []}
            last = si == len(ch) - 1
            for _ in range(cfg.layers_per_block):
                stage["res"].append(
                    _resblock_init(next(ks), c, cout, temb, g))
                c = cout
                if not last:        # SD: no attention at the deepest res
                    stage["tx"].append(_spatial_tx_init(
                        next(ks), c, cfg.heads(c),
                        cfg.cross_attention_dim, cfg.tx_depth))
                self._skip_chs.append(c)
            if not last:
                stage["down"] = _conv_init(next(ks), 3, 3, c, c)
                self._skip_chs.append(c)
            downs.append(stage)
        p["downs"] = downs
        p["mid"] = {
            "res1": _resblock_init(next(ks), c, c, temb, g),
            "tx": _spatial_tx_init(next(ks), c, cfg.heads(c),
                                   cfg.cross_attention_dim, cfg.tx_depth),
            "res2": _resblock_init(next(ks), c, c, temb, g)}
        ups: List[Dict] = []
        skips = list(self._skip_chs)
        for si, cout in enumerate(reversed(ch)):
            stage = {"res": [], "tx": []}
            first = si == 0
            for _ in range(cfg.layers_per_block + 1):
                cskip = skips.pop()
                stage["res"].append(
                    _resblock_init(next(ks), c + cskip, cout, temb, g))
                c = cout
                if not first:       # mirrors the down stages
                    stage["tx"].append(_spatial_tx_init(
                        next(ks), c, cfg.heads(c),
                        cfg.cross_attention_dim, cfg.tx_depth))
            if si != len(ch) - 1:
                stage["up"] = _conv_init(next(ks), 3, 3, c, c)
            ups.append(stage)
        p["ups"] = ups
        p["gn_out"] = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
        p["conv_out"] = _conv_init(next(ks), 3, 3, c, cfg.out_channels,
                                   scale=1e-3)
        self.params = (jax.tree.map(lambda x: x.astype(dtype), p)
                       if dtype != jnp.float32 else p)
        self._step = jax.jit(self._forward)

    def _forward(self, params, latents, timesteps, context):
        cfg = self.config
        g = cfg.num_groups
        ch0 = cfg.block_out_channels[0]
        temb = timestep_embedding(timesteps, ch0)
        temb = dense(silu(dense(temb.astype(latents.dtype),
                                params["time_mlp"][0])),
                     params["time_mlp"][1])
        h = conv2d(latents, params["conv_in"])
        skips = [h]
        for si, stage in enumerate(params["downs"]):
            for ri, rp in enumerate(stage["res"]):
                h = resblock(h, rp, temb, g)
                if stage["tx"]:
                    h = spatial_transformer(
                        h, stage["tx"][ri],
                        cfg.heads(h.shape[-1]), context, g)
                skips.append(h)
            if "down" in stage:
                h = conv2d(h, stage["down"], stride=2)
                skips.append(h)
        m = params["mid"]
        h = resblock(h, m["res1"], temb, g)
        h = spatial_transformer(h, m["tx"], cfg.heads(h.shape[-1]),
                                context, g)
        h = resblock(h, m["res2"], temb, g)
        for si, stage in enumerate(params["ups"]):
            for ri, rp in enumerate(stage["res"]):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = resblock(h, rp, temb, g)
                if stage["tx"]:
                    h = spatial_transformer(
                        h, stage["tx"][ri],
                        cfg.heads(h.shape[-1]), context, g)
            if "up" in stage:
                B, H, W, C = h.shape
                h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
                h = conv2d(h, stage["up"])
        h = silu(nhwc_group_norm(h, params["gn_out"]["scale"],
                                 params["gn_out"]["bias"], num_groups=g))
        return conv2d(h, params["conv_out"])

    def __call__(self, latents, timesteps, context):
        return self._step(self.params, latents, timesteps, context)


# --------------------------------------------------------------------------
# AutoencoderKL (VAE)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    num_groups: int = 32
    scaling_factor: float = 0.18215


def _vae_attn(x, p, num_groups):
    h = nhwc_group_norm(x, p["gn"]["scale"], p["gn"]["bias"],
                        num_groups=num_groups, eps=1e-6)
    return x + spatial_attention(h, p, num_heads=1)


def _vae_attn_init(key, c):
    p = _attn_params_init(key, c)
    p["gn"] = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
    return p


class AutoencoderKL:
    """VAE: encode images → (mean, logvar) latents; decode latents →
    images.  Mirrors diffusers AutoencoderKL shape (down/up stages of
    resblocks, single-head mid attention), NHWC throughout
    (reference: model_implementations/diffusers/vae.py DSVAE)."""

    def __init__(self, config: VAEConfig = None, seed: int = 0,
                 dtype=jnp.float32):
        cfg = self.config = config or VAEConfig()
        ks = iter(jax.random.split(jax.random.PRNGKey(seed), 256))
        ch = cfg.block_out_channels
        g = cfg.num_groups
        enc: Dict[str, Any] = {
            "conv_in": _conv_init(next(ks), 3, 3, cfg.in_channels, ch[0])}
        c = ch[0]
        stages = []
        for si, cout in enumerate(ch):
            st = {"res": [_resblock_init(next(ks),
                                         c if i == 0 else cout,
                                         cout, None, g)
                          for i in range(cfg.layers_per_block)]}
            c = cout
            if si != len(ch) - 1:
                st["down"] = _conv_init(next(ks), 3, 3, c, c)
            stages.append(st)
        enc["stages"] = stages
        enc["mid"] = {"res1": _resblock_init(next(ks), c, c, None, g),
                      "attn": _vae_attn_init(next(ks), c),
                      "res2": _resblock_init(next(ks), c, c, None, g)}
        enc["gn_out"] = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
        enc["conv_out"] = _conv_init(next(ks), 3, 3, c,
                                     2 * cfg.latent_channels)
        dec: Dict[str, Any] = {
            "conv_in": _conv_init(next(ks), 3, 3, cfg.latent_channels, c),
            "mid": {"res1": _resblock_init(next(ks), c, c, None, g),
                    "attn": _vae_attn_init(next(ks), c),
                    "res2": _resblock_init(next(ks), c, c, None, g)}}
        dstages = []
        for si, cout in enumerate(reversed(ch)):
            st = {"res": [_resblock_init(next(ks),
                                         c if i == 0 else cout,
                                         cout, None, g)
                          for i in range(cfg.layers_per_block + 1)]}
            c = cout
            if si != len(ch) - 1:
                st["up"] = _conv_init(next(ks), 3, 3, c, c)
            dstages.append(st)
        dec["stages"] = dstages
        dec["gn_out"] = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
        dec["conv_out"] = _conv_init(next(ks), 3, 3, c, cfg.in_channels)
        p = {"enc": enc, "dec": dec}
        self.params = (jax.tree.map(lambda x: x.astype(dtype), p)
                       if dtype != jnp.float32 else p)
        self._encode = jax.jit(self._enc_fwd)
        self._decode = jax.jit(self._dec_fwd)

    def _enc_fwd(self, params, images):
        cfg = self.config
        g = cfg.num_groups
        e = params["enc"]
        h = conv2d(images, e["conv_in"])
        for st in e["stages"]:
            for rp in st["res"]:
                h = resblock(h, rp, None, g)
            if "down" in st:
                h = conv2d(h, st["down"], stride=2)
        m = e["mid"]
        h = resblock(h, m["res1"], None, g)
        h = _vae_attn(h, m["attn"], g)
        h = resblock(h, m["res2"], None, g)
        h = silu(nhwc_group_norm(h, e["gn_out"]["scale"],
                                 e["gn_out"]["bias"], num_groups=g))
        h = conv2d(h, e["conv_out"])
        mean, logvar = jnp.split(h, 2, axis=-1)
        return mean, logvar

    def _dec_fwd(self, params, latents):
        cfg = self.config
        g = cfg.num_groups
        d = params["dec"]
        h = conv2d(latents, d["conv_in"])
        m = d["mid"]
        h = resblock(h, m["res1"], None, g)
        h = _vae_attn(h, m["attn"], g)
        h = resblock(h, m["res2"], None, g)
        for st in d["stages"]:
            for rp in st["res"]:
                h = resblock(h, rp, None, g)
            if "up" in st:
                B, H, W, C = h.shape
                h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
                h = conv2d(h, st["up"])
        h = silu(nhwc_group_norm(h, d["gn_out"]["scale"],
                                 d["gn_out"]["bias"], num_groups=g))
        return conv2d(h, d["conv_out"])

    def encode(self, images, rng=None):
        """→ latents [B, H/8, W/8, latent_channels] (sampled when rng
        given, else the mean), scaled by ``scaling_factor``."""
        mean, logvar = self._encode(self.params, images)
        z = mean
        if rng is not None:
            z = mean + jnp.exp(0.5 * logvar) * jax.random.normal(
                rng, mean.shape, mean.dtype)
        return z * self.config.scaling_factor

    def decode(self, latents):
        return self._decode(self.params,
                            latents / self.config.scaling_factor)
