"""Decoder-only transformer, scan-over-layers, TPU-first.

The model-family core behind ``deepspeed_tpu.models.gpt2 / llama``:
a single configurable implementation covering the reference's training
model zoo (megatron-style GPT, llama/llama2/llama3, mistral-ish GQA — the
containers of ``module_inject/containers/`` and
``inference/v2/model_implementations/``) as *config presets* rather than
per-model classes.

TPU-first choices:
* layer params are **stacked** on a leading ``layers`` dim and the block is
  applied with ``lax.scan`` — one compiled layer body regardless of depth
  (fast compiles, natural ``jax.checkpoint`` remat point, and the natural
  unit for pipeline staging later);
* logical axes on every param (see parallel/sharding.py) give Megatron-style
  TP (column-parallel qkv/up, row-parallel out/down) with zero model code;
* attention is pluggable: XLA softmax attention today, Pallas flash /
  Ulysses all-to-all / ring attention slot in via ``attention_fn``.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from . import layers as L


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 50257
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    num_kv_heads: Optional[int] = None        # None => MHA
    d_ff: Optional[int] = None                # None => 4*d_model (or 8/3 gated)
    max_seq_len: int = 1024
    activation: str = "gelu"
    gated_mlp: bool = False                   # SwiGLU-style (llama)
    norm: str = "layernorm"                   # layernorm | rmsnorm
    position: str = "learned"                 # learned | rope | alibi
    rope_theta: float = 10000.0
    rope_pct: float = 1.0                     # partial rotary (phi: 0.4)
    # bloom: layernorm applied to the word embeddings before the stack
    embed_norm: bool = False
    # parallel residual: x + attn(ln(x)) + mlp(ln(x)), one shared norm
    # (falcon, phi, gpt-j)
    parallel_block: bool = False
    # gpt-neox/pythia: parallel residual but TWO norms — the MLP reads
    # ln2(x) instead of the attention's ln1(x)
    parallel_separate_norms: bool = False
    tie_embeddings: bool = True
    attn_bias: bool = True
    # o-projection bias; None follows attn_bias (qwen2: q/k/v biases
    # but NO o bias)
    attn_out_bias: Optional[bool] = None
    mlp_bias: bool = True
    head_bias: bool = False                   # lm_head bias (phi)
    eps: float = 1e-5
    remat: bool = False                       # jax.checkpoint each layer
    remat_policy: str = "nothing"              # nothing|dots|dots_no_batch
    # xla (stock softmax autodiff) | xla_flash (flash-style custom VJP in
    # pure XLA, ops/xla_attention.py) | flash (Pallas kernel)
    attention_impl: str = "xla_flash"
    # layer-scan unroll factor (lax.scan unroll=): >1 trades compile time
    # for removing per-layer dynamic-update-slice traffic on the scan
    # carries (profiled at ~20% of a GPT-2s step on v5e)
    scan_unroll: int = 1
    # gpt-neo: attention WITHOUT the 1/sqrt(d) scaling; None = default
    attn_scale: Optional[float] = None
    # --- MoE (reference: deepspeed/moe; presets: mixtral) ----------------
    num_experts: int = 1                      # >1 => every layer is MoE
    moe_top_k: int = 2
    # qwen2-moe: a dense "shared expert" MLP of this width runs on every
    # token, sigmoid-gated, added to the routed output; None disables
    moe_shared_ff: Optional[int] = None
    # renormalize kept top-k gate weights to sum 1 (mixtral yes;
    # qwen2-moe norm_topk_prob=False keeps raw softmax probabilities)
    moe_norm_topk: bool = True
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0         # inference-time capacity
    min_capacity: int = 4
    noise_policy: Optional[str] = None        # None | Jitter | RSample
    aux_loss_coef: float = 0.01
    # scatter (capacity, EP-shardable) | einsum (GShard dense masks) |
    # ragged (dropless megablox grouped GEMM via lax.ragged_dot)
    moe_dispatch: str = "scatter"

    def __post_init__(self):
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.attn_out_bias is None:
            self.attn_out_bias = self.attn_bias
        if self.d_ff is None:
            if self.gated_mlp:
                # llama sizing: 2/3 * 4d, rounded up to a multiple of 256
                raw = int(8 * self.d_model / 3)
                self.d_ff = 256 * ((raw + 255) // 256)
            else:
                self.d_ff = 4 * self.d_model
        assert self.d_model % self.num_heads == 0
        assert self.num_heads % self.num_kv_heads == 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def rotary_dim(self) -> int:
        """Head dims receiving rotary embedding (even, <= head_dim)."""
        return (int(self.head_dim * self.rope_pct) // 2) * 2


REMAT_POLICIES = {
    "nothing": None,
    "dots": lambda: jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch": lambda: jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "everything": lambda: jax.checkpoint_policies.nothing_saveable,
    # save flash-attention outputs (its VJP self-recomputes) + non-batch dots
    "flash": lambda: jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        jax.checkpoint_policies.save_only_these_names("flash_out")),
    # save the xla_flash VJP residuals (attention output + per-row lse) so
    # a checkpointed layer's backward re-enters the custom VJP instead of
    # replaying the forward softmax
    "xla_flash": lambda: jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_lse")),
}


# --------------------------------------------------------------------------
# init
# --------------------------------------------------------------------------

def init_params(cfg: TransformerConfig, key) -> Tuple[Dict, Dict]:
    """Returns (params, logical_axes).  Per-layer params are stacked on a
    leading 'layers' dimension (scan layout)."""
    keys = jax.random.split(key, 9)
    H, D, Hkv = cfg.num_heads, cfg.head_dim, cfg.num_kv_heads
    dm, dff, nl = cfg.d_model, cfg.d_ff, cfg.num_layers
    out_scale = 1.0 / math.sqrt(dm) / math.sqrt(2.0 * nl)   # GPT-2 depth scaling

    def stack_init(fn, key, *args, **kw):
        """Init one layer's worth with per-layer keys, stacked on dim 0."""
        ks = jax.random.split(key, nl)
        outs = [fn(k, *args, **kw) for k in ks]
        p0, a0 = outs[0]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *[o[0] for o in outs])
        axes = jax.tree.map(lambda ax: ("layers",) + ax, a0,
                            is_leaf=lambda x: isinstance(x, tuple) and
                            all(e is None or isinstance(e, str) for e in x))
        return stacked, axes

    params: Dict[str, Any] = {}
    axes: Dict[str, Any] = {}

    params["embed"], axes["embed"] = L.embedding_init(keys[0], cfg.vocab_size, dm)
    if cfg.position == "learned":
        params["pos_embed"], axes["pos_embed"] = (
            {"table": jax.random.normal(keys[1], (cfg.max_seq_len, dm)) * 0.01},
            {"table": (None, "embed")})
    if cfg.embed_norm:                      # bloom word_embeddings_layernorm
        _ninit = (L.layernorm_init if cfg.norm == "layernorm"
                  else L.rmsnorm_init)
        params["ln_embed"], axes["ln_embed"] = _ninit(dm)

    blk_p: Dict[str, Any] = {}
    blk_a: Dict[str, Any] = {}

    # attention — fused qkv as separate heads-aware tensors
    def qkv_init(k):
        k1, k2, k3, k4 = jax.random.split(k, 4)
        p, a = {}, {}
        p["wq"] = jax.random.normal(k1, (dm, H, D)) / math.sqrt(dm)
        a["wq"] = ("embed", "heads", "head_dim")
        p["wk"] = jax.random.normal(k2, (dm, Hkv, D)) / math.sqrt(dm)
        a["wk"] = ("embed", "kv_heads", "head_dim")
        p["wv"] = jax.random.normal(k3, (dm, Hkv, D)) / math.sqrt(dm)
        a["wv"] = ("embed", "kv_heads", "head_dim")
        p["wo"] = jax.random.normal(k4, (H, D, dm)) * out_scale
        a["wo"] = ("heads", "head_dim", "embed")
        if cfg.attn_bias:
            p["bq"] = jnp.zeros((H, D)); a["bq"] = ("heads", "head_dim")
            p["bk"] = jnp.zeros((Hkv, D)); a["bk"] = ("kv_heads", "head_dim")
            p["bv"] = jnp.zeros((Hkv, D)); a["bv"] = ("kv_heads", "head_dim")
        if cfg.attn_out_bias:
            p["bo"] = jnp.zeros((dm,)); a["bo"] = ("embed",)
        return p, a

    blk_p["attn"], blk_a["attn"] = stack_init(qkv_init, keys[2])

    if cfg.num_experts > 1:
        from ..parallel import moe as M

        blk_p["gate"], blk_a["gate"] = stack_init(
            lambda k: M.gate_init(k, dm, cfg.num_experts), keys[7])
        blk_p["experts"], blk_a["experts"] = stack_init(
            lambda k: M.experts_init(k, cfg.num_experts, dm, dff,
                                     gated=cfg.gated_mlp,
                                     out_scale=out_scale), keys[3])
        if cfg.moe_shared_ff:        # qwen2-moe dense shared expert
            sff = cfg.moe_shared_ff

            def shared_init(k):
                k1, k2, k3, k4 = jax.random.split(k, 4)
                p = {"wi": jax.random.normal(k1, (dm, sff))
                     / math.sqrt(dm),
                     "wo": jax.random.normal(k2, (sff, dm)) * out_scale}
                a = {"wi": ("embed", "mlp"), "wo": ("mlp", "embed")}
                if cfg.gated_mlp:
                    p["wg"] = jax.random.normal(k3, (dm, sff)) \
                        / math.sqrt(dm)
                    a["wg"] = ("embed", "mlp")
                p["gate"] = jax.random.normal(k4, (dm, 1)) / math.sqrt(dm)
                a["gate"] = ("embed", None)
                return p, a

            blk_p["shared"], blk_a["shared"] = stack_init(
                shared_init, keys[8])

    def mlp_init(k):
        k1, k2, k3 = jax.random.split(k, 3)
        p, a = {}, {}
        p["wi"] = jax.random.normal(k1, (dm, dff)) / math.sqrt(dm)
        a["wi"] = ("embed", "mlp")
        if cfg.gated_mlp:
            p["wg"] = jax.random.normal(k3, (dm, dff)) / math.sqrt(dm)
            a["wg"] = ("embed", "mlp")
        p["wo"] = jax.random.normal(k2, (dff, dm)) * out_scale
        a["wo"] = ("mlp", "embed")
        if cfg.mlp_bias:
            p["bi"] = jnp.zeros((dff,)); a["bi"] = ("mlp",)
            p["bo"] = jnp.zeros((dm,)); a["bo"] = ("embed",)
        return p, a

    if cfg.num_experts <= 1:
        blk_p["mlp"], blk_a["mlp"] = stack_init(mlp_init, keys[3])

    norm_init = L.layernorm_init if cfg.norm == "layernorm" else L.rmsnorm_init
    blk_p["ln1"], blk_a["ln1"] = stack_init(
        lambda k: norm_init(dm), keys[4])
    if not cfg.parallel_block or cfg.parallel_separate_norms:
        blk_p["ln2"], blk_a["ln2"] = stack_init(
            lambda k: norm_init(dm), keys[5])

    params["blocks"] = blk_p
    axes["blocks"] = blk_a

    params["ln_f"], axes["ln_f"] = norm_init(dm)
    if not cfg.tie_embeddings:
        params["lm_head"], axes["lm_head"] = (
            {"kernel": jax.random.normal(keys[6], (dm, cfg.vocab_size))
             / math.sqrt(dm)},
            {"kernel": ("embed", "vocab")})
        if cfg.head_bias:
            params["lm_head"]["bias"] = jnp.zeros((cfg.vocab_size,))
            axes["lm_head"]["bias"] = ("vocab",)
    return params, axes


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _norm(cfg):
    fn = L.layernorm if cfg.norm == "layernorm" else L.rmsnorm
    return partial(fn, eps=cfg.eps)


def _shared_expert(sp, h, act, gated: bool):
    """qwen2-moe dense shared expert: a full MLP on every token, scaled
    by a per-token sigmoid gate (reference analog: the qwen_v2_moe v2
    model implementation's shared_expert path)."""
    dt = h.dtype
    u = h @ sp["wi"].astype(dt)
    u = act(h @ sp["wg"].astype(dt)) * u if "wg" in sp else act(u)
    d = u @ sp["wo"].astype(dt)
    g = jax.nn.sigmoid((h @ sp["gate"].astype(dt)).astype(jnp.float32))
    return d * g.astype(dt)


def block_apply(cfg: TransformerConfig, lp, x, cos, sin,
                mask=None, attention_fn: Callable = L.causal_attention,
                rng=None, positions=None):
    """One decoder layer. lp: this layer's (unstacked) params.
    x: [B, S, dm].  ``positions``: optional [B, S] original token
    positions (random-LTD gathered subsequences keep their rotary
    phases).  Returns (x, metrics) — metrics non-empty for MoE."""
    norm = _norm(cfg)
    act = L.ACTIVATIONS[cfg.activation]
    ap = lp["attn"]
    if cfg.attn_scale is not None and attention_fn is L.causal_attention:
        # safety net for call sites that never resolved attention_fn
        # (pipeline stage bodies, streamed sweeps): gpt-neo's unscaled
        # attention must not silently regain the 1/sqrt(d) factor
        attention_fn = partial(L.causal_attention, scale=cfg.attn_scale)

    h = norm(lp["ln1"], x)
    dt = x.dtype
    q = jnp.einsum("bsd,dhk->bshk", h, ap["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", h, ap["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", h, ap["wv"].astype(dt))
    if cfg.attn_bias:
        q = q + ap["bq"].astype(dt)
        k = k + ap["bk"].astype(dt)
        v = v + ap["bv"].astype(dt)
    if cfg.position == "rope":
        q = L.apply_rope(q, cos, sin, positions=positions)
        k = L.apply_rope(k, cos, sin, positions=positions)
    o = attention_fn(q, k, v, mask=mask)
    o = jnp.einsum("bshk,hkd->bsd", o, ap["wo"].astype(dt))
    if cfg.attn_out_bias:
        o = o + ap["bo"].astype(dt)

    if not cfg.parallel_block:
        x = x + o
        h = norm(lp["ln2"], x)
    elif cfg.parallel_separate_norms:
        # gpt-neox: the MLP reads its own norm of the ORIGINAL x
        h = norm(lp["ln2"], x)
    # parallel residual (falcon/phi): the MLP reads the same ln1 output
    metrics: Dict[str, Any] = {}
    if cfg.num_experts > 1:
        from ..parallel import moe as M

        d, metrics = M.moe_ffn(
            lp["gate"], lp["experts"], h, top_k=cfg.moe_top_k,
            capacity_factor=cfg.capacity_factor,
            min_capacity=cfg.min_capacity, activation=act,
            gated=cfg.gated_mlp, rng=rng, noise_policy=cfg.noise_policy,
            dispatch_mode=cfg.moe_dispatch,
            norm_topk=cfg.moe_norm_topk)
        if "shared" in lp:       # qwen2-moe sigmoid-gated shared expert
            d = d + _shared_expert(lp["shared"], h, act, cfg.gated_mlp)
    else:
        mp = lp["mlp"]
        u = h @ mp["wi"].astype(dt)
        if cfg.mlp_bias:
            u = u + mp["bi"].astype(dt)
        if cfg.gated_mlp:
            u = act(h @ mp["wg"].astype(dt)) * u
        else:
            u = act(u)
        d = u @ mp["wo"].astype(dt)
        if cfg.mlp_bias:
            d = d + mp["bo"].astype(dt)
    if cfg.parallel_block:
        return x + o + d, metrics
    return x + d, metrics


def apply(cfg: TransformerConfig, params, input_ids, mask=None,
          attention_fn: Callable = L.causal_attention,
          dtype=None, rng=None, with_aux: bool = False,
          pld_theta=None, ltd_keep: Optional[int] = None):
    """Forward pass → logits [B, S, vocab] (or (logits, aux) with
    with_aux=True; aux carries MoE load-balancing metrics averaged over
    layers).

    ``pld_theta``: progressive-layer-drop theta (traced scalar; layer i
    is dropped whole-batch with prob (i/L)(1-theta) — reference:
    progressive_layer_drop.py consumed by the BERT forward).
    ``ltd_keep``: random-LTD kept-token count (STATIC int — one compiled
    program per value): a sorted random subset of positions runs through
    the layer stack, dropped positions bypass with their embedding
    (reference: data_routing/basic_layer.py gather/scatter)."""
    dt = dtype or params["embed"]["table"].dtype
    x = L.embed(params["embed"], input_ids).astype(dt)
    if cfg.embed_norm:
        x = _norm(cfg)(params["ln_embed"], x)
    if cfg.position == "learned":
        S = input_ids.shape[1]
        x = x + params["pos_embed"]["table"][:S].astype(dt)
        cos = sin = None
    elif cfg.position == "alibi":
        cos = sin = None
        # safety net for direct apply() calls: the default eager
        # attention gains the ALiBi bias (Model wraps attention_fn too)
        if attention_fn is L.causal_attention:
            attention_fn = L.make_alibi_attention()
    else:
        cos, sin = L.rope_freqs(cfg.rotary_dim, cfg.max_seq_len, cfg.rope_theta)

    have_rng = rng is not None
    if (pld_theta is not None or ltd_keep is not None) and not have_rng:
        raise ValueError("pld_theta / ltd_keep need a training rng")

    positions = None
    full_x = None
    idx = None
    if ltd_keep is not None and ltd_keep < x.shape[1]:
        from ..runtime.data_pipeline import (random_ltd_scatter,
                                             random_ltd_select)
        rng, sel_rng = jax.random.split(rng)
        full_x = x
        x, idx = random_ltd_select(x, ltd_keep, sel_rng)
        positions = idx
        if mask is not None:
            mask = jnp.take_along_axis(mask, idx, axis=1)

    layer_rngs = (jax.random.split(rng, cfg.num_layers) if have_rng
                  else jnp.zeros((cfg.num_layers, 2), jnp.uint32))

    def body(h, xs):
        lp, r, li = xs
        y, metrics = block_apply(cfg, lp, h, cos, sin, mask=mask,
                                 attention_fn=attention_fn,
                                 rng=r if have_rng else None,
                                 positions=positions)
        if pld_theta is not None:
            # whole-batch per-layer coin; deeper layers drop more
            keep_p = 1.0 - (li.astype(jnp.float32) / cfg.num_layers) \
                * (1.0 - pld_theta)
            drop = jax.random.bernoulli(
                jax.random.fold_in(r, 1), 1.0 - keep_p)
            y = jnp.where(drop, h, y)
        return y, metrics

    if cfg.remat:
        policy = REMAT_POLICIES[cfg.remat_policy]
        body = jax.checkpoint(body, policy=policy() if policy else None)

    x, metrics = jax.lax.scan(
        body, x,
        (params["blocks"], layer_rngs,
         jnp.arange(cfg.num_layers, dtype=jnp.int32)),
        unroll=min(cfg.scan_unroll, cfg.num_layers))
    if idx is not None:
        # dropped positions bypass the stack with their embedding
        x = random_ltd_scatter(full_x, x, idx)
    x = _norm(cfg)(params["ln_f"], x)
    if cfg.tie_embeddings:
        logits = x @ params["embed"]["table"].astype(dt).T
    else:
        logits = x @ params["lm_head"]["kernel"].astype(dt)
        if cfg.head_bias:
            logits = logits + params["lm_head"]["bias"].astype(dt)
    if with_aux:
        aux = {k: v.mean() for k, v in metrics.items()} if metrics else {}
        return logits, aux
    return logits


def rolled_lm_targets(ids, mask=None):
    """Next-token targets by rolling left with the final position masked —
    equivalent to the shift-by-one convention but length-preserving, so it
    divides evenly under sequence/pipeline sharding.  Returns
    (labels, target_mask)."""
    labels = jnp.roll(ids, -1, axis=1)
    S = ids.shape[1]
    tgt_mask = jnp.broadcast_to(
        (jnp.arange(S) < S - 1).astype(jnp.float32)[None, :], ids.shape)
    if mask is not None:
        tgt_mask = tgt_mask * jnp.roll(mask, -1, axis=1)
    return labels, tgt_mask


def cross_entropy_loss(logits, labels, mask=None):
    """Next-token LM loss; logits [B,S,V], labels [B,S].

    Written as ``lse - target_logit`` with fp32 *reductions* rather than
    ``log_softmax`` so XLA fuses the bf16→fp32 convert into the reduce and
    never materializes an fp32 [B,S,V] buffer (6.6 GB for GPT-2 vocab at
    batch 32·1024 — the difference between fitting in HBM or not)."""
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - tgt.astype(jnp.float32)
    if mask is not None:
        mask = mask.astype(jnp.float32)
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return nll.mean()


def lm_loss_fn(cfg: TransformerConfig,
               attention_fn: Callable = L.causal_attention,
               pld: bool = False, ltd_keep: Optional[int] = None):
    """Standard causal-LM loss over a batch {input_ids, [attention_mask]}.

    ``pld``: consume the engine-injected per-row ``_pld_theta`` column
    (progressive layer drop).  ``ltd_keep``: bake a static random-LTD
    kept-token count; the engine swaps programs via ``with_ltd`` as the
    schedule anneals."""

    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        mask = batch.get("attention_mask")
        theta = batch["_pld_theta"][0] if pld else None
        logits, aux = apply(cfg, params, ids, mask=mask,
                            attention_fn=attention_fn, rng=rng,
                            with_aux=True, pld_theta=theta,
                            ltd_keep=ltd_keep)
        labels, tgt_mask = rolled_lm_targets(ids, mask)
        loss = cross_entropy_loss(logits, labels, tgt_mask)
        if "moe_aux_loss" in aux:
            loss = loss + cfg.aux_loss_coef * aux["moe_aux_loss"]
            return loss, aux
        return loss

    loss_fn.uses_pld = pld
    loss_fn.with_ltd = lambda keep: lm_loss_fn(
        cfg, attention_fn, pld=pld, ltd_keep=keep)
    if pld or ltd_keep is not None:
        # evaluation must run the clean forward: no theta column in eval
        # batches, no token dropping skewing eval losses
        loss_fn.base_eval = lm_loss_fn(cfg, attention_fn)
    return loss_fn


def _resolve_attention(cfg: TransformerConfig) -> Callable:
    """attention_impl -> callable; ALiBi wraps the eager attention with
    the per-head bias (the flash kernels have no bias operand)."""
    if cfg.attn_scale is not None and cfg.attention_impl in (
            "flash", "xla_flash"):
        raise ValueError(
            "attn_scale needs the eager attention (attention_impl="
            "'xla'): the flash kernels bake in 1/sqrt(d)")
    if cfg.position == "alibi":
        if cfg.attention_impl in ("flash", "xla_flash"):
            raise ValueError(
                "position='alibi' needs the eager attention "
                "(attention_impl='xla'): the flash kernels carry no "
                "additive-bias operand")
        fn = L.make_alibi_attention()
    elif cfg.attention_impl == "flash":
        from ..ops.flash_attention import flash_attention
        return flash_attention
    elif cfg.attention_impl == "xla_flash":
        from ..ops.xla_attention import fused_attention
        return fused_attention
    else:
        fn = L.causal_attention
    if cfg.attn_scale is not None:
        base = fn
        s = cfg.attn_scale

        def fn(q, k, v, mask=None, **kw):        # gpt-neo: no 1/sqrt(d)
            return base(q, k, v, mask=mask, scale=s, **kw)
    return fn


class Model:
    """Bundles config+params+loss for ``deepspeed_tpu.initialize(model=…)``."""

    def __init__(self, cfg: TransformerConfig, seed: int = 0,
                 attention_fn: Optional[Callable] = None):
        self.config = cfg
        if attention_fn is None:
            attention_fn = _resolve_attention(cfg)
        self.params, self.param_axes = init_params(cfg, jax.random.PRNGKey(seed))
        self.loss_fn = lm_loss_fn(cfg, attention_fn)
        self.attention_fn = attention_fn

    def apply(self, params, input_ids, **kw):
        kw.setdefault("attention_fn", self.attention_fn)
        return apply(self.config, params, input_ids, **kw)

    @classmethod
    def from_params(cls, cfg: TransformerConfig, params,
                    param_axes=None,
                    attention_fn: Optional[Callable] = None) -> "Model":
        """Build a Model around EXISTING parameters without running the
        initializer (big-model flows: pre-quantized serving trees,
        host-loaded checkpoints — the 16 GB+ random init would otherwise
        dominate or OOM)."""
        m = cls.__new__(cls)
        m.config = cfg
        if attention_fn is None:
            attention_fn = _resolve_attention(cfg)
        m.params = params
        if param_axes is None:
            from ..parallel.sharding import infer_logical_axes
            param_axes = infer_logical_axes(params)
        m.param_axes = param_axes
        m.loss_fn = lm_loss_fn(cfg, attention_fn)
        m.attention_fn = attention_fn
        return m
