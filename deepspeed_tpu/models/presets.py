"""Model-family presets (the reference's model zoo as configs).

Covers the families the reference injects/implements (SURVEY §2.6:
gpt2/neo/neox/j, llama/llama2/llama3, mistral, opt, qwen2 — containers in
``module_inject/containers/`` and ``inference/v2/model_implementations/``)
as :class:`TransformerConfig` presets for the single transformer core.
"""

from __future__ import annotations

from typing import Dict

from .transformer import Model, TransformerConfig

PRESETS: Dict[str, dict] = {
    # --- GPT-2 family ---------------------------------------------------
    "gpt2": dict(vocab_size=50257, num_layers=12, d_model=768, num_heads=12,
                 max_seq_len=1024, activation="gelu_new", norm="layernorm",
                 position="learned", tie_embeddings=True),
    "gpt2-medium": dict(vocab_size=50257, num_layers=24, d_model=1024,
                        num_heads=16, max_seq_len=1024,
                        activation="gelu_new", position="learned"),
    "gpt2-large": dict(vocab_size=50257, num_layers=36, d_model=1280,
                       num_heads=20, max_seq_len=1024,
                       activation="gelu_new", position="learned"),
    "gpt2-xl": dict(vocab_size=50257, num_layers=48, d_model=1600,
                    num_heads=25, max_seq_len=1024,
                    activation="gelu_new", position="learned"),
    # --- Llama family ---------------------------------------------------
    "llama-tiny": dict(vocab_size=32000, num_layers=4, d_model=256,
                       num_heads=8, num_kv_heads=4, d_ff=688,
                       max_seq_len=2048, activation="silu", gated_mlp=True,
                       norm="rmsnorm", position="rope", tie_embeddings=False,
                       attn_bias=False, mlp_bias=False, eps=1e-5),
    "llama2-7b": dict(vocab_size=32000, num_layers=32, d_model=4096,
                      num_heads=32, d_ff=11008, max_seq_len=4096,
                      activation="silu", gated_mlp=True, norm="rmsnorm",
                      position="rope", tie_embeddings=False,
                      attn_bias=False, mlp_bias=False),
    "llama3-8b": dict(vocab_size=128256, num_layers=32, d_model=4096,
                      num_heads=32, num_kv_heads=8, d_ff=14336,
                      max_seq_len=8192, activation="silu", gated_mlp=True,
                      norm="rmsnorm", position="rope", rope_theta=500000.0,
                      tie_embeddings=False, attn_bias=False, mlp_bias=False),
    "llama3-70b": dict(vocab_size=128256, num_layers=80, d_model=8192,
                       num_heads=64, num_kv_heads=8, d_ff=28672,
                       max_seq_len=8192, activation="silu", gated_mlp=True,
                       norm="rmsnorm", position="rope", rope_theta=500000.0,
                       tie_embeddings=False, attn_bias=False, mlp_bias=False),
    # --- Qwen2 (llama layout + qkv biases, no o bias) --------------------
    "qwen2-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                       num_heads=8, num_kv_heads=4, d_ff=688,
                       max_seq_len=2048, activation="silu", gated_mlp=True,
                       norm="rmsnorm", position="rope",
                       rope_theta=1000000.0, tie_embeddings=False,
                       attn_bias=True, attn_out_bias=False,
                       mlp_bias=False, eps=1e-6),
    "qwen2-7b": dict(vocab_size=152064, num_layers=28, d_model=3584,
                     num_heads=28, num_kv_heads=4, d_ff=18944,
                     max_seq_len=32768, activation="silu", gated_mlp=True,
                     norm="rmsnorm", position="rope",
                     rope_theta=1000000.0, tie_embeddings=False,
                     attn_bias=True, attn_out_bias=False,
                     mlp_bias=False, eps=1e-6),
    # --- GPT-J (partial rotary + parallel residual, single shared LN) -----
    "gptj-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                      num_heads=8, max_seq_len=2048, activation="gelu_new",
                      norm="layernorm", position="rope", rope_pct=0.25,
                      parallel_block=True, tie_embeddings=False,
                      attn_bias=False, mlp_bias=True, head_bias=True),
    "gptj-6b": dict(vocab_size=50400, num_layers=28, d_model=4096,
                    num_heads=16, max_seq_len=2048, activation="gelu_new",
                    norm="layernorm", position="rope", rope_pct=0.25,
                    parallel_block=True, tie_embeddings=False,
                    attn_bias=False, mlp_bias=True, head_bias=True),
    # --- GPT-NeoX / Pythia (parallel residual, SEPARATE norms) ------------
    "gpt-neox-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                          num_heads=8, max_seq_len=2048,
                          activation="gelu", norm="layernorm",
                          position="rope", rope_pct=0.25,
                          parallel_block=True, parallel_separate_norms=True,
                          tie_embeddings=False, attn_bias=True,
                          mlp_bias=True),
    "pythia-1.4b": dict(vocab_size=50304, num_layers=24, d_model=2048,
                        num_heads=16, max_seq_len=2048,
                        activation="gelu", norm="layernorm",
                        position="rope", rope_pct=0.25,
                        parallel_block=True, parallel_separate_norms=True,
                        tie_embeddings=False, attn_bias=True,
                        mlp_bias=True),
    # --- Mistral (GQA + high theta) --------------------------------------
    "mistral-7b": dict(vocab_size=32000, num_layers=32, d_model=4096,
                       num_heads=32, num_kv_heads=8, d_ff=14336,
                       max_seq_len=8192, activation="silu", gated_mlp=True,
                       norm="rmsnorm", position="rope", rope_theta=1000000.0,
                       tie_embeddings=False, attn_bias=False, mlp_bias=False),
    # --- Mixtral (MoE, reference: v2 model_implementations/mixtral) -------
    "mixtral-tiny": dict(vocab_size=32000, num_layers=4, d_model=256,
                         num_heads=8, num_kv_heads=4, d_ff=512,
                         max_seq_len=2048, activation="silu", gated_mlp=True,
                         norm="rmsnorm", position="rope",
                         tie_embeddings=False, attn_bias=False,
                         mlp_bias=False, num_experts=8, moe_top_k=2),
    "mixtral-8x7b": dict(vocab_size=32000, num_layers=32, d_model=4096,
                         num_heads=32, num_kv_heads=8, d_ff=14336,
                         max_seq_len=8192, activation="silu", gated_mlp=True,
                         norm="rmsnorm", position="rope",
                         rope_theta=1000000.0, tie_embeddings=False,
                         attn_bias=False, mlp_bias=False,
                         num_experts=8, moe_top_k=2),
    # --- Falcon (MQA + parallel residual, reference: containers/falcon) --
    "falcon-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                        num_heads=8, num_kv_heads=1, max_seq_len=2048,
                        activation="gelu", norm="layernorm",
                        position="rope", parallel_block=True,
                        tie_embeddings=True, attn_bias=False,
                        mlp_bias=False),
    "falcon-7b": dict(vocab_size=65024, num_layers=32, d_model=4544,
                      num_heads=71, num_kv_heads=1, max_seq_len=2048,
                      activation="gelu", norm="layernorm", position="rope",
                      parallel_block=True, tie_embeddings=True,
                      attn_bias=False, mlp_bias=False),
    # --- Phi (partial rotary + parallel residual + biased head) ----------
    "phi-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                     num_heads=8, max_seq_len=2048, activation="gelu_new",
                     norm="layernorm", position="rope", rope_pct=0.4,
                     parallel_block=True, tie_embeddings=False,
                     attn_bias=True, mlp_bias=True, head_bias=True),
    "phi-2": dict(vocab_size=51200, num_layers=32, d_model=2560,
                  num_heads=32, max_seq_len=2048, activation="gelu_new",
                  norm="layernorm", position="rope", rope_pct=0.4,
                  parallel_block=True, tie_embeddings=False,
                  attn_bias=True, mlp_bias=True, head_bias=True),
    # --- BLOOM (ALiBi + word-embedding layernorm; reference container:
    # module_inject/containers/bloom.py) ---------------------------------
    "bloom-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                       num_heads=8, max_seq_len=2048,
                       activation="gelu_new", norm="layernorm",
                       position="alibi", embed_norm=True,
                       tie_embeddings=True, attn_bias=True,
                       mlp_bias=True, attention_impl="xla"),
    "bloom-560m": dict(vocab_size=250880, num_layers=24, d_model=1024,
                       num_heads=16, max_seq_len=2048,
                       activation="gelu_new", norm="layernorm",
                       position="alibi", embed_norm=True,
                       tie_embeddings=True, attn_bias=True,
                       mlp_bias=True, attention_impl="xla"),
    "bloom-7b1": dict(vocab_size=250880, num_layers=30, d_model=4096,
                      num_heads=32, max_seq_len=2048,
                      activation="gelu_new", norm="layernorm",
                      position="alibi", embed_norm=True,
                      tie_embeddings=True, attn_bias=True,
                      mlp_bias=True, attention_impl="xla"),
    # --- OPT ------------------------------------------------------------
    "opt-125m": dict(vocab_size=50272, num_layers=12, d_model=768,
                     num_heads=12, max_seq_len=2048, activation="relu",
                     norm="layernorm", position="learned"),
    # --- Phi-3 (llama-ish: rmsnorm + gated silu, fused qkv/gate_up in
    # the HF checkpoint — reference: inference/v2/model_implementations/
    # phi3/policy.py) -----------------------------------------------------
    "phi3-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                      num_heads=8, d_ff=512, max_seq_len=2048,
                      activation="silu", gated_mlp=True, norm="rmsnorm",
                      position="rope", tie_embeddings=False,
                      attn_bias=False, mlp_bias=False, eps=1e-5),
    "phi3-mini": dict(vocab_size=32064, num_layers=32, d_model=3072,
                      num_heads=32, d_ff=8192, max_seq_len=4096,
                      activation="silu", gated_mlp=True, norm="rmsnorm",
                      position="rope", tie_embeddings=False,
                      attn_bias=False, mlp_bias=False, eps=1e-5),
    # --- InternLM (llama layout + q/k/v/o biases — reference:
    # module_inject/containers/internlm.py) -------------------------------
    "internlm-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                          num_heads=8, d_ff=688, max_seq_len=2048,
                          activation="silu", gated_mlp=True,
                          norm="rmsnorm", position="rope",
                          tie_embeddings=False, attn_bias=True,
                          attn_out_bias=True, mlp_bias=False, eps=1e-6),
    "internlm-7b": dict(vocab_size=103168, num_layers=32, d_model=4096,
                        num_heads=32, d_ff=11008, max_seq_len=2048,
                        activation="silu", gated_mlp=True, norm="rmsnorm",
                        position="rope", tie_embeddings=False,
                        attn_bias=True, attn_out_bias=True,
                        mlp_bias=False, eps=1e-6),
    # --- GPT-Neo (learned positions, UNSCALED attention, no qkv biases —
    # reference: module_inject/containers/gptneo.py.  Like the reference
    # injection kernels, the alternating 256-token local-attention
    # windows serve as dense causal attention) ----------------------------
    "gpt-neo-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                         num_heads=8, max_seq_len=2048,
                         activation="gelu_new", norm="layernorm",
                         position="learned", tie_embeddings=True,
                         attn_bias=False, attn_out_bias=True,
                         mlp_bias=True, attn_scale=1.0,
                         attention_impl="xla"),
    "gpt-neo-1.3b": dict(vocab_size=50257, num_layers=24, d_model=2048,
                         num_heads=16, max_seq_len=2048,
                         activation="gelu_new", norm="layernorm",
                         position="learned", tie_embeddings=True,
                         attn_bias=False, attn_out_bias=True,
                         mlp_bias=True, attn_scale=1.0,
                         attention_impl="xla"),
    # --- Qwen2-MoE (sparse experts + sigmoid-gated dense shared expert,
    # raw softmax top-k probs — reference: inference/v2/
    # model_implementations/qwen_v2_moe/model.py) -------------------------
    "qwen2-moe-tiny": dict(vocab_size=1024, num_layers=4, d_model=256,
                           num_heads=8, num_kv_heads=4, d_ff=352,
                           max_seq_len=2048, activation="silu",
                           gated_mlp=True, norm="rmsnorm",
                           position="rope", rope_theta=1000000.0,
                           tie_embeddings=False, attn_bias=True,
                           attn_out_bias=False, mlp_bias=False,
                           eps=1e-6, num_experts=4, moe_top_k=2,
                           moe_shared_ff=704, moe_norm_topk=False),
    "qwen2-moe-a2.7b": dict(vocab_size=151936, num_layers=24,
                            d_model=2048, num_heads=16, num_kv_heads=16,
                            d_ff=1408, max_seq_len=8192,
                            activation="silu", gated_mlp=True,
                            norm="rmsnorm", position="rope",
                            rope_theta=1000000.0, tie_embeddings=False,
                            attn_bias=True, attn_out_bias=False,
                            mlp_bias=False, eps=1e-6, num_experts=60,
                            moe_top_k=4, moe_shared_ff=5632,
                            moe_norm_topk=False),
    # --- Megatron-GPT (gpt2 architecture, megatron-lm checkpoint naming
    # with per-head-interleaved fused QKV — reference:
    # module_inject/containers/megatron_gpt.py) ---------------------------
    "megatron-gpt2-345m": dict(vocab_size=50304, num_layers=24,
                               d_model=1024, num_heads=16,
                               max_seq_len=1024, activation="gelu_new",
                               norm="layernorm", position="learned",
                               tie_embeddings=True),
}


def build_config(name: str, **overrides) -> TransformerConfig:
    if name not in PRESETS:
        raise ValueError(f"Unknown model preset {name!r}; "
                         f"known: {sorted(PRESETS)}")
    kw = dict(PRESETS[name])
    kw.update(overrides)
    return TransformerConfig(**kw)


def build_model(name: str, seed: int = 0, **overrides) -> Model:
    return Model(build_config(name, **overrides), seed=seed)
