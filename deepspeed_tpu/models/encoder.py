"""BERT-class bidirectional encoder (embedding / rerank serving class).

TPU-native analog of the reference's encoder serving support
(``module_inject/containers/bert.py:13``, ``distil_bert.py`` — policy
injection into HF BertLayer; here a scan-layout post-LN encoder core of
its own, because the decoder core in ``models/transformer.py`` is
pre-LN and causal by construction).

Architecture (BERT): word + position + token-type embeddings → LayerNorm
→ N × [x = LN(x + Attn(x)); x = LN(x + MLP(x))] (post-LN, bidirectional
with a padding mask) → optional tanh pooler over [CLS].

Serving is batch-stateless (no KV cache): :meth:`Encoder.encode_batch`
pads requests into power-of-two sequence buckets so the compiled-program
count stays O(log max_len), the encoder analog of the decoder engine's
context buckets.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import layers as L


@dataclasses.dataclass
class EncoderConfig:
    vocab_size: int
    d_model: int = 768
    num_layers: int = 12
    num_heads: int = 12
    d_ff: Optional[int] = None            # None => 4*d_model
    max_seq_len: int = 512
    type_vocab_size: int = 2
    activation: str = "gelu"
    eps: float = 1e-12                    # BERT's LayerNorm eps
    pooler: bool = True                   # tanh pooler over [CLS]

    def __post_init__(self):
        if self.d_ff is None:
            self.d_ff = 4 * self.d_model
        assert self.d_model % self.num_heads == 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


def init_params(cfg: EncoderConfig, key) -> Tuple[Dict, Dict]:
    """(params, logical-axis tree) — same axis vocabulary as the decoder
    core so ``parallel/sharding.py`` TP rules apply unchanged."""
    dm, H, D, dff = cfg.d_model, cfg.num_heads, cfg.head_dim, cfg.d_ff
    keys = jax.random.split(key, 8)
    norm_init = lambda: L.layernorm_init(dm)    # noqa: E731

    params: Dict[str, Any] = {}
    axes: Dict[str, Any] = {}
    params["embed"], axes["embed"] = L.embedding_init(
        keys[0], cfg.vocab_size, dm)
    params["pos_embed"] = {"table": jax.random.normal(
        keys[1], (cfg.max_seq_len, dm)) * 0.01}
    axes["pos_embed"] = {"table": (None, "embed")}
    if cfg.type_vocab_size > 0:           # distilbert: no segment embeds
        params["type_embed"] = {"table": jax.random.normal(
            keys[2], (cfg.type_vocab_size, dm)) * 0.01}
        axes["type_embed"] = {"table": (None, "embed")}
    params["ln_embed"], axes["ln_embed"] = norm_init()

    def layer_init(k):
        k1, k2, k3, k4, k5, k6 = jax.random.split(k, 6)
        p: Dict[str, Any] = {"attn": {}, "mlp": {}}
        a: Dict[str, Any] = {"attn": {}, "mlp": {}}
        ap, aa = p["attn"], a["attn"]
        ap["wq"] = jax.random.normal(k1, (dm, H, D)) / math.sqrt(dm)
        aa["wq"] = ("embed", "heads", "head_dim")
        ap["wk"] = jax.random.normal(k2, (dm, H, D)) / math.sqrt(dm)
        aa["wk"] = ("embed", "kv_heads", "head_dim")
        ap["wv"] = jax.random.normal(k3, (dm, H, D)) / math.sqrt(dm)
        aa["wv"] = ("embed", "kv_heads", "head_dim")
        ap["wo"] = jax.random.normal(k4, (H, D, dm)) / math.sqrt(dm)
        aa["wo"] = ("heads", "head_dim", "embed")
        for n, shp, ax in (("bq", (H, D), ("heads", "head_dim")),
                           ("bk", (H, D), ("kv_heads", "head_dim")),
                           ("bv", (H, D), ("kv_heads", "head_dim")),
                           ("bo", (dm,), ("embed",))):
            ap[n] = jnp.zeros(shp)
            aa[n] = ax
        mp, ma = p["mlp"], a["mlp"]
        mp["wi"] = jax.random.normal(k5, (dm, dff)) / math.sqrt(dm)
        ma["wi"] = ("embed", "mlp")
        mp["bi"] = jnp.zeros((dff,)); ma["bi"] = ("mlp",)
        mp["wo"] = jax.random.normal(k6, (dff, dm)) / math.sqrt(dff)
        ma["wo"] = ("mlp", "embed")
        mp["bo"] = jnp.zeros((dm,)); ma["bo"] = ("embed",)
        p["ln_attn"], a["ln_attn"] = norm_init()
        p["ln_mlp"], a["ln_mlp"] = norm_init()
        return p, a

    lkeys = jax.random.split(keys[3], cfg.num_layers)
    per = [layer_init(k) for k in lkeys]
    params["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs),
                                    *[p for p, _ in per])
    axes["blocks"] = per[0][1]

    if cfg.pooler:
        params["pooler"] = {
            "kernel": jax.random.normal(keys[4], (dm, dm)) / math.sqrt(dm),
            "bias": jnp.zeros((dm,))}
        axes["pooler"] = {"kernel": ("embed", None), "bias": (None,)}
    return params, axes


def encode(cfg: EncoderConfig, params, input_ids,
           attention_mask=None, token_type_ids=None, dtype=None):
    """→ last hidden state [B, S, dm] (bidirectional, padding-masked)."""
    dt = dtype or params["embed"]["table"].dtype
    B, S = input_ids.shape
    x = L.embed(params["embed"], input_ids).astype(dt)
    x = x + params["pos_embed"]["table"][:S].astype(dt)
    if cfg.type_vocab_size > 0:
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + params["type_embed"]["table"][token_type_ids].astype(dt)
    norm = lambda p, h: L.layernorm(p, h, eps=cfg.eps)   # noqa: E731
    x = norm(params["ln_embed"], x)
    act = L.ACTIVATIONS[cfg.activation]

    def body(h, lp):
        ap = lp["attn"]
        q = jnp.einsum("bsd,dhk->bshk", h, ap["wq"].astype(dt)) \
            + ap["bq"].astype(dt)
        k = jnp.einsum("bsd,dhk->bshk", h, ap["wk"].astype(dt)) \
            + ap["bk"].astype(dt)
        v = jnp.einsum("bsd,dhk->bshk", h, ap["wv"].astype(dt)) \
            + ap["bv"].astype(dt)
        o = L.causal_attention(q, k, v, mask=attention_mask, causal=False)
        o = jnp.einsum("bshk,hkd->bsd", o, ap["wo"].astype(dt)) \
            + ap["bo"].astype(dt)
        h = norm(lp["ln_attn"], h + o)                   # post-LN
        mp = lp["mlp"]
        u = act(h @ mp["wi"].astype(dt) + mp["bi"].astype(dt))
        d = u @ mp["wo"].astype(dt) + mp["bo"].astype(dt)
        h = norm(lp["ln_mlp"], h + d)
        return h, None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    return x


def pooled(cfg: EncoderConfig, params, hidden):
    """BERT pooler: tanh(dense([CLS])) — the sentence embedding."""
    cls = hidden[:, 0]
    p = params["pooler"]
    return jnp.tanh(cls @ p["kernel"].astype(cls.dtype)
                    + p["bias"].astype(cls.dtype))


class Encoder:
    """Encoder model + bucketed batch serving.

    ``encode_batch`` is the embedding/rerank serving surface: requests
    pad into power-of-two sequence buckets (one compiled program per
    bucket), masked mean- or CLS-pooled."""

    def __init__(self, config: EncoderConfig, seed: int = 0,
                 dtype=jnp.float32):
        self.config = config
        self.params, self.param_axes = init_params(
            config, jax.random.PRNGKey(seed))
        if dtype != jnp.float32:
            self.params = jax.tree.map(
                lambda x: x.astype(dtype)
                if x.dtype == jnp.float32 else x, self.params)
        self._fns: Dict[int, Any] = {}

    @classmethod
    def from_params(cls, config: EncoderConfig, params):
        """Wrap an existing tree (e.g. ``checkpoint.hf.load_hf_bert``)."""
        self = cls.__new__(cls)
        self.config = config
        self.params = params
        self.param_axes = None
        self._fns = {}
        return self

    def _fn(self, S: int):
        f = self._fns.get(S)
        if f is None:
            cfg = self.config

            def run(params, ids, mask, types):
                h = encode(cfg, params, ids, attention_mask=mask,
                           token_type_ids=types)
                cls_vec = (pooled(cfg, params, h) if cfg.pooler
                           else h[:, 0])
                m = mask.astype(h.dtype)[..., None]
                mean_vec = (h * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
                return h, cls_vec, mean_vec

            f = self._fns[S] = jax.jit(run)
        return f

    def encode_batch(self, requests: Sequence[Sequence[int]],
                     token_type_ids: Optional[Sequence[Sequence[int]]]
                     = None, pool: str = "cls"
                     ) -> "np.ndarray | List[np.ndarray]":
        """→ [len(requests), d_model] embeddings (``pool``: "cls" |
        "mean" | "none" for the full hidden states list)."""
        assert pool in ("cls", "mean", "none")
        maxlen = max(len(r) for r in requests)
        S = 16
        while S < maxlen:
            S *= 2
        S = min(S, self.config.max_seq_len)
        assert maxlen <= S, (maxlen, self.config.max_seq_len)
        B = len(requests)
        ids = np.zeros((B, S), np.int32)
        mask = np.zeros((B, S), np.int32)
        types = np.zeros((B, S), np.int32)
        for i, r in enumerate(requests):
            ids[i, :len(r)] = r
            mask[i, :len(r)] = 1
            if token_type_ids is not None:
                types[i, :len(token_type_ids[i])] = token_type_ids[i]
        h, cls_vec, mean_vec = self._fn(S)(
            self.params, jnp.asarray(ids), jnp.asarray(mask),
            jnp.asarray(types))
        if pool == "cls":
            return np.asarray(cls_vec)
        if pool == "mean":
            return np.asarray(mean_vec)
        return [np.asarray(h[i, :len(r)]) for i, r in enumerate(requests)]
