"""Functional layer library with logical-axis parameter metadata.

Every constructor returns ``(params, axes)`` where ``axes`` is a matching
pytree of logical-axis tuples consumed by
:mod:`deepspeed_tpu.parallel.sharding`.  Apply functions are pure.

This replaces the reference's module-injection machinery: where DeepSpeed
walks an existing torch module tree and slices weights imperatively
(``module_inject/auto_tp.py:189``, ``module_inject/layers.py:78-124``
LinearAllreduce/LinearLayer), TPU-native models are *born* with sharding
metadata and XLA places the collectives.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def dense_init(key, in_dim: int, out_dim: int, in_axis: str, out_axis: str,
               bias: bool = True, scale: Optional[float] = None):
    scale = scale if scale is not None else 1.0 / math.sqrt(in_dim)
    p = {"kernel": jax.random.normal(key, (in_dim, out_dim)) * scale}
    a = {"kernel": (in_axis, out_axis)}
    if bias:
        p["bias"] = jnp.zeros((out_dim,))
        a["bias"] = (out_axis,)
    return p, a


def dense(p, x):
    y = x @ p["kernel"].astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def embedding_init(key, vocab: int, dim: int, scale: float = 0.02):
    return ({"table": jax.random.normal(key, (vocab, dim)) * scale},
            {"table": ("vocab", "embed")})


def embed(p, ids):
    return jnp.take(p["table"], ids, axis=0)


def layernorm_init(dim: int):
    return ({"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))},
            {"scale": ("norm",), "bias": ("norm",)})


def layernorm(p, x, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def rmsnorm_init(dim: int):
    return ({"scale": jnp.ones((dim,))}, {"scale": ("norm",)})


def rmsnorm(p, x, eps: float = 1e-6):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt((x32 * x32).mean(-1, keepdims=True) + eps)
    return (y * p["scale"]).astype(x.dtype)


# --------------------------------------------------------------------------
# Rotary position embeddings (reference kernel analog:
# csrc/transformer/inference apply_rotary_pos_emb, v2 kv_rotary)
# --------------------------------------------------------------------------

def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """ALiBi per-head slopes (reference consumers: the bloom injection
    policy, module_inject/containers/bloom.py; math from the ALiBi
    paper): geometric sequence from 2^(-8/n), closest power of two
    padded like the HF implementation for non-power-of-two head counts."""
    n = 2 ** math.floor(math.log2(num_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
    slopes = [base ** (i + 1) for i in range(n)]
    if n < num_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * n) - 3)))
        slopes += [extra_base ** (2 * i + 1)
                   for i in range(num_heads - n)]
    return jnp.asarray(slopes, jnp.float32)


def make_alibi_attention(base=None, head_offset=None,
                         total_heads: Optional[int] = None):
    """Wrap an attention fn with the ALiBi bias.  Uses the key-position
    form ``slope_h * j`` (the query-position term is constant per softmax
    row and cancels) — exactly HF Bloom's ``build_alibi_tensor``.

    Under manual head sharding (Ulysses inside ``shard_map``) the local
    head block is a SLICE of the global geometric slope series:
    ``total_heads`` fixes the global head count and ``head_offset`` (a
    zero-arg callable, e.g. ``lambda: axis_index(seq) * H_local``)
    locates this shard's first head.  Default: local heads ARE the
    global heads."""
    base_fn = base or causal_attention

    def attn(q, k, v, mask=None, **kw):
        Hl, Sk = q.shape[2], k.shape[1]
        slopes = alibi_slopes(total_heads or Hl)
        if head_offset is not None:
            slopes = jax.lax.dynamic_slice_in_dim(
                slopes, head_offset(), Hl)
        bias = slopes[:, None, None] \
            * jnp.arange(Sk, dtype=jnp.float32)[None, None, :]
        return base_fn(q, k, v, mask=mask, bias=bias, **kw)
    return attn


def rope_freqs(head_dim: int, max_seq: int, theta: float = 10000.0):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    ang = jnp.outer(t, inv)                    # [S, D/2]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin, positions=None):
    """x: [B, S, H, D]; cos/sin: [maxS, R/2] with R <= D (partial rotary
    — phi-style — rotates only the first R head dims); positions: [B, S]
    or None."""
    if positions is None:
        c = cos[: x.shape[1]][None, :, None, :]
        s = sin[: x.shape[1]][None, :, None, :]
    else:
        c = cos[positions][:, :, None, :]
        s = sin[positions][:, :, None, :]
    rot = 2 * cos.shape[-1]
    xr, x_pass = x[..., :rot], x[..., rot:]
    x1, x2 = jnp.split(xr.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return jnp.concatenate([out.astype(x.dtype), x_pass], axis=-1)


# --------------------------------------------------------------------------
# Attention (XLA path; Pallas flash kernel plugs in via the same signature)
# --------------------------------------------------------------------------

def causal_attention(q, k, v, mask: Optional[jnp.ndarray] = None,
                     scale: Optional[float] = None, causal: bool = True,
                     bias: Optional[jnp.ndarray] = None):
    """q: [B, S, H, D]; k/v: [B, Sk, Hkv, D].  GQA via grouped einsum — KV
    are never materialized at full head count, preserving the memory GQA
    exists to save.  Softmax in fp32 for stability; XLA fuses the block
    onto the MXU.  ``causal=False`` gives bidirectional attention.
    ``bias``: additive attention bias [H, S|1, Sk] (ALiBi et al.)."""
    B, S, H, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    rep = H // Hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    qg = q.reshape(B, S, Hkv, rep, D)
    logits = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k) * scale
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32).reshape(
            Hkv, rep, bias.shape[-2], Sk)[None]
    if causal:
        keep = jnp.tril(jnp.ones((S, Sk), bool), k=Sk - S)
        logits = jnp.where(keep[None, None, None], logits, -1e30)
    if mask is not None:                        # [B, Sk] padding mask
        logits = jnp.where(mask[:, None, None, None, :].astype(bool),
                           logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhrqk,bkhd->bqhrd", probs, v)
    return out.reshape(B, S, H, D)


ACTIVATIONS = {
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
}
