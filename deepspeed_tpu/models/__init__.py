from . import layers
from .transformer import (Model, TransformerConfig, apply, init_params,
                          cross_entropy_loss, lm_loss_fn, block_apply)
from .presets import PRESETS, build_config, build_model
from .encoder import Encoder, EncoderConfig
from .diffusion import (AutoencoderKL, UNet2DCondition, UNetConfig,
                        VAEConfig)
from .clip import CLIP, CLIPConfig

__all__ = ["layers", "Model", "TransformerConfig", "apply", "init_params",
           "cross_entropy_loss", "lm_loss_fn", "block_apply",
           "PRESETS", "build_config", "build_model",
           "Encoder", "EncoderConfig",
           "AutoencoderKL", "UNet2DCondition", "UNetConfig", "VAEConfig",
           "CLIP", "CLIPConfig"]
