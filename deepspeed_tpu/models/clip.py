"""CLIP dual-tower (vision transformer + causal text transformer).

TPU-native analog of the reference's CLIP serving support
(``module_inject/containers/clip.py:13`` — policy injection into HF
CLIPEncoderLayer; both towers share that layer shape).  Implemented as
one shared pre-LN residual block applied with ``lax.scan`` over stacked
layer params (the repo's standard scan layout): the vision tower runs it
bidirectionally over patch tokens + a class token, the text tower runs
it causally over BPE tokens; each pools (class token / EOT token),
projects into the shared embedding space, and similarity is the
logit-scaled cosine — ``encode_image`` / ``encode_text`` /
``similarity`` are the serving surface (embedding / retrieval class).

QuickGELU (x * sigmoid(1.702 x)) matches OpenAI CLIP checkpoints.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import layers as L


def quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


@dataclasses.dataclass
class CLIPTowerConfig:
    width: int
    num_layers: int
    num_heads: int
    d_ff: Optional[int] = None       # None => 4*width

    def __post_init__(self):
        if self.d_ff is None:
            self.d_ff = 4 * self.width


@dataclasses.dataclass
class CLIPConfig:
    embed_dim: int = 512
    # vision
    image_size: int = 224
    patch_size: int = 32
    vision: CLIPTowerConfig = None
    # text
    vocab_size: int = 49408
    max_text_len: int = 77
    text: CLIPTowerConfig = None
    eps: float = 1e-5

    def __post_init__(self):
        if self.vision is None:
            self.vision = CLIPTowerConfig(width=768, num_layers=12,
                                          num_heads=12)
        if self.text is None:
            self.text = CLIPTowerConfig(width=512, num_layers=12,
                                        num_heads=8)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def _block_init(key, tw: CLIPTowerConfig):
    w, H, dff = tw.width, tw.num_heads, tw.d_ff
    D = w // H
    k = jax.random.split(key, 6)
    ln = lambda: {"scale": jnp.ones((w,)), "bias": jnp.zeros((w,))}
    return {
        "ln1": ln(), "ln2": ln(),
        "attn": {
            "wq": jax.random.normal(k[0], (w, H, D)) / math.sqrt(w),
            "wk": jax.random.normal(k[1], (w, H, D)) / math.sqrt(w),
            "wv": jax.random.normal(k[2], (w, H, D)) / math.sqrt(w),
            "wo": jax.random.normal(k[3], (H, D, w)) / math.sqrt(w),
            "bq": jnp.zeros((H, D)), "bk": jnp.zeros((H, D)),
            "bv": jnp.zeros((H, D)), "bo": jnp.zeros((w,)),
        },
        "mlp": {
            "wi": jax.random.normal(k[4], (w, dff)) / math.sqrt(w),
            "bi": jnp.zeros((dff,)),
            "wo": jax.random.normal(k[5], (dff, w)) / math.sqrt(dff),
            "bo": jnp.zeros((w,)),
        },
    }


def _tower_blocks_init(key, tw: CLIPTowerConfig):
    ks = jax.random.split(key, tw.num_layers)
    per = [_block_init(k, tw) for k in ks]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per)


def _tower_apply(cfg: CLIPConfig, tw: CLIPTowerConfig, blocks, x,
                 causal: bool):
    """Shared pre-LN residual stack (the CLIPEncoderLayer shape):
    x += attn(LN(x)); x += mlp(LN(x)) — scan over stacked layers."""
    dt = x.dtype
    norm = lambda p, v: L.layernorm(p, v, eps=cfg.eps)   # noqa: E731

    def body(h, lp):
        a = norm(lp["ln1"], h)
        ap = lp["attn"]
        q = jnp.einsum("bsd,dhk->bshk", a, ap["wq"].astype(dt)) \
            + ap["bq"].astype(dt)
        k = jnp.einsum("bsd,dhk->bshk", a, ap["wk"].astype(dt)) \
            + ap["bk"].astype(dt)
        v = jnp.einsum("bsd,dhk->bshk", a, ap["wv"].astype(dt)) \
            + ap["bv"].astype(dt)
        o = L.causal_attention(q, k, v, causal=causal)
        o = jnp.einsum("bshk,hkd->bsd", o, ap["wo"].astype(dt)) \
            + ap["bo"].astype(dt)
        h = h + o
        m = norm(lp["ln2"], h)
        mp = lp["mlp"]
        u = quick_gelu(m @ mp["wi"].astype(dt) + mp["bi"].astype(dt))
        h = h + (u @ mp["wo"].astype(dt) + mp["bo"].astype(dt))
        return h, None

    x, _ = jax.lax.scan(body, x, blocks)
    return x


def init_params(cfg: CLIPConfig, key) -> Dict[str, Any]:
    (kv, kt, kvb, ktb, k3, k4, k5, k6,
     k7) = jax.random.split(key, 9)
    vw, tw = cfg.vision.width, cfg.text.width
    P = cfg.patch_size
    return {
        "visual": {
            "patch_embed": {"kernel": jax.random.normal(
                kv, (P, P, 3, vw)) / math.sqrt(P * P * 3)},
            "class_embed": jax.random.normal(k3, (vw,)) * 0.02,
            "pos_embed": jax.random.normal(
                k4, (cfg.num_patches + 1, vw)) * 0.02,
            "ln_pre": {"scale": jnp.ones((vw,)), "bias": jnp.zeros((vw,))},
            "blocks": _tower_blocks_init(kvb, cfg.vision),
            "ln_post": {"scale": jnp.ones((vw,)),
                        "bias": jnp.zeros((vw,))},
            "proj": jax.random.normal(k5, (vw, cfg.embed_dim))
            / math.sqrt(vw),
        },
        "text": {
            "embed": {"table": jax.random.normal(
                kt, (cfg.vocab_size, tw)) * 0.02},
            "pos_embed": jax.random.normal(
                k6, (cfg.max_text_len, tw)) * 0.01,
            "blocks": _tower_blocks_init(ktb, cfg.text),
            "ln_final": {"scale": jnp.ones((tw,)),
                         "bias": jnp.zeros((tw,))},
            "proj": jax.random.normal(k7, (tw, cfg.embed_dim))
            / math.sqrt(tw),
        },
        "logit_scale": jnp.asarray(np.log(1 / 0.07), jnp.float32),
    }


def encode_image(cfg: CLIPConfig, params, images) -> jnp.ndarray:
    """images [B, H, W, 3] (NHWC) → [B, embed_dim] (unnormalized)."""
    vp = params["visual"]
    dt = images.dtype
    x = jax.lax.conv_general_dilated(
        images, vp["patch_embed"]["kernel"].astype(dt),
        window_strides=(cfg.patch_size, cfg.patch_size), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    B = x.shape[0]
    x = x.reshape(B, -1, cfg.vision.width)              # [B, P², W]
    cls = jnp.broadcast_to(vp["class_embed"].astype(dt),
                           (B, 1, cfg.vision.width))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + vp["pos_embed"][: x.shape[1]].astype(dt)
    x = L.layernorm(vp["ln_pre"], x, eps=cfg.eps)
    x = _tower_apply(cfg, cfg.vision, vp["blocks"], x, causal=False)
    pooled = L.layernorm(vp["ln_post"], x[:, 0], eps=cfg.eps)
    return pooled @ vp["proj"].astype(dt)


def encode_text(cfg: CLIPConfig, params, input_ids) -> jnp.ndarray:
    """input_ids [B, S] → [B, embed_dim]; pools at the EOT token, which
    in CLIP's vocabulary is the highest token id in the sequence."""
    tp = params["text"]
    x = L.embed(tp["embed"], input_ids)
    dt = x.dtype
    x = x + tp["pos_embed"][: x.shape[1]].astype(dt)
    x = _tower_apply(cfg, cfg.text, tp["blocks"], x, causal=True)
    x = L.layernorm(tp["ln_final"], x, eps=cfg.eps)
    eot = jnp.argmax(input_ids, axis=-1)
    pooled = jnp.take_along_axis(
        x, eot[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    return pooled @ tp["proj"].astype(dt)


def similarity(cfg: CLIPConfig, params, images, input_ids):
    """→ (logits_per_image [B_img, B_txt], logits_per_text)."""
    ie = encode_image(cfg, params, images)
    te = encode_text(cfg, params, input_ids)
    ie = ie / jnp.linalg.norm(ie, axis=-1, keepdims=True)
    te = te / jnp.linalg.norm(te, axis=-1, keepdims=True)
    scale = jnp.exp(params["logit_scale"]).astype(ie.dtype)
    lpi = scale * ie @ te.T
    return lpi, lpi.T


class CLIP:
    """Model wrapper: jitted encode/similarity serving surface."""

    def __init__(self, config: CLIPConfig = None, seed: int = 0,
                 dtype=jnp.float32):
        self.config = config or CLIPConfig()
        self.params = init_params(self.config, jax.random.PRNGKey(seed))
        if dtype != jnp.float32:
            self.params = jax.tree.map(
                lambda x: x.astype(dtype)
                if x.dtype == jnp.float32 else x, self.params)
        self._build_jits()

    @classmethod
    def from_params(cls, config: CLIPConfig, params):
        self = cls.__new__(cls)
        self.config = config
        self.params = params
        self._build_jits()
        return self

    def _build_jits(self):
        cfg = self.config
        self._img = jax.jit(lambda p, im: encode_image(cfg, p, im))
        self._txt = jax.jit(lambda p, ids: encode_text(cfg, p, ids))
        self._sim = jax.jit(
            lambda p, im, ids: similarity(cfg, p, im, ids))

    def encode_image(self, images):
        return self._img(self.params, images)

    def encode_text(self, input_ids):
        return self._txt(self.params, input_ids)

    def similarity(self, images, input_ids):
        return self._sim(self.params, images, input_ids)
