"""Environment report CLI — the ``ds_report`` analog
(reference: ``deepspeed/env_report.py``; ``bin/ds_report``).

Usage: ``python -m deepspeed_tpu.env_report``
"""

from __future__ import annotations

import platform as _platform
import shutil
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[93m[NO]\033[0m"


def _row(name: str, status: str, extra: str = "") -> str:
    return f"{name:.<30} {status} {extra}"


def main() -> int:
    lines = ["-" * 60, "DeepSpeed-TPU environment report", "-" * 60]

    import numpy
    lines.append(_row("python", GREEN_OK, sys.version.split()[0]))
    lines.append(_row("platform", GREEN_OK, _platform.platform()))
    lines.append(_row("numpy", GREEN_OK, numpy.__version__))

    try:
        import jax
        import jaxlib
        lines.append(_row("jax", GREEN_OK, jax.__version__))
        lines.append(_row("jaxlib", GREEN_OK, jaxlib.__version__))
        devs = jax.devices()
        lines.append(_row("devices", GREEN_OK,
                          f"{len(devs)} x {devs[0].platform} "
                          f"({devs[0].device_kind})"))
        try:
            stats = devs[0].memory_stats() or {}
            lim = stats.get("bytes_limit")
            if lim:
                lines.append(_row("device memory", GREEN_OK,
                                  f"{lim / 2**30:.1f} GiB"))
        # optional-info probe: absence of the row is the report
        except Exception:  # tpulint: disable=silent-except
            pass
        try:
            devs[0].memory("pinned_host")
            lines.append(_row("pinned_host memory", GREEN_OK,
                              "(ZeRO-Offload capable)"))
        # failure surfaces as the RED_NO row in the printed report
        except Exception:  # tpulint: disable=silent-except
            lines.append(_row("pinned_host memory", RED_NO))
    except Exception as e:  # tpulint: disable=silent-except
        lines.append(_row("jax", RED_NO, str(e)))

    for mod in ("flax", "optax", "orbax.checkpoint", "chex", "einops",
                "transformers", "torch"):
        try:
            m = __import__(mod)
            ver = getattr(m, "__version__", "?")
            lines.append(_row(mod, GREEN_OK, ver))
        except Exception:  # tpulint: disable=silent-except
            lines.append(_row(mod, RED_NO))

    # native op builders (reference: op compatibility table in ds_report)
    lines.append("-" * 60)
    lines.append("native ops:")
    gxx = shutil.which("g++")
    lines.append(_row("g++ toolchain", GREEN_OK if gxx else RED_NO,
                      gxx or ""))
    try:
        from .ops.builder import AsyncIOBuilder
        b = AsyncIOBuilder()
        ok = b.is_compatible()
        lines.append(_row("async_io", GREEN_OK if ok else RED_NO))
        if ok:
            b.load()
            lines.append(_row("async_io build", GREEN_OK))
    except Exception as e:  # tpulint: disable=silent-except
        lines.append(_row("async_io build", RED_NO, str(e)[:60]))

    lines.append("-" * 60)
    print("\n".join(lines))  # tpulint: disable=print — the report IS the output
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
