"""Training engine: the TPU-native ``DeepSpeedEngine``.

Re-design of the reference engine (``runtime/engine.py:182`` —
``DeepSpeedEngine.forward/backward/step`` :1838/:1977/:2176, optimizer
configuration :1272, ZeRO wiring :1532) for the XLA compilation model:

* forward/backward/step collapse into ONE jitted, donated train-step
  function; gradient accumulation is a ``lax.scan`` over micro-batches
  (the GAS boundary of engine.py:1960 becomes a scan carry), so a whole
  optimizer step is a single device dispatch.
* ZeRO stages are sharding specs (see ``parallel/zero.py``); the grad
  hooks / bucketing / overlap machinery of stage_1_and_2.py &
  stage3.py is replaced by the XLA SPMD partitioner, which emits the same
  reduce-scatter / all-gather schedule, overlapped with compute.
* fp16 overflow handling (CheckOverflow, dynamic loss scaler) runs inside
  the step with ``jnp.where`` — no host sync, no global state.

Public API mirrors the reference:

    engine = deepspeed_tpu.initialize(loss_fn=..., params=..., config=...)
    metrics = engine.train_batch(batch)       # one full optimizer step
    engine.save_checkpoint(dir); engine.load_checkpoint(dir)
"""

from __future__ import annotations

import dataclasses
import time
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Tuple)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.comms_logging import comms_logger
from ..comm.mesh import DATA_AXIS, FSDP_AXIS, MeshTopology
from ..compat import shard_map
from ..comm.collectives import init_distributed
from ..config.config import Config, ConfigError, load_config
from ..parallel.zero import ZeroPolicy
from ..parallel import sharding as shd
from ..telemetry import (AnomalyConfig, AnomalyMonitor, DeviceTelemetry,
                         MetricsRegistry, ProfilerCapture, SpanTracer,
                         default_training_detectors)
from ..utils.logging import log_dist, logger
from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from .loss_scaler import LossScaler, LossScaleState, all_finite
from .lr_schedules import build_schedule, constant
from .optimizers import Optimizer, build_optimizer
from .runtime_utils import clip_by_global_norm, global_norm, param_count

PRECISION_DTYPE = {"fp32": jnp.float32, "fp16": jnp.float16, "bf16": jnp.bfloat16}


class TrainState(NamedTuple):
    """Everything that persists across steps — a single donated pytree."""
    step: jnp.ndarray          # i32 scalar (optimizer steps taken)
    master: Any                # fp32 master params (sharded per ZeRO stage)
    opt_state: Any             # optimizer moments (sharded like master)
    loss_scale: LossScaleState
    skipped: jnp.ndarray       # i32 count of overflow-skipped steps


class OnebitCommState(NamedTuple):
    """Optimizer-state wrapper for 1-bit compressed communication: the
    base optimizer's state plus the per-shard error-feedback buffers
    (stacked over the reduce axes — each shard owns its slice)."""
    base: Any
    comm_err: Any


class _StagedBatch(dict):
    """Marker: this batch is already device-placed (and, when staged with
    accumulate=True and gas>1, reshaped to [gas, micro, ...])."""

    accumulate: bool = True


jax.tree_util.register_pytree_node(
    _StagedBatch,
    lambda d: (tuple(d[k] for k in sorted(d)), tuple(sorted(d))),
    lambda keys, vals: _StagedBatch(zip(keys, vals)))


class Engine:
    """TPU-native training engine (reference: DeepSpeedEngine engine.py:182)."""

    def __init__(self,
                 loss_fn: Callable,
                 params: Any,
                 config: Config,
                 topology: Optional[MeshTopology] = None,
                 param_axes: Any = None,
                 sharding_rules: Optional[Dict] = None,
                 eval_fn: Optional[Callable] = None,
                 monitor=None,
                 model: Any = None):
        """``loss_fn(params, batch, rng) -> loss`` or ``(loss, aux_dict)``.

        ``params`` is a pytree of arrays (any dtype; cast to fp32 master).
        ``param_axes`` is an optional matching pytree of logical-axis tuples
        for TP sharding; absent axes mean replicate-under-TP, fsdp-by-shape.
        """
        self.config = config
        init_distributed()
        hpz = config.zero_optimization.zero_hpz_partition_size
        mics = config.zero_optimization.mics_shard_size
        mesh_cfg = config.mesh
        if mics > 0 and hpz > 1:
            raise ConfigError(
                "mics_shard_size and zero_hpz_partition_size both bound "
                "the shard group; set only one")

        def fold_fsdp(mc, group: int, knob: str):
            """Shrink the fsdp axis to ``group`` and fold the remaining
            degree into data replicas (copy — the user's config object
            stays as written)."""
            if mc.fsdp <= 0:
                raise ConfigError(
                    f"{knob} requires an explicit mesh.fsdp size "
                    "(the full shard degree being bounded)")
            if mc.fsdp % group:
                raise ConfigError(f"{knob}={group} must divide "
                                  f"mesh.fsdp={mc.fsdp}")
            outer = mc.fsdp // group
            return dataclasses.replace(
                mc, fsdp=group,
                data=mc.data * outer if mc.data > 0 else mc.data)

        if mics > 0:
            if topology is not None:
                raise ConfigError(
                    "mics_shard_size remaps the mesh and cannot be "
                    "combined with a pre-built topology; pass mesh "
                    "config instead")
            # MiCS (reference: runtime/zero/mics.py:64): shard over a
            # sub-group of mics_shard_size instead of the full DP world —
            # params, masters AND optimizer state live within the group,
            # replicated across groups (unlike hpZ, which keeps masters
            # world-sharded).  Mesh formulation: fsdp shrinks to the
            # group size, the remaining degree folds into data replicas;
            # XLA's grad psum over data+fsdp IS the hierarchical
            # reduce-scatter-then-all-reduce of mics.py:254.
            # Exception: with offload_optimizer=cpu, masters/moments
            # world-shard over data x fsdp anyway (host-DRAM
            # minimization, zero.py master_spec) — the MiCS bound
            # applies to the DEVICE collectives (compute-param gathers),
            # which stay within the group either way.
            mesh_cfg = fold_fsdp(mesh_cfg, mics, "mics_shard_size")
        if topology is None and hpz > 1 and mesh_cfg.fsdp > hpz:
            # hpZ: the gather axis shrinks to the secondary-partition size
            # (intra-slice) and the rest of the requested fsdp degree folds
            # into data; masters still shard over data x fsdp (zero.py).
            mesh_cfg = fold_fsdp(mesh_cfg, hpz, "zero_hpz_partition_size")
        self.topology = topology or MeshTopology.build(mesh_cfg)
        self.loss_fn = loss_fn
        self.eval_fn = eval_fn

        # batch-size triangulation (reference: runtime/config.py:802-884)
        self.train_batch_size, self.micro_batch_size, self.gas = \
            config.resolve_batch_sizes(self.topology.dp_world_size)

        # precision policy
        self.precision = config.precision
        self.compute_dtype = PRECISION_DTYPE[self.precision]
        self.scaler = LossScaler.from_config(config.fp16)

        # ZeRO policy + shardings
        self.param_axes = (param_axes if param_axes is not None
                           else shd.infer_logical_axes(params))
        self.zero = ZeroPolicy.from_config(
            config.zero_optimization, self.topology, rules=sharding_rules)
        # ZeRO-Infinity: fp32 master + moments on NVMe, bf16 working copy
        # on device (reference: stage3.py:614 _configure_tensor_swapping)
        self._nvme = None
        off_opt = config.zero_optimization.offload_optimizer
        if off_opt.device == "nvme":
            from .zero_infinity import NVMeOptimizer
            self._nvme = NVMeOptimizer(
                off_opt.nvme_path, config.optimizer.type,
                config.optimizer.params, buffer_size=off_opt.buffer_size,
                aio_config=config.aio)
        # ZeRO-Infinity param streaming: offload_param=nvme + a
        # stacked-layer model => per-layer NVMe parameter streaming
        # (reference: partitioned_param_swapper.py:290 / stage3.py:614)
        self._model = model
        self._stream = None
        self._stream_params = (
            self._nvme is not None
            and config.zero_optimization.offload_param.device == "nvme"
            and model is not None and hasattr(model, "config")
            and isinstance(params, dict) and "blocks" in params)
        self._build_shardings(params)
        self._qgz_axes = self._qgz_manual_axes()
        self._sparse_axes = self._sparse_manual_axes(params)
        # overlapped / quantized grad-sync collectives (comm/overlap.py;
        # ROADMAP item 1): explicit tile-decomposed reduce-scatter /
        # all-reduce (optionally on the qgZ int8/int4 wire) over the DP
        # axes.  qgZ proper (zero_quantized_gradients) and sparse
        # gradients keep precedence — they already own the manual
        # region; _manual_reduce_axes carries the PR-1 loud-degradation
        # contract for meshes that cannot host it.
        self._comm_axes: Tuple[str, ...] = ()
        ccfg = config.comm
        opt_name = config.optimizer.type.lower()
        onebit_opt = "onebit" in opt_name or "zeroone" in opt_name
        if (ccfg.overlap or ccfg.quantized_allreduce) \
                and not self._qgz_axes and not self._sparse_axes:
            if onebit_opt:
                # the documented precedence: a 1-bit optimizer's packed
                # sign+scale reduction with error feedback owns the
                # wire — silently replacing it with the comm path would
                # downgrade the compression the optimizer is built
                # around
                logger.warning(
                    "comm.overlap/comm.quantized_allreduce: a 1-bit "
                    "optimizer (%s) owns the gradient reduction; comm "
                    "settings ignored", config.optimizer.type)
            else:
                self._comm_axes = self._manual_reduce_axes(
                    "comm.overlap/comm.quantized_allreduce gradient sync")
        self._comm_wire: Optional[Dict[str, float]] = None

        # optimizer + schedule (reference: _configure_basic_optimizer :1322)
        opt_cfg = config.optimizer
        lr = opt_cfg.params.get("lr", 1e-3)
        if config.scheduler is not None:
            sched_params = dict(config.scheduler.params)
            if config.scheduler.type in ("WarmupCosineLR",):
                sched_params.setdefault("lr", lr)
            self.lr_schedule = build_schedule(config.scheduler.type, sched_params)
        else:
            self.lr_schedule = constant(lr)

        # 1-bit optimizers: route the DP gradient reduction through the
        # packed sign+scale collective with error feedback (reference:
        # compressed_allreduce nccl.py:16; up to 5x/32x comm reduction,
        # docs/_tutorials/onebit-adam.md:2)
        self._onebit_axes: Tuple[str, ...] = ()
        if ("onebit" in opt_cfg.type.lower()
                or "zeroone" in opt_cfg.type.lower()) \
                and self._nvme is None and not self._qgz_axes \
                and not self._sparse_axes \
                and not getattr(self, "offload_active", False):
            self._onebit_axes = self._manual_reduce_axes(
                "onebit compressed communication")
        self._onebit_freeze = 0
        if self._onebit_axes:
            # exact (uncompressed) reduction through the warmup, like the
            # reference's pre-freeze allreduce
            self._onebit_freeze = int(opt_cfg.params.get(
                "freeze_step", opt_cfg.params.get("var_freeze_step", 100)))
            self._onebit_b1 = float(
                opt_cfg.params.get("betas", (0.9, 0.999))[0])
            # the wire carries the compression now — the in-optimizer
            # momentum compression would compound the noise
            base_opt = build_optimizer(
                opt_cfg.type, self.lr_schedule,
                {**opt_cfg.params, "compress": False})
            W = int(np.prod([self.topology.axis_sizes[a]
                             for a in self._onebit_axes]))

            def ob_init(master, _base=base_opt, _w=W):
                return OnebitCommState(
                    base=_base.init(master),
                    comm_err=jax.tree.map(
                        lambda p: jnp.zeros((_w,) + p.shape, jnp.float32),
                        master))

            self.optimizer = Optimizer(ob_init, base_opt.update)
        else:
            self.optimizer: Optimizer = build_optimizer(
                opt_cfg.type, self.lr_schedule, opt_cfg.params)

        # state init (sharded via jit out_shardings → no host-side gather)
        self.state = self._init_state(params)
        self.global_steps = 0
        self.global_samples = 0

        self.timers = SynchronizedWallClockTimer()
        self.tput = ThroughputTimer(batch_size=self.train_batch_size)
        self._setup_telemetry()
        if monitor is None and (config.tensorboard.enabled
                                or config.csv_monitor.enabled
                                or config.wandb.enabled
                                or config.comet.enabled):
            # reference: MonitorMaster constructed by the engine
            # (engine.py:259) from the monitor sub-configs
            from ..monitor import MonitorMaster
            monitor = MonitorMaster(config)
            if not monitor.enabled:
                monitor = None
        self.monitor = monitor
        self._train_step_fn = None
        self._warmup_step_fn = None
        self._eval_step_fn = None
        self._nvme_step_fn = None
        self._setup_data_efficiency()

        log_dist(
            f"Engine: {param_count(params):,} params | precision={self.precision} "
            f"| zero_stage={self.zero.stage} | mesh={self.topology.axis_sizes} "
            f"| batch={self.train_batch_size} (micro={self.micro_batch_size} "
            f"x gas={self.gas} x dp={self.topology.dp_world_size})")

    # ------------------------------------------------------------------
    # telemetry (docs/OBSERVABILITY.md)
    # ------------------------------------------------------------------
    def _setup_telemetry(self) -> None:
        """Metrics registry + span tracer for the training step's host
        phases.  Everything is host-side floats — the step itself is one
        fused jit program, so the phases telemetry can see are the host
        work around it: data-efficiency pre-step, batch staging, the
        (async) dispatch, and the metrics fetch.  Serving metrics and
        these training counters share the registry/export machinery
        (telemetry/metrics.py), and :meth:`_finish_step` fans both
        through the same ``monitor/`` writers as the loss scalars."""
        tcfg = self.config.telemetry
        self.metrics = MetricsRegistry()
        self.tracer = SpanTracer(capacity=tcfg.trace_capacity,
                                 enabled=tcfg.trace)
        reg = self.metrics
        self._phase_ms = {
            k: reg.counter(f"training_{k}_ms_total",
                           f"cumulative host milliseconds in the {k} "
                           "phase of train_batch")
            for k in ("pre_step", "stage", "dispatch", "fetch")}
        self._c_steps = reg.counter("training_steps_total",
                                    "optimizer steps taken",
                                    int_valued=True)
        self._h_step_host = reg.histogram(
            "training_step_host_ms",
            (0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
             1000.0, 2000.0, 5000.0, 10000.0, 60000.0),
            "host-side wall ms per train_batch call (dispatch is async: "
            "device time appears here only when something blocks)")
        # compile observatory (docs/OBSERVABILITY.md "Device & compiler
        # telemetry"): always-on host counters — a train-step rebuild
        # after the first is a runtime retrace and warns loudly (the
        # dynamic complement of tpulint's static retrace-hazard rule)
        # overlapped/quantized grad-sync collectives (docs/SERVING.md
        # "Overlapped & quantized collectives"): static per-step wire
        # accounting for the comm.{overlap,quantized_allreduce} path —
        # quantized ops carry bits/8 of the exact bytes (asserted by
        # the reconciliation test)
        self._c_comm_ops = reg.counter(
            "training_comm_ops_total",
            "explicit grad-sync collectives dispatched "
            "(kind: exact|quant)", int_valued=True)
        self._c_comm_tiles = reg.counter(
            "training_comm_tiles_total",
            "tiles across dispatched grad-sync collectives",
            int_valued=True)
        self._c_comm_bytes = reg.counter(
            "training_comm_bytes_total",
            "modeled bytes on the wire for explicit grad-sync "
            "collectives (kind: exact|quant)")
        # eager-collective profiling (comm/comms_logging.py): configure
        # the module logger from config and mirror its op records into
        # this registry as training_comm_* counters, so comm time shows
        # up in Prometheus exposition and flight dumps instead of only
        # the ad-hoc log_summary() table
        clcfg = self.config.comms_logger
        if clcfg.enabled:
            comms_logger.configure(enabled=True, verbose=clcfg.verbose,
                                   prof_all=clcfg.prof_all,
                                   prof_ops=clcfg.prof_ops)
        comms_logger.attach_registry(reg)
        self._c_compiles = reg.counter(
            "training_compiles_total",
            "training step programs built (jit-cache fills)",
            int_valued=True)
        self._c_retraces = reg.counter(
            "training_compile_retraces_total",
            "re-builds of a program key this engine had already "
            "compiled (runtime retrace — each warns loudly)",
            int_valued=True)
        self._compiled_ever: set = set()
        # gated device telemetry (telemetry/device.py): per-program
        # cost_analysis + derived training_mfu / training_hbm_bw_util
        # gauges (divided by the throughput timer's step wall — the
        # training dispatch is async, so host phase ms would lie) +
        # memory polling at the steps_per_print boundary.  config:
        # {"telemetry": {"device": true}}
        self.devtel = DeviceTelemetry(
            reg, "training",
            step_ms_fn=lambda: self.tput.total_elapsed_time * 1e3) \
            if tcfg.device else None
        # streaming anomaly detection (telemetry/anomaly.py): None when
        # off — the step path then contains no detector call and no
        # added clock read (the serving engine's zero-cost bar, shared)
        self._acfg = AnomalyConfig()
        self._anom = None
        self._anom_prev: Dict[str, float] = {}
        if tcfg.anomaly:
            self._anom = AnomalyMonitor(self._acfg, reg, "training")
            self._anom.watch_all(default_training_detectors(self._acfg))
        # deep-capture windows (telemetry/profiler.py): the training
        # engine's one profiler seam, same artifact layout as serving
        # (tools/tracemerge.py merges host phases + device trace)
        self._cap = None
        if tcfg.profile:
            self._cap = ProfilerCapture(tcfg.profile, tracer=self.tracer,
                                        max_captures=self._acfg.
                                        max_captures)
            if tcfg.profile_steps > 0:
                self._cap.arm(tcfg.profile_steps, "config")

    def anomaly_summary(self) -> Optional[Dict[str, Any]]:
        """JSON-able anomaly tally (total / by-signal / recent events +
        completed capture dirs); None while anomaly detection is off."""
        if self._anom is None:
            return None
        return {**self._anom.summary(), "captures": self.capture_dirs}

    @property
    def capture_dirs(self) -> List[str]:
        return [] if self._cap is None else list(self._cap.captures)

    def capture(self, steps: Optional[int] = None,
                reason: str = "manual",
                out_dir: Optional[str] = None) -> Optional[str]:
        """Arm an explicit deep-capture window over the next ``steps``
        train steps (jax.profiler device trace + host phase spans,
        merged by tools/tracemerge.py); returns the capture dir or
        None when a window is already armed/active."""
        if self._cap is None:
            if not out_dir:
                raise ValueError("no capture directory: pass out_dir= "
                                 "or set config telemetry.profile")
            self._cap = ProfilerCapture(out_dir, tracer=self.tracer,
                                        max_captures=self._acfg.
                                        max_captures)
        return self._cap.arm(steps or self._acfg.capture_steps, reason,
                             budgeted=False)

    def finish_capture(self) -> Optional[str]:
        """Close any ACTIVE capture window immediately with the steps
        it has (releases the process-wide jax profiler session and the
        force-enabled tracer) — call when training ends before a
        window armed for more steps ran out.  Returns the capture dir
        or None."""
        if self._cap is None or not self._cap.active:
            return None
        return self._cap.finish_now()

    def _feed_step_signals(self, t0: float, t3: float) -> None:
        """Per-step anomaly feed from timestamps already taken (no
        added clock reads); called only when the monitor exists."""
        anom, prev = self._anom, self._anom_prev
        step = self.global_steps
        fired = []
        last_t0 = prev.get("t0")
        prev["t0"] = t0
        if last_t0 is not None:
            fired.append(anom.observe("step_interval_ms",
                                      (t0 - last_t0) * 1e3, step))
        fired.append(anom.observe("step_host_ms", (t3 - t0) * 1e3,
                                  step))
        retr = self._c_retraces.value()
        fired.append(anom.observe("retrace",
                                  retr - prev.get("retrace", 0), step))
        prev["retrace"] = retr
        for ev in fired:
            if ev is not None:
                logger.warning(
                    "training anomaly: %s observed=%.3f baseline=%.3f "
                    "score=%.1f (step %d)", ev.signal, ev.observed,
                    ev.baseline, ev.score, ev.step)
                if self._cap is not None:
                    self._cap.arm(self._acfg.capture_steps,
                                  f"anomaly_{ev.signal}", budgeted=True)

    def _note_compile(self, key: str) -> None:
        self._c_compiles.inc()
        if key in self._compiled_ever:
            self._c_retraces.inc()
            logger.warning(
                "training program %r RECOMPILED at runtime (retrace "
                "#%d) — something invalidated the step executable",
                key, int(self._c_retraces.value()))
        else:
            self._compiled_ever.add(key)

    def metrics_snapshot(self) -> Dict[str, Any]:
        """JSON-able snapshot of the training metrics registry; see also
        ``engine.metrics.prometheus_text()`` and
        ``engine.metrics.write_jsonl(path)``."""
        return self.metrics.snapshot()

    # ------------------------------------------------------------------
    # sharding setup
    # ------------------------------------------------------------------
    def _build_shardings(self, params):
        topo = self.topology
        zero = self.zero
        self.param_shapes = jax.tree.map(lambda p: tuple(np.shape(p)),
                                         params)
        self.param_specs = zero.tree_param_specs(self.param_axes, params)
        self.master_specs = zero.tree_master_specs(self.param_axes, params)
        self.grad_specs = zero.tree_grad_specs(self.param_axes, params)
        self.param_shardings = zero.tree_named(self.param_specs)
        self.master_shardings = zero.tree_named(self.master_specs)
        self.batch_sharding = topo.batch_sharding()
        self.repl = NamedSharding(topo.mesh, P())

        # ZeRO-Offload: master params + optimizer moments live in host DRAM
        # (memory_kind pinned_host); XLA streams them through the device at
        # step time.  The reference's analogous path is CPU optimizer state
        # + DeepSpeedCPUAdam (stage_1_and_2 cpu_offload, csrc/adam) — under
        # XLA the "CPU adam" is the compiler-scheduled host<->HBM transfer
        # around the same fused update.
        self.offload_active = False
        self._offload_validated = False
        if self._nvme is not None:
            # ZeRO-Infinity: the device-resident state is the bf16 working
            # copy in the *compute* layout (fp32 master + moments live on
            # NVMe, see runtime/zero_infinity.py); offload_param=cpu/nvme
            # additionally pins the working copy to host DRAM so HBM only
            # holds parameters transiently during the step.
            self.master_specs = self.param_specs
            self.master_shardings = self.param_shardings
            offp = self.config.zero_optimization.offload_param.device
            if self._stream_params:
                # per-layer NVMe param streaming: the working copy never
                # stages anywhere whole — layers stream through HBM
                # (param_stream.py); shardings stay plain device specs
                return
            if offp in ("cpu", "nvme"):
                if offp == "nvme":
                    logger.warning(
                        "offload_param.device=nvme without a stacked-"
                        "layer model: staging the full bf16 working copy "
                        "in host DRAM; pass model= (models.transformer) "
                        "to stream parameters per layer instead")
                if self._host_memory_supported():
                    multi = self.topology.mesh.size > 1
                    self.master_shardings = jax.tree.map(
                        lambda sh: sh if (multi and sh.is_fully_replicated)
                        else sh.with_memory_kind("pinned_host"),
                        self.master_shardings)
                    self.offload_active = True
                else:
                    logger.warning(
                        "offload_param requested but this backend has no "
                        "pinned_host memory space; ignoring")
            return
        zcfg = self.config.zero_optimization
        if (zcfg.offload_optimizer.device == "cpu"
                or zcfg.offload_param.device == "cpu"):
            # offload_param=cpu without NVMe state rides the same host-DRAM
            # master placement: compute params are cast from the
            # host-placed master each step, so the persistent fp32/param
            # footprint leaves HBM either way (reference:
            # offload_param/offload_optimizer offload_config.py)
            if "lamb" in self.config.optimizer.type.lower():
                # LAMB trust ratios need whole-tensor norms; the offload
                # update runs per-shard inside shard_map, which would
                # silently compute per-shard ratios.
                raise ConfigError(
                    "optimizer offload is not supported with LAMB: trust "
                    "ratios need whole-tensor parameter/update norms, but "
                    "the offloaded update runs per-shard inside shard_map "
                    "and would silently compute per-shard ratios. Use "
                    "adam/adamw/lion/adagrad/sgd with offload, or drop "
                    "offload_optimizer/offload_param for LAMB.")
            if self._host_memory_supported():
                # Per-leaf placement: only sharded leaves move to host DRAM.
                # Under multi-device SPMD, fully-replicated leaves (tiny
                # params the mesh can't divide) stay in HBM — the
                # partitioner cannot express a memory-space transfer of a
                # replicated value, and their footprint is negligible.  On
                # a single-chip mesh there is no partitioning, so
                # everything pins to host (the reference's 1-GPU
                # ZeRO-Offload headline case).
                multi = self.topology.mesh.size > 1
                self.master_shardings = jax.tree.map(
                    lambda sh: sh if (multi and sh.is_fully_replicated)
                    else sh.with_memory_kind("pinned_host"),
                    self.master_shardings)
                self.offload_active = True
            else:
                logger.warning(
                    "offload_optimizer.device=cpu requested but this "
                    "backend has no pinned_host memory space; ignoring")

    @staticmethod
    def _host_memory_supported() -> bool:
        try:
            jax.devices()[0].memory("pinned_host")
            return True
        except Exception:  # tpulint: disable=silent-except — capability probe
            return False

    def _opt_state_shardings(self, opt_state, master):
        """Optimizer moments mirror the master param sharding.

        Any opt-state subtree whose structure equals the master param tree
        (e.g. AdamState.m / .v) gets the master shardings; NamedTuple
        wrappers are recursed into; anything else replicates."""
        master_def = jax.tree.structure(master)

        def rec(node):
            if isinstance(node, OnebitCommState):
                err_sh = jax.tree.map(
                    lambda _: NamedSharding(
                        self.topology.mesh, P(self._onebit_axes)),
                    node.comm_err)
                return OnebitCommState(base=rec(node.base),
                                       comm_err=err_sh)
            if jax.tree.structure(node) == master_def:
                return self.master_shardings
            if isinstance(node, tuple) and hasattr(node, "_fields"):
                return type(node)(*[rec(f) for f in node])
            return jax.tree.map(lambda _: self.repl, node)

        return rec(opt_state)

    # ------------------------------------------------------------------
    # state init
    # ------------------------------------------------------------------
    def _init_state(self, params) -> TrainState:
        if self._nvme is not None:
            return self._init_state_nvme(params)

        def init_fn(p):
            master = jax.tree.map(lambda x: x.astype(jnp.float32), p)
            opt_state = self.optimizer.init(master)
            return master, opt_state

        # discover opt-state structure via eval_shape, then jit w/ device
        # shardings; host (pinned_host) placement happens *outside* jit via
        # device_put — out_shardings with host memory kinds trip the SPMD
        # partitioner on some backends when the value aliases an input.
        master_shape, opt_shape = jax.eval_shape(init_fn, params)
        device_master_sh = jax.tree.map(
            lambda sh: NamedSharding(self.topology.mesh, sh.spec),
            self.master_shardings)
        opt_shardings = self._opt_state_shardings(opt_shape, master_shape)
        device_opt_sh = jax.tree.map(
            lambda sh: NamedSharding(self.topology.mesh, sh.spec),
            opt_shardings)
        init_jit = jax.jit(init_fn, out_shardings=(device_master_sh,
                                                   device_opt_sh))
        master, opt_state = init_jit(params)
        if self.offload_active:
            try:
                master = jax.device_put(master, self.master_shardings)
                opt_state = jax.device_put(opt_state, opt_shardings)
            except Exception as e:
                logger.warning(
                    "optimizer offload unsupported for this mesh/layout "
                    "(%s); keeping optimizer state in device memory",
                    str(e).splitlines()[0][:120])
                self.offload_active = False
                self.master_shardings = device_master_sh
                opt_shardings = device_opt_sh
                # the first put may have committed master to host already
                master = jax.device_put(master, device_master_sh)
                opt_state = jax.device_put(opt_state, device_opt_sh)
        self.opt_shardings = opt_shardings
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            master=master,
            opt_state=opt_state,
            loss_scale=self.scaler.init(),
            skipped=jnp.zeros((), jnp.int32))

    def _init_state_nvme(self, params) -> TrainState:
        """ZeRO-Infinity init: fp32 master + zero moments written straight
        to NVMe (never materialized in HBM); the device keeps only the
        bf16 working copy in the compute layout — or, with param
        streaming, only the RESIDENT (non-layer) leaves."""
        if self._stream_params:
            from .param_stream import StreamedInfinityTrainer
            self._stream = StreamedInfinityTrainer(self, self._model,
                                                   params)
            self.opt_shardings = ()
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                master=self._stream.resident,
                opt_state=(),
                loss_scale=self.scaler.init(),
                skipped=jnp.zeros((), jnp.int32))
        dev_sh = jax.tree.map(
            lambda sh: NamedSharding(self.topology.mesh, sh.spec),
            self.master_shardings)
        cast = jax.jit(
            lambda p: jax.tree.map(
                lambda x: x.astype(self.compute_dtype), p),
            out_shardings=dev_sh)
        master = cast(params)
        if self.offload_active:
            try:
                master = jax.device_put(master, self.master_shardings)
            except Exception as e:
                logger.warning(
                    "param offload unsupported for this mesh/layout (%s); "
                    "keeping the working copy in device memory",
                    str(e).splitlines()[0][:120])
                self.offload_active = False
                self.master_shardings = dev_sh
        # multi-host: masters partition into per-process fragments along
        # the GRADIENT layout — the layout step grads arrive in, so every
        # process's update reads only addressable shards (reference:
        # per-rank swap, stage3.py:614)
        self._nvme_grad_sh = jax.tree.map(
            lambda sp: NamedSharding(self.topology.mesh, sp),
            self.grad_specs, is_leaf=lambda x: isinstance(x, P))
        self._nvme_reshard_fn = None
        self._nvme.initialize(params, shardings=self._nvme_grad_sh)
        self.opt_shardings = ()
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            master=master,
            opt_state=(),
            loss_scale=self.scaler.init(),
            skipped=jnp.zeros((), jnp.int32))

    @property
    def state_shardings(self) -> TrainState:
        return TrainState(
            step=self.repl, master=self.master_shardings,
            opt_state=self.opt_shardings,
            loss_scale=LossScaleState(self.repl, self.repl, self.repl),
            skipped=self.repl)

    # ------------------------------------------------------------------
    # data-efficiency family (reference: engine.py:288,346-356 —
    # curriculum/random-LTD/PLD/MoQ hooks driven purely by the config)
    # ------------------------------------------------------------------
    def _setup_data_efficiency(self) -> None:
        cfg = self.config
        self.curriculum = None
        self.curriculum_sampler = None
        ccfg = cfg.curriculum_learning
        de = cfg.data_efficiency
        if de.enabled and de.data_sampling.enabled \
                and de.data_sampling.curriculum_learning.enabled:
            ccfg = de.data_sampling.curriculum_learning
        if ccfg.enabled:
            from .data_pipeline import (CurriculumDataSampler,
                                        CurriculumScheduler)

            def sched():
                return CurriculumScheduler({
                    "min_difficulty": ccfg.min_difficulty,
                    "max_difficulty": ccfg.max_difficulty,
                    "schedule_type": ccfg.schedule_type,
                    "schedule_config": ccfg.schedule_config})

            if ccfg.curriculum_type == "seqlen":
                # batch-shape curriculum: the engine truncates each batch
                # in _data_efficiency_pre_step
                self.curriculum = sched()
            else:
                # metric-indexed curriculum: any DataAnalyzer metric drives
                # *sampling order* (reference: data_sampler.py consuming
                # index files produced by data_analyzer.py) — consumed via
                # curriculum_dataloader()/curriculum_sampler
                if not ccfg.data_analyzer_path:
                    raise ConfigError(
                        f"curriculum_type={ccfg.curriculum_type!r}: a "
                        "metric curriculum needs data_analyzer_path "
                        "pointing at a DataAnalyzer save dir containing "
                        f"{ccfg.curriculum_type}/sample_to_metric.npy")
                try:
                    self.curriculum_sampler = CurriculumDataSampler\
                        .from_analyzer(
                            ccfg.data_analyzer_path, ccfg.curriculum_type,
                            sched(), self.train_batch_size, seed=cfg.seed)
                except FileNotFoundError as e:
                    raise ConfigError(
                        f"curriculum_type={ccfg.curriculum_type!r}: no "
                        f"analyzer index under "
                        f"{ccfg.data_analyzer_path!r} ({e}); run "
                        "runtime.data_analyzer.DataAnalyzer first") from e

        self.pld = None
        if cfg.progressive_layer_drop.enabled:
            if not getattr(self.loss_fn, "uses_pld", False):
                raise ConfigError(
                    "progressive_layer_drop: this loss_fn does not "
                    "consume _pld_theta — initialize with model=")
            from .progressive_layer_drop import ProgressiveLayerDrop
            self.pld = ProgressiveLayerDrop(
                cfg.progressive_layer_drop.theta,
                cfg.progressive_layer_drop.gamma)

        self._ltd_cfg = None
        self._ltd_sched = None
        self._ltd_keep = None
        rl = de.data_routing.random_ltd
        if de.enabled and de.data_routing.enabled and rl.enabled:
            if not hasattr(self.loss_fn, "with_ltd"):
                raise ConfigError(
                    "random_ltd: this loss_fn has no with_ltd hook — "
                    "initialize with model=")
            self._ltd_base_loss = self.loss_fn
            # max_value=0 means "the batch's seqlen" — resolved against
            # the first batch (the scheduler needs the real target or the
            # anneal overshoots at step 1 and silently disables LTD)
            self._ltd_cfg = rl

        self.moq = None
        qt = cfg.quantize_training
        if qt.enabled:
            from .quantize import Quantizer
            self.moq = Quantizer(
                q_start_bits=qt.start_bits, q_target_bits=qt.target_bits,
                q_period=qt.quantize_period, q_groups=qt.quantize_groups)
            self._moq_bits = None
            self._moq_eig0 = None
            self._eig = None
            if qt.eigenvalue.enabled:
                from .eigenvalue import Eigenvalue
                self._eig = Eigenvalue(max_iter=qt.eigenvalue.max_iter,
                                       tol=qt.eigenvalue.tol,
                                       stability=qt.eigenvalue.stability)

    def _data_efficiency_pre_step(self, batch, rng):
        """Apply the scheduled per-step transforms; returns the possibly
        modified batch (host-side, before sharding)."""
        step = self.global_steps
        if self.curriculum is not None:
            from .data_pipeline import truncate_to_difficulty
            batch = truncate_to_difficulty(
                batch, self.curriculum.get_difficulty(step + 1))
        if self._ltd_cfg is not None:
            from .data_pipeline import RandomLTDScheduler
            S = int(np.shape(batch["input_ids"])[1])
            max_t = min(self._ltd_cfg.max_value or S, S)
            if self._ltd_sched is None or self._ltd_sched.max != max_t:
                self._ltd_sched = RandomLTDScheduler(
                    total_layers=0,
                    start_tokens=min(self._ltd_cfg.min_value, max_t),
                    max_tokens=max_t,
                    schedule_steps=self._ltd_cfg.require_steps,
                    step_size=self._ltd_cfg.seq_per_step)
            keep = min(self._ltd_sched.kept_tokens(step), S)
            keep_eff = None if keep >= S else keep
            if keep_eff != self._ltd_keep:
                self._ltd_keep = keep_eff
                self.loss_fn = (self._ltd_base_loss if keep_eff is None
                                else self._ltd_base_loss.with_ltd(keep_eff))
                self._train_step_fn = self._warmup_step_fn = None
                self._eval_step_fn = None
                self._nvme_step_fn = None
        if self.pld is not None:
            # injected BEFORE the MoQ block: _measure_eigenvalue slices
            # this batch and traces the pld-consuming loss
            theta = self.pld.update_state(step)
            B = int(np.shape(batch["input_ids"])[0])
            batch = dict(batch)
            # per-row column: survives batch sharding / the gas reshape;
            # the loss reads element 0 of its local shard
            batch["_pld_theta"] = np.full((B,), theta, np.float32)
        if self.moq is not None:
            qt = self.config.quantize_training
            bits = self.moq.current_bits(step)
            boundary = (step > 0 and step % self.moq.period == 0
                        and bits > self.moq.target_bits)
            if self._eig is not None and boundary:
                # eigenvalue pacing (reference: eigenvalue-scheduled MoQ):
                # growing curvature postpones the next bit reduction
                eig = self._measure_eigenvalue(batch, rng)
                if self._moq_eig0 is None:
                    self._moq_eig0 = abs(eig)
                elif abs(eig) > 1.5 * self._moq_eig0:
                    self.moq.period *= 2
                    logger.info(
                        f"MoQ: |eigenvalue| grew {abs(eig):.3g} vs "
                        f"{self._moq_eig0:.3g}; quantize_period -> "
                        f"{self.moq.period}")
                    bits = self.moq.current_bits(step)
            if bits != self._moq_bits:
                self._moq_bits = bits
                self._train_step_fn = self._warmup_step_fn = None
                self._eval_step_fn = None
                self._nvme_step_fn = None
                if hasattr(self, "_compute_params_fn"):
                    del self._compute_params_fn
        return batch

    def curriculum_dataloader(self, data, **kwargs):
        """Build a :class:`~deepspeed_tpu.runtime.dataloader.DataLoader`
        whose sampling order follows the configured metric curriculum
        (reference: engine.deepspeed_io attaching DeepSpeedDataSampler).
        Only valid when a non-seqlen ``curriculum_type`` is configured."""
        if self.curriculum_sampler is None:
            raise ConfigError(
                "curriculum_dataloader() needs a metric curriculum "
                "(curriculum_learning with curriculum_type != 'seqlen' "
                "and data_analyzer_path set)")
        from .dataloader import DataLoader
        return DataLoader(data, self.train_batch_size,
                          sampler=self.curriculum_sampler, **kwargs)

    def _measure_eigenvalue(self, batch, rng) -> float:
        """Dominant Hessian eigenvalue of the micro-loss at the current
        params (host-driven power iteration; period boundaries only)."""
        micro = jax.tree.map(lambda x: np.asarray(x)[:self.micro_batch_size],
                             batch)
        cparams = self._compute_params(self.state.master)

        def scalar_loss(p):
            out = self.loss_fn(p, micro, rng)
            return out[0] if isinstance(out, tuple) else out

        eig, _ = self._eig.compute_eigenvalue(scalar_loss, cparams, rng)
        return float(eig)

    # ------------------------------------------------------------------
    # the train step
    # ------------------------------------------------------------------
    def _compute_params(self, master):
        """Cast fp32 master → compute dtype, re-shard to the compute-param
        layout.  For ZeRO 1/2 this makes XLA all-gather in the *compute*
        dtype (half the bytes of an fp32 gather) — the comm-pattern analog
        of all_gather_dp_groups of fp16 shards (stage_1_and_2.py:1823)."""
        offloaded = self.offload_active
        qwz = self.config.zero_optimization.zero_quantized_weights

        def cast(p, spec, msh):
            if offloaded and getattr(msh, "memory_kind", None) == "pinned_host":
                # host->HBM transfer first (jit-legal device_put), then cast
                p = jax.device_put(p, NamedSharding(
                    self.topology.mesh, msh.spec, memory_kind="device"))
            if qwz:
                q = self._qwz_gather(p, msh.spec, spec)
                if q is not None:
                    return q.astype(self.compute_dtype)
            c = p.astype(self.compute_dtype)
            return jax.lax.with_sharding_constraint(
                c, NamedSharding(self.topology.mesh, spec))
        out = jax.tree.map(cast, master, self.param_specs,
                           self.master_shardings)
        if qwz and not getattr(self, "_qwz_applied", False) \
                and not getattr(self, "_qwz_noop_warned", False):
            # plain stage 3: compute and master layouts coincide, so the
            # per-use gathers live inside the model's XLA program where
            # this explicit path can't reach; combine qwZ with hpZ or
            # offload for an actual quantized gather boundary
            self._qwz_noop_warned = True
            logger.warning(
                "zero_quantized_weights: no parameter has a "
                "master->compute gather boundary under this config; "
                "weight gathers stay full-precision (combine with "
                "zero_hpz_partition_size or offload, or use stage<=2)")
        bits = getattr(self, "_moq_bits", None)
        if bits is not None and bits <= 8:
            # MoQ: fake-quantize 2-D+ weights in the forward at the
            # scheduled bit width (reference: quantize_weight_in_forward)
            from ..compression.compress import weight_quantization
            g = self.config.quantize_training.quantize_groups
            out = jax.tree.map(
                lambda w: weight_quantization(w, bits=bits, groups=g)
                if hasattr(w, "ndim") and w.ndim >= 2 else w, out)
        return out

    def _qwz_gather(self, p, mspec, pspec):
        """qwZ: int8-quantized weight all-gather (ZeRO++; reference:
        CUDAQuantizer partition_parameters.py:753, zeropp.md — 2x less
        all-gather traffic).  Replaces the implicit XLA gather from the
        master layout to the compute layout with an explicit shard_map
        int8 gather over the extra (fsdp/data) axes.  Returns None when
        the leaf has no extra sharded axes (nothing to gather)."""
        def axes_of(entry):
            if entry is None:
                return ()
            return (entry,) if isinstance(entry, str) else tuple(entry)

        ndim = len(np.shape(p))
        ments = list(mspec) + [None] * (ndim - len(list(mspec)))
        pents = list(pspec) + [None] * (ndim - len(list(pspec)))
        extra = []
        for d in range(ndim):
            gather_axes = [a for a in axes_of(ments[d])
                           if a not in axes_of(pents[d])
                           and self.topology.axis_sizes.get(a, 1) > 1]
            if gather_axes:
                extra.append((d, gather_axes))
        if not extra:
            return None
        self._qwz_applied = True
        from ..ops.quant import quantized_all_gather

        def local(x):
            for d, axes in extra:
                # minor axis first: sharding (a, b) splits the dim
                # a-major, so reconstruct b-blocks inside each a-block
                for ax in reversed(axes):
                    x = quantized_all_gather(x, ax, bits=8, gather_dim=d)
            return x

        # check_vma can't statically prove the all_gather output is
        # replicated along the gathered axes
        return shard_map(local, mesh=self.topology.mesh,
                             in_specs=mspec, out_specs=pspec,
                             check_vma=False)(p)

    # ------------------------------------------------------------------
    # qgZ: quantized gradient reduction (ZeRO++ third leg)
    # ------------------------------------------------------------------
    def _qgz_manual_axes(self) -> Tuple[str, ...]:
        """Mesh axes whose gradient reduction runs through the explicit
        int8 collectives instead of XLA's implicit fp32 reduce.

        data always; fsdp only through stage 2 — at stage 3 the compute
        params are fsdp-sharded and must stay under XLA auto-sharding for
        the per-use gathers, so fsdp-axis reductions of the few replicated
        (persistent) leaves remain full-precision."""
        if not self.config.zero_optimization.zero_quantized_gradients:
            return ()
        return self._manual_reduce_axes("zero_quantized_gradients")

    def _sparse_manual_axes(self, params) -> Tuple[str, ...]:
        """Mesh axes for the sparse embedding-grad reduction
        (config.sparse_gradients; reference: sparse_gradients_enabled +
        engine.py sparse_allreduce_bucket)."""
        if not self.config.sparse_gradients:
            return ()
        if self.config.zero_optimization.zero_quantized_gradients:
            self._degrade("sparse_gradients + zero_quantized_gradients: "
                          "qgZ takes the manual reduction; "
                          "sparse_gradients is dropped")
            return ()
        # tied embeddings feed the unembed projection: the table's grad
        # is DENSE over the vocab and row-capacity truncation would
        # silently corrupt it.  Untied models carry a separate lm_head
        # leaf — absence means tied; warn-and-disable.
        from ..parallel.zero import _is_axes
        a_flat = jax.tree.leaves(self.param_axes, is_leaf=_is_axes)
        has_vocab_table = any(
            isinstance(a, tuple) and len(a) >= 2 and a[0] == "vocab"
            for a in a_flat)
        untied = isinstance(params, dict) and "lm_head" in params
        if has_vocab_table and not untied:
            self._degrade("sparse_gradients: model ties embeddings (no "
                          "lm_head leaf) — the vocab-table gradient is "
                          "dense; sparse_gradients is dropped")
            return ()
        return self._manual_reduce_axes("sparse_gradients")

    def _degrade(self, msg: str) -> None:
        """Unsupported feature combination: hard error unless the config
        opts into degradation (``allow_feature_degradation``) — silently
        weaker training is worse than a loud stop (the reference composes
        e.g. 1-bit with PP; we do not yet)."""
        if self.config.allow_feature_degradation:
            logger.warning(msg)
            return
        from ..config.config import ConfigError
        raise ConfigError(
            msg + " — set allow_feature_degradation=true to run anyway "
            "with the plain reduction")

    def _manual_reduce_axes(self, feature: str) -> Tuple[str, ...]:
        sizes = self.topology.axis_sizes
        if sizes.get("pipe", 1) > 1 or sizes.get("seq", 1) > 1:
            # both wrap the loss in their own shard_map (pipeline stages /
            # Ulysses all_to_all), which cannot nest inside the manual
            # region
            self._degrade(f"{feature} is not composable with pipeline "
                          "or sequence parallelism yet")
            return ()
        from ..compat import _MODERN
        if not _MODERN and (self.zero.stage >= 3
                            or sizes.get("tensor", 1) > 1
                            or sizes.get("expert", 1) > 1):
            # jaxlib 0.4.x CHECK-crashes (uncatchable process abort) in
            # backend_compile on partial-manual shard_map programs whose
            # auto region carries real sharding (stage-3 param gathers,
            # tensor-parallel layers, expert-parallel MoE grads); loud
            # stop instead of a crash (compat.shard_map also refuses)
            self._degrade(f"{feature} does not compose with zero stage 3, "
                          "tensor or expert parallelism on legacy jaxlib "
                          "(XLA CHECK-crashes compiling the partial-manual"
                          " reduction); upgrade jax")
            return ()
        axes = []
        if sizes.get(DATA_AXIS, 1) > 1:
            axes.append(DATA_AXIS)
        if self.zero.stage <= 2 and sizes.get(FSDP_AXIS, 1) > 1:
            axes.append(FSDP_AXIS)
        if not axes:
            logger.warning(f"{feature}: no multi-device reduction axis "
                           "on this mesh; ignoring")
        return tuple(axes)

    @staticmethod
    def _restrict_spec(spec: P, manual: Tuple[str, ...]) -> P:
        """PartitionSpec with only the ``manual`` axes kept (the rest of
        the sharding stays with the auto axes of the partial shard_map)."""
        out = []
        for e in spec:
            if e is None:
                out.append(None)
                continue
            ax = (e,) if isinstance(e, str) else tuple(e)
            kept = tuple(a for a in ax if a in manual)
            out.append(kept[0] if len(kept) == 1 else (kept or None))
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def _build_qgz_grads(self, gas: int):
        """Per-microbatch gradient function with explicit quantized
        reduction (reference: qgZ — all_to_all_quant_reduce,
        runtime/comm/coalesced_collectives.py + quant_reduce.cu;
        docs/_tutorials/zeropp.md:12-17 4x comm-volume claim).

        Per grad leaf: axes appearing in its grad spec get an int8
        reduce-scatter onto the owner shard (dequant-reduce on arrival);
        axes the leaf replicates over get an int8 reduce-scatter +
        all-gather."""
        from ..ops.quant import (quantized_all_reduce,
                                 quantized_psum_scatter_dim)

        manual = self._qgz_axes

        def reduce_leaf(g, spec, axes, batch_tokens):
            ents = list(spec) + [None] * (g.ndim - len(list(spec)))
            seen = set()
            for d, e in enumerate(ents):
                if e is None:
                    continue
                ax = (e,) if isinstance(e, str) else tuple(e)
                # major -> minor: scatter in entry order lands each
                # (outer, inner) coordinate on its owner shard
                for a in ax:
                    if a in manual:
                        g = quantized_psum_scatter_dim(g, a, dim=d)
                        seen.add(a)
            for a in manual:
                if a not in seen:
                    g = quantized_all_reduce(g, a)
            return g

        return self._build_manual_grads(gas, manual, reduce_leaf)

    def _build_comm_grads(self, gas: int):
        """Per-microbatch gradients with tile-decomposed (T3, arxiv
        2401.16677) and optionally quantized (EQuARX, arxiv 2506.17615)
        explicit reduction over the DP axes — config ``comm:
        {overlap, tiles, quantized_allreduce}``.

        Per grad leaf: axes appearing in its grad spec get a tiled
        reduce-scatter onto the owner shard, axes the leaf replicates
        over get a tiled all-reduce.  Each tile's collective carries no
        dependency on the next tile (or the next microbatch's backward
        GEMMs), so XLA may co-schedule them; the default exact rung is
        bitwise-identical to the plain reduction (parity-tested), the
        quantized rung rides the qgZ int8/int4 wire."""
        from ..comm import overlap as ov

        manual = self._comm_axes
        ccfg = self.config.comm
        tiles = ccfg.tiles if ccfg.overlap else 1
        qbits = {None: None, "int8": 8, "int4": 4}[
            ccfg.quantized_allreduce]
        sizes = self.topology.axis_sizes

        def plan(spec, ndim):
            """(scatter ops [(axis, dim)...] in entry order, leftover
            all-reduce axes) for one leaf — the same major->minor walk
            the qgZ reduce_leaf does."""
            ents = list(spec) + [None] * (ndim - len(list(spec)))
            scat, seen = [], set()
            for d, e in enumerate(ents):
                if e is None:
                    continue
                ax = (e,) if isinstance(e, str) else tuple(e)
                for a in ax:
                    if a in manual:
                        scat.append((a, d))
                        seen.add(a)
            return scat, tuple(a for a in manual if a not in seen)

        def reduce_leaf(g, spec, axes, batch_tokens):
            scat, rest = plan(spec, g.ndim)
            for a, d in scat:
                g = ov.overlapped_reduce_scatter(
                    g, a, scatter_dim=d, tiles=tiles, quant_bits=qbits)
            for a in rest:
                g = ov.overlapped_all_reduce(g, a, tiles=tiles,
                                             quant_bits=qbits)
            return g

        # static wire accounting (host arithmetic mirroring reduce_leaf;
        # bumped once per train_batch in _finish_step): the shapes and
        # specs fully determine what one microbatch's grad sync moves
        isz = jnp.dtype(self.compute_dtype).itemsize
        wire = {"ops_exact": 0, "ops_quant": 0, "tiles": 0,
                "bytes_exact": 0.0, "bytes_quant": 0.0}
        s_flat = jax.tree.leaves(self.grad_specs,
                                 is_leaf=lambda x: isinstance(x, P))
        shp_flat = jax.tree.leaves(self.param_shapes,
                                   is_leaf=lambda x: isinstance(x, tuple))
        for spec, shp in zip(s_flat, shp_flat):
            scat, rest = plan(spec, len(shp))
            shape = list(shp)
            for a, d in scat:
                n = sizes[a]
                elems = int(np.prod(shape)) if shape else 1
                kind = "quant" if qbits else "exact"
                wire[f"bytes_{kind}"] += ov.wire_bytes(
                    "reduce_scatter", elems, isz, n, qbits)
                wire[f"ops_{kind}"] += 1
                td = ov._rs_tile_dim(tuple(shape), d, tiles)
                wire["tiles"] += (ov._resolve_tiles(shape[td], tiles)
                                  if td is not None else 1)
                shape[d] //= n
            for a in rest:
                n = sizes[a]
                elems = int(np.prod(shape)) if shape else 1
                kind = "quant" if (qbits and shape) else "exact"
                wire[f"bytes_{kind}"] += ov.wire_bytes(
                    "all_reduce", elems, isz, n,
                    qbits if shape else None)
                wire[f"ops_{kind}"] += 1
                wire["tiles"] += (ov._resolve_tiles(shape[0], tiles)
                                  if shape else 1)
        self._comm_wire = wire

        return self._build_manual_grads(gas, manual, reduce_leaf)

    def _build_sparse_grads(self, gas: int):
        """Per-microbatch gradients with SPARSE reduction of embedding
        grads (reference: runtime/sparse_tensor.py + engine.py:2518
        sparse_allreduce_bucket): vocab-leading leaves travel as
        (row ids, rows) over the DP axes — capacity one row per shard
        token, so the reduction is lossless for pure-lookup embeddings.
        NOTE: tied embeddings receive a DENSE unembed gradient; enable
        only for untied models (capacity would truncate by row mass)."""
        from .sparse_grads import is_sparse_leaf, sparse_psum

        manual = self._sparse_axes
        sizes = self.topology.axis_sizes

        def reduce_leaf(g, spec, axes, batch_tokens):
            ents = list(spec) + [None] * (g.ndim - len(list(spec)))
            seen = set()
            for d, e in enumerate(ents):
                if e is None:
                    continue
                ax = (e,) if isinstance(e, str) else tuple(e)
                for a in ax:
                    if a in manual:
                        g = jax.lax.psum_scatter(
                            g, a, scatter_dimension=d, tiled=True)
                        seen.add(a)
            rest = tuple(a for a in manual if a not in seen)
            if rest:
                if is_sparse_leaf(axes):
                    # a preceding psum_scatter (stage-2 fsdp grad layout)
                    # merged rows from every scattered peer into the
                    # local vocab slice — the lossless capacity is one
                    # row per token across ALL merged shards
                    merged = int(np.prod([sizes[a] for a in seen])) \
                        if seen else 1
                    g = sparse_psum(
                        g, rest,
                        capacity=min(g.shape[0], batch_tokens * merged))
                else:
                    g = jax.lax.psum(g, rest)
            return g

        return self._build_manual_grads(gas, manual, reduce_leaf)

    def _build_local_grads(self, gas: int):
        """UNREDUCED per-shard gradients, stacked on a leading reduce-axes
        dim — the front half of the 1-bit compressed-communication step
        (the actual packed reduce happens once per step on the
        accumulated gradient, see ``_onebit_reduce``)."""
        manual = self._onebit_axes

        def reduce_leaf(g, spec, axes, batch_tokens):
            return g[None]                       # stack; no collective

        return self._build_manual_grads(gas, manual, reduce_leaf,
                                        stacked=True)

    def _onebit_reduce(self, grads_stacked, err, m_prev, b1, denom):
        """The reference 1-bit step at the wire: each shard forms its
        LOCAL momentum ``b1*m + (1-b1)*g_local``, sends sign bits + one
        scale (error feedback local), and the mean of the per-shard
        reconstructions is the new global momentum
        (reference: OnebitAdam.step adam.py:198 + compressed_allreduce).

        Returns (pseudo_grads, new_err): feeding
        ``(m_hat - b1*m_prev)/(1-b1)`` to the uncompressed-momentum
        optimizer makes its ``m`` land exactly on ``m_hat``."""
        from ..ops.quant import onebit_all_reduce

        manual = self._onebit_axes
        mesh = self.topology.mesh
        spec_in = jax.tree.map(lambda _: P(manual), grads_stacked)
        rep = jax.tree.map(lambda _: P(), grads_stacked)

        def local(gs, es, ms):
            def one(g, e, m):
                m_loc = b1 * m + (1 - b1) * (g[0].astype(jnp.float32)
                                             / denom)
                return onebit_all_reduce(m_loc, manual, e[0])
            outs = jax.tree.map(one, gs, es, ms)
            m_hat = jax.tree.map(lambda o: o[0], outs,
                                 is_leaf=lambda x: isinstance(x, tuple))
            e_new = jax.tree.map(lambda o: o[1][None], outs,
                                 is_leaf=lambda x: isinstance(x, tuple))
            return m_hat, e_new

        m_hat, new_err = shard_map(
            local, mesh=mesh,
            in_specs=(spec_in, spec_in, rep),
            out_specs=(rep, spec_in),
            axis_names=set(manual),
            check_vma=False)(grads_stacked, err, m_prev)
        pseudo = jax.tree.map(lambda mh, m: (mh - b1 * m) / (1 - b1),
                              m_hat, m_prev)
        return pseudo, new_err

    def _build_manual_grads(self, gas: int, manual: Tuple[str, ...],
                            reduce_leaf, stacked: bool = False):
        """Shared scaffolding for explicitly-reduced gradient paths (qgZ,
        sparse, 1-bit): shard_map *manual* over the reduce axes and auto
        elsewhere (TP collectives stay compiler-placed)."""
        mesh = self.topology.mesh
        sizes = self.topology.axis_sizes
        nred = int(np.prod([sizes[a] for a in manual]))

        grad_specs = self.grad_specs
        p_in = jax.tree.map(lambda s: self._restrict_spec(s, manual),
                            self.param_specs,
                            is_leaf=lambda x: isinstance(x, P))
        if stacked:
            # leading dim = the reduce-axes product; no manual axes on
            # the unreduced leaf dims (every shard keeps its full local
            # gradient)
            g_out = jax.tree.map(lambda s: P(manual), grad_specs,
                                 is_leaf=lambda x: isinstance(x, P))
        else:
            g_out = jax.tree.map(
                lambda s: self._restrict_spec(s, manual),
                grad_specs, is_leaf=lambda x: isinstance(x, P))
        batch_spec = P(self._restrict_spec(
            P((DATA_AXIS, FSDP_AXIS)), manual)[0])

        def local(cparams, batch, rng, scale):
            idx = jnp.int32(0)
            for a in manual:
                idx = idx * sizes[a] + jax.lax.axis_index(a)
            rng = jax.random.fold_in(rng, idx)

            def scaled_loss(p):
                loss, aux = self._micro_loss(p, batch, rng)
                return loss * scale / gas, (loss, aux)

            (_, (loss, aux)), grads = jax.value_and_grad(
                scaled_loss, has_aux=True)(cparams)
            batch_tokens = int(jax.tree.leaves(batch)[0].size)
            g_flat, treedef = jax.tree.flatten(grads)
            s_flat = jax.tree.leaves(grad_specs,
                                     is_leaf=lambda x: isinstance(x, P))
            from ..parallel.zero import _is_axes
            a_flat = jax.tree.leaves(self.param_axes, is_leaf=_is_axes)
            # the three trees were flattened independently: a leaf-count
            # drift (e.g. bare None leaves in user param_axes, which
            # jax.tree.leaves drops) would silently mis-pair specs with
            # gradients and apply the wrong reduction
            if not (len(g_flat) == len(s_flat) == len(a_flat)):
                raise ValueError(
                    f"manual-reduction tree mismatch: {len(g_flat)} grads "
                    f"vs {len(s_flat)} specs vs {len(a_flat)} param_axes "
                    "leaves (param_axes must annotate every parameter "
                    "leaf)")
            grads = jax.tree.unflatten(treedef, [
                reduce_leaf(g, s, a, batch_tokens)
                for g, s, a in zip(g_flat, s_flat, a_flat)])
            if not stacked:
                # local losses are means over the local batch shard; the
                # global mean divides the reduced sums by the rank count
                grads = jax.tree.map(
                    lambda g: (g / nred).astype(g.dtype), grads)
            loss = jax.lax.psum(loss, manual) / nred
            aux = jax.tree.map(lambda a: jax.lax.psum(a, manual) / nred, aux)
            return loss, aux, grads

        def manual_grads(cparams, batch, rng, scale):
            mb_specs = jax.tree.map(lambda _: batch_spec, batch)
            return shard_map(
                local, mesh=mesh,
                in_specs=(p_in, mb_specs, P(), P()),
                out_specs=(P(), P(), g_out),
                axis_names=set(manual),     # auto everywhere else: TP/fsdp
                check_vma=False,            # shardings stay compiler-placed
            )(cparams, batch, rng, scale)

        return manual_grads

    def _offload_update(self, grads, opt_state, master, step, finite):
        """ZeRO-Offload optimizer step: fp32 master + moments live in host
        DRAM and the update executes as XLA host compute — the TPU analog
        of the reference's DeepSpeedCPUAdam path (stage_1_and_2.py
        cpu_offload + csrc/adam/cpu_adam_impl.cpp), with the
        compiler-scheduled grad HBM->host stream standing in for the
        hand-rolled async grad copy (async_accumulate_grad_in_cpu_via_gpu,
        stage_1_and_2.py:1190).

        Runs inside shard_map: under manual sharding every op carries a
        sharding, which the SPMD partitioner requires of memory-space
        transfer annotations (a *replicated* transfer is inexpressible —
        the reason replicated leaves stay in HBM, see _build_shardings)."""
        from jax.experimental.compute_on import compute_on

        opt_specs = jax.tree.map(lambda sh: sh.spec, self.opt_shardings)

        def host_flags(shardings):
            return jax.tree.map(
                lambda sh: getattr(sh, "memory_kind", None) == "pinned_host",
                shardings)

        m_host, o_host = (host_flags(self.master_shardings),
                          host_flags(self.opt_shardings))

        def put(tree, flags, space):
            # host-flagged leaves never move (host is both where they
            # arrive and where they belong); the rest transfer to `space`
            # — Host on entry for the update, Device on exit to restore.
            return jax.tree.map(
                lambda x, h: x if h else jax.device_put(x, space),
                tree, flags)

        def local(g, o, m, step, finite):
            g = jax.tree.map(
                lambda x: jax.device_put(x, jax.memory.Space.Host), g)
            o = put(o, o_host, jax.memory.Space.Host)
            m = put(m, m_host, jax.memory.Space.Host)
            step_h = jax.device_put(step, jax.memory.Space.Host)
            finite_h = jax.device_put(finite, jax.memory.Space.Host)
            with compute_on("device_host"):
                updates, new_o = self.optimizer.update(g, o, m, step_h)
                new_m = jax.tree.map(lambda p, u: p + u, m, updates)

                def sel(new, old):
                    return jax.tree.map(
                        lambda a, b: jnp.where(finite_h, a, b), new, old)
                new_m, new_o = sel(new_m, m), sel(new_o, o)
            # leaves that live in HBM go back before leaving the region
            new_m = put(new_m, m_host, jax.memory.Space.Device)
            new_o = put(new_o, o_host, jax.memory.Space.Device)
            return new_m, new_o

        return shard_map(
            local, mesh=self.topology.mesh,
            in_specs=(self.master_specs, opt_specs, self.master_specs,
                      P(), P()),
            out_specs=(self.master_specs, opt_specs),
        )(grads, opt_state, master, step, finite)

    def _micro_loss(self, cparams, batch, rng):
        out = self.loss_fn(cparams, batch, rng)
        if isinstance(out, tuple):
            loss, aux = out
        else:
            loss, aux = out, {}
        return loss, aux

    def _build_grad_pipeline(self, gas: int):
        """(cparams, batch, rng, scale) -> (loss, aux, fp32 grads in the
        ZeRO grad layout) — the shared front half of the device-resident
        and NVMe-offloaded train steps (gas scan = the IPG/bucketing
        analog, compiler-scheduled)."""
        qgz_grads = self._build_qgz_grads(gas) if self._qgz_axes else None
        if qgz_grads is None and self._sparse_axes:
            qgz_grads = self._build_sparse_grads(gas)
        if qgz_grads is None and self._comm_axes:
            qgz_grads = self._build_comm_grads(gas)
        stacked = bool(self._onebit_axes)
        if qgz_grads is None and stacked:
            qgz_grads = self._build_local_grads(gas)

        def grads_of_microbatch(cparams, batch, rng, scale):
            if qgz_grads is not None:
                return qgz_grads(cparams, batch, rng, scale)

            def scaled_loss(p):
                loss, aux = self._micro_loss(p, batch, rng)
                return loss * scale / gas, (loss, aux)
            (_, (loss, aux)), grads = jax.value_and_grad(
                scaled_loss, has_aux=True)(cparams)
            return loss, aux, grads

        if stacked:
            acc_specs = jax.tree.map(
                lambda _: P(self._onebit_axes), self.grad_specs,
                is_leaf=lambda x: isinstance(x, P))
        else:
            acc_specs = self.grad_specs

        def shard_grads(g):
            return jax.tree.map(
                lambda t, spec: jax.lax.with_sharding_constraint(
                    t, NamedSharding(self.topology.mesh, spec)),
                g, acc_specs)

        def pipeline(cparams, batch, rng, scale):
            if gas > 1:
                # batch leaves have leading [gas, ...]; scan accumulates
                # fp32 grads in the ZeRO grad layout (reduce-scattered for
                # stage>=2)
                def body(acc, xs):
                    mb, r = xs
                    loss, aux, g = grads_of_microbatch(cparams, mb, r, scale)
                    g = shard_grads(jax.tree.map(
                        lambda t: t.astype(jnp.float32), g))
                    acc_g, acc_loss = acc
                    acc_g = jax.tree.map(jnp.add, acc_g, g)
                    return (acc_g, acc_loss + loss), aux

                W = int(np.prod([self.topology.axis_sizes[a]
                                 for a in self._onebit_axes])) \
                    if stacked else 1
                zero_g = jax.tree.map(
                    lambda p, spec: jax.lax.with_sharding_constraint(
                        jnp.zeros(((W,) if stacked else ())
                                  + tuple(np.shape(p)), jnp.float32),
                        NamedSharding(self.topology.mesh, spec)),
                    cparams, acc_specs)
                rngs = jax.random.split(rng, gas)
                (grads, loss_sum), aux = jax.lax.scan(
                    body, (zero_g, jnp.float32(0.0)), (batch, rngs))
                loss = loss_sum / gas
                aux = jax.tree.map(lambda a: a[-1], aux)
            else:
                loss, aux, grads = grads_of_microbatch(cparams, batch, rng,
                                                       scale)
                grads = shard_grads(jax.tree.map(
                    lambda t: t.astype(jnp.float32), grads))
            return loss, aux, grads

        return pipeline

    def _build_grad_epilogue(self):
        """Shared back half of both step builders: unscale (+ predivide,
        reference: prescale_gradients), overflow check, clip."""
        use_scaling = self.precision == "fp16"
        clip = self.config.gradient_clipping
        prescale = self.config.prescale_gradients
        predivide = self.config.gradient_predivide_factor

        def epilogue(grads, scale):
            denom = scale * (predivide if prescale else 1.0)
            grads = jax.tree.map(lambda g: g / denom, grads)
            finite = all_finite(grads) if use_scaling else jnp.asarray(True)
            grads, gnorm = clip_by_global_norm(grads, clip)
            return grads, finite, gnorm
        return epilogue

    def _build_train_step(self, onebit_compress: bool = True):
        gas = self.gas
        scaler = self.scaler
        use_scaling = self.precision == "fp16"
        offloaded = self.offload_active
        pipeline = self._build_grad_pipeline(gas)
        epilogue = self._build_grad_epilogue()

        onebit = bool(self._onebit_axes)
        opt_update = self.optimizer.update
        if onebit:
            # phase-aligned optimizer: the engine switches host-side on
            # global_steps, but the optimizer's own frozen flag counts
            # only APPLIED steps (state.step) — under fp16 overflow skips
            # the two drift apart.  Pin the optimizer to this compiled
            # step's phase instead of its step counter.
            opt_cfg = self.config.optimizer
            key = ("var_freeze_step" if "zeroone" in opt_cfg.type.lower()
                   else "freeze_step")
            phase_params = {**opt_cfg.params, "compress": False,
                            key: -1 if onebit_compress else (1 << 30)}
            from .optimizers import build_optimizer
            opt_update = build_optimizer(
                opt_cfg.type, self.lr_schedule, phase_params).update

        def train_step(state: TrainState, batch, rng):
            scale = state.loss_scale.scale if use_scaling else jnp.float32(1.0)
            cparams = self._compute_params(state.master)
            loss, aux, grads = pipeline(cparams, batch, rng, scale)
            opt_in = state.opt_state
            if onebit:
                # packed 1-bit momentum reduce with error feedback,
                # threaded through the opt state.  During warmup
                # (reference: exact allreduce until freeze_step) the
                # mean is exact and EF stays zero.
                err = opt_in.comm_err
                opt_in = opt_in.base
                if onebit_compress:
                    # loss-scale unscaling happens inside the reduce; the
                    # epilogue (called with scale=1) still applies the
                    # predivide factor exactly once
                    grads, new_err = self._onebit_reduce(
                        grads, err, opt_in.m, self._onebit_b1, scale)
                    grads, finite, gnorm = epilogue(grads,
                                                    jnp.float32(1.0))
                else:
                    grads = jax.tree.map(lambda g: g.mean(axis=0), grads)
                    new_err = err
                    grads, finite, gnorm = epilogue(grads, scale)
            else:
                grads, finite, gnorm = epilogue(grads, scale)

            # overflow → skip update (jnp.where keeps shapes static)
            def sel(new, old):
                return jax.tree.map(
                    lambda a, b: jnp.where(finite, a, b), new, old)

            # optimizer update on the (fsdp-sharded) master partition —
            # the local-adam-on-owned-shard of stage_1_and_2.py:1823.
            step_next = state.step + 1

            def update_master(grads, opt_state, master):
                updates, new_opt = opt_update(
                    grads, opt_state, master, step_next)
                new_master = jax.tree.map(lambda p, u: p + u, master, updates)
                return sel(new_master, master), sel(new_opt, opt_state)

            if offloaded:
                new_master, new_opt = self._offload_update(
                    grads, opt_in, state.master, step_next, finite)
            else:
                new_master, new_opt = update_master(
                    grads, opt_in, state.master)
            if onebit:
                new_opt = OnebitCommState(
                    base=new_opt, comm_err=sel(new_err, err))
            new_step = jnp.where(finite, step_next, state.step)
            new_scale_state = scaler.update(state.loss_scale, ~finite)
            new_skipped = state.skipped + jnp.where(finite, 0, 1)
            if offloaded:
                # mixed memory kinds make jit annotate every output's
                # placement; scalar outputs need an explicit (replicated)
                # sharding attached or the SPMD partitioner rejects the
                # annotation op (hlo->has_sharding() RET_CHECK).
                rep = lambda x: jax.lax.with_sharding_constraint(x, self.repl)
                new_step = rep(new_step)
                new_skipped = rep(new_skipped)
                new_scale_state = jax.tree.map(rep, new_scale_state)

            new_state = TrainState(
                step=new_step, master=new_master, opt_state=new_opt,
                loss_scale=new_scale_state,
                skipped=new_skipped)
            lr = self.lr_schedule(new_step.astype(jnp.float32))
            metrics = {
                "loss": loss.astype(jnp.float32),
                "grad_norm": gnorm,
                "lr": lr,
                "loss_scale": state.loss_scale.scale,
                "overflow": (~finite).astype(jnp.int32),
                **{f"aux/{k}": v for k, v in aux.items()},
            }
            return new_state, metrics

        state_sh = self.state_shardings
        return jax.jit(
            train_step,
            in_shardings=(state_sh, None, None),
            out_shardings=(state_sh, None),
            donate_argnums=() if offloaded else (0,))

    # ------------------------------------------------------------------
    # ZeRO-Infinity step (NVMe-backed optimizer state)
    # ------------------------------------------------------------------
    def _build_nvme_step(self):
        """Device half of the ZeRO-Infinity step: grads + overflow check +
        clip, returning the gradients for the host-side NVMe update
        (reference: stage3.py:2049 per-sub_group gather-step-swap loop;
        here the group loop lives in runtime/zero_infinity.py)."""
        gas = self.gas
        scaler = self.scaler
        use_scaling = self.precision == "fp16"
        pipeline = self._build_grad_pipeline(gas)
        epilogue = self._build_grad_epilogue()

        def nvme_step(state: TrainState, batch, rng):
            scale = state.loss_scale.scale if use_scaling else jnp.float32(1.0)
            cparams = self._compute_params(state.master)
            loss, aux, grads = pipeline(cparams, batch, rng, scale)
            grads, finite, gnorm = epilogue(grads, scale)
            new_scale_state = scaler.update(state.loss_scale, ~finite)
            metrics = {
                "loss": loss.astype(jnp.float32),
                "grad_norm": gnorm,
                "loss_scale": state.loss_scale.scale,
                "overflow": (~finite).astype(jnp.int32),
                **{f"aux/{k}": v for k, v in aux.items()},
            }
            return grads, finite, new_scale_state, metrics

        state_sh = self.state_shardings
        return jax.jit(nvme_step, in_shardings=(state_sh, None, None))

    def _train_batch_nvme(self, batch, rng) -> Dict[str, Any]:
        if self._stream is not None:
            # per-layer param streaming: the host loop IS the step
            self.tput.start()
            metrics = self._stream.train_batch(batch, rng)
            return self._finish_step(batch, rng, metrics)
        if self._nvme_step_fn is None:
            self._nvme_step_fn = self._build_nvme_step()
        batch = self.shard_batch(batch)
        self.tput.start()
        try:
            grads, finite, new_scale_state, metrics = \
                self._nvme_step_fn(self.state, batch, rng)
            finite_b = bool(np.asarray(finite))
        except jax.errors.JaxRuntimeError as e:
            if not self.offload_active or self._offload_validated:
                raise
            self._disable_offload(e)
            return self._train_batch_nvme(batch, rng)
        self._offload_validated = True

        step_next = int(np.asarray(self.state.step)) + 1
        lr = float(np.asarray(self.lr_schedule(np.float32(step_next))))
        if finite_b:
            flat_grads = jax.tree_util.tree_leaves(grads)
            new_master = self._nvme.step(flat_grads, lr, step_next)
            if self._nvme._multi:
                master = self._assemble_nvme_master(new_master)
            else:
                flat_sh = jax.tree_util.tree_leaves(
                    self.master_shardings,
                    is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
                dev_leaves = [
                    jax.device_put(m.astype(self.compute_dtype), sh)
                    for m, sh in zip(new_master, flat_sh)]
                master = jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(self.state.master),
                    dev_leaves)
            new_step = jnp.asarray(step_next, jnp.int32)
            skipped = self.state.skipped
        else:
            master = self.state.master
            new_step = self.state.step
            skipped = self.state.skipped + 1
        self.state = TrainState(
            step=new_step, master=master, opt_state=(),
            loss_scale=new_scale_state, skipped=skipped)
        metrics = dict(metrics)
        metrics["lr"] = jnp.float32(lr)
        return self._finish_step(batch, rng, metrics)

    def _assemble_nvme_master(self, frag_leaves):
        """Multi-host: build the device working copy from this process's
        updated master fragments — per-device buffers in the gradient
        layout, then one jitted reshard (XLA collectives over ICI) into
        the compute layout."""
        dt = self.compute_dtype
        flat_sh = jax.tree_util.tree_leaves(
            self._nvme_grad_sh,
            is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
        arrs = []
        for i, (frags, sh) in enumerate(zip(frag_leaves, flat_sh)):
            shape = self._nvme._leaf_meta[i][0]
            imap = sh.devices_indices_map(shape)
            fragmap = dict(zip(self._nvme._frags[i], frags))
            bufs = [jax.device_put(fragmap[tuple(imap[d])].astype(dt), d)
                    for d in sh.addressable_devices]
            arrs.append(jax.make_array_from_single_device_arrays(
                shape, sh, bufs))
        tree = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self.state.master), arrs)
        if self._nvme_reshard_fn is None:
            self._nvme_reshard_fn = jax.jit(
                lambda t: t, out_shardings=self.master_shardings)
        return self._nvme_reshard_fn(tree)

    # ------------------------------------------------------------------
    # public API (reference: engine.train_batch / forward+backward+step)
    # ------------------------------------------------------------------
    def train_batch(self, batch, rng: Optional[jax.Array] = None) -> Dict[str, Any]:
        """Run one full optimizer step (forward+backward+step fused).

        ``batch``: pytree of arrays with leading dim ``gas * micro`` (host-
        local view is fine under multi-host; see ``shard_batch``); with
        gas>1, leaves are reshaped to [gas, micro, ...] for the scan.
        """
        if self._cap is not None and self._cap.armed:
            # an armed deep-capture window opens at the step boundary
            # (the one profiler seam — tpulint: profiler-capture)
            self._cap.begin(step=self.global_steps)
        t0 = time.perf_counter()
        if rng is None:
            rng = jax.random.PRNGKey(self.config.seed + self.global_steps)
        if self.curriculum or self.pld or self._ltd_cfg or self.moq:
            batch = self._data_efficiency_pre_step(batch, rng)
        if self._nvme is not None:
            # the NVMe-streamed step runs as many per-layer programs; its
            # phases are not the four this instrumentation names
            return self._train_batch_nvme(batch, rng)
        t1 = time.perf_counter()
        step_fn = self._pick_train_step()
        batch = self.shard_batch(batch)
        t2 = time.perf_counter()
        self.tput.start()
        try:
            self.state, metrics = step_fn(self.state, batch, rng)
            if self.offload_active and not self._offload_validated:
                # dispatch is async: an unsupported host-compute path
                # surfaces at the first blocking fetch, which would land
                # OUTSIDE this try in the caller — force execution now so
                # the fallback can actually fire
                float(np.asarray(metrics["loss"]))
        except jax.errors.JaxRuntimeError as e:
            # only the *first* execution may fall back — a later failure is
            # a genuine runtime error, not a backend capability gap
            if not self.offload_active or self._offload_validated:
                raise
            self._disable_offload(e)
            self._train_step_fn = self._warmup_step_fn = None
            step_fn = self._pick_train_step()
            self.state, metrics = step_fn(self.state, batch, rng)
        self._offload_validated = True
        t3 = time.perf_counter()
        if self.devtel is not None:
            # cost probe once per program (post-call: the donated state
            # was rebound to the step's output, same avals), then
            # attribute this dispatch's flops/bytes from the table
            pkey = ("train_step_warmup"
                    if step_fn is self._warmup_step_fn else "train_step")
            if pkey not in self.devtel.program_costs:
                self.devtel.probe_program(pkey, step_fn,
                                          (self.state, batch, rng))
            self.devtel.on_dispatch(pkey)
        self._phase_ms["pre_step"].inc((t1 - t0) * 1e3)
        self._phase_ms["stage"].inc((t2 - t1) * 1e3)
        self._phase_ms["dispatch"].inc((t3 - t2) * 1e3)
        self._h_step_host.observe((t3 - t0) * 1e3)
        if self._anom is not None:
            # detectors fed from the timestamps above — no added reads
            self._feed_step_signals(t0, t3)
        tr = self.tracer
        if tr.enabled:
            # one track per phase — reuses the timestamps above, so
            # tracing adds no clock reads to the step path
            sid = self.global_steps + 1
            tr.record("pre_step", t0, t1, track="pre_step", step=sid)
            tr.record("stage", t1, t2, track="stage", step=sid)
            tr.record("dispatch", t2, t3, track="dispatch", step=sid)
        return self._finish_step(batch, rng, metrics)

    def _pick_train_step(self):
        """Standard jitted step, or — for 1-bit optimizers — the exact
        warmup step until ``freeze_step`` optimizer updates have run
        (reference: uncompressed allreduce during warmup, adam.py)."""
        if self._onebit_axes and self.global_steps < self._onebit_freeze:
            if self._warmup_step_fn is None:
                self._warmup_step_fn = self._build_train_step(
                    onebit_compress=False)
                self._note_compile("train_step_warmup")
            return self._warmup_step_fn
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
            self._note_compile("train_step")
        return self._train_step_fn

    def _finish_step(self, batch, rng, metrics) -> Dict[str, Any]:
        self.global_steps += 1
        self.global_samples += self.train_batch_size
        self._c_steps.inc()
        if self._comm_wire is not None:
            # one bump per train_batch: the gas per-microbatch explicit
            # reductions of the comm grad path (static accounting —
            # host arithmetic mirroring _build_comm_grads' reduce plan)
            w = self._comm_wire
            gas = self.gas
            if w["ops_exact"]:
                self._c_comm_ops.inc(w["ops_exact"] * gas, kind="exact")
                self._c_comm_bytes.inc(w["bytes_exact"] * gas,
                                       kind="exact")
            if w["ops_quant"]:
                self._c_comm_ops.inc(w["ops_quant"] * gas, kind="quant")
                self._c_comm_bytes.inc(w["bytes_quant"] * gas,
                                       kind="quant")
            self._c_comm_tiles.inc(w["tiles"] * gas)
        if self._cap is not None and self._cap.active:
            self._cap.end_step(step=self.global_steps)
        # metrics stay on device — a host fetch every step would stall the
        # async dispatch pipeline (and on tunneled TPUs pay a round trip
        # per value); fetch once, and only when someone actually looks
        self._last_metrics = metrics
        self._last_metrics_host = None
        self.tput.stop()
        fp_cfg = self.config.flops_profiler
        if fp_cfg.enabled and self.global_steps == fp_cfg.profile_step:
            self._write_flops_profile(batch, rng)
        need_host = (self.global_steps % self.config.steps_per_print == 0
                     or self.monitor is not None)
        if need_host:
            if self.devtel is not None and self.global_steps \
                    % self.config.steps_per_print == 0:
                # the steps_per_print boundary is the training loop's
                # phase boundary: refresh the memory gauges here (one
                # host call per device — NOT every step; a configured
                # monitor makes need_host true per step, so the poll
                # keeps its own cadence guard like publish below)
                self.devtel.poll_memory()
            t_f0 = time.perf_counter()
            fetched = jax.device_get(metrics)        # ONE transfer
            t_f1 = time.perf_counter()
            self._phase_ms["fetch"].inc((t_f1 - t_f0) * 1e3)
            if self.tracer.enabled:
                self.tracer.record("fetch", t_f0, t_f1, track="fetch",
                                   step=self.global_steps)
            self._last_metrics_host = fetched
            if self.global_steps % self.config.steps_per_print == 0:
                log_dist(
                    f"step={self.global_steps} loss={fetched['loss']:.4f} "
                    f"lr={fetched['lr']:.3e} "
                    f"gnorm={fetched['grad_norm']:.3f} "
                    f"tput={self.tput.avg_samples_per_sec():.1f} samples/s")
            if self.monitor is not None:
                self.monitor.write_scalars(self.global_steps, {
                    "Train/loss": float(fetched["loss"]),
                    "Train/lr": float(fetched["lr"]),
                    "Train/grad_norm": float(fetched["grad_norm"]),
                    "Train/loss_scale": float(fetched["loss_scale"]),
                })
                # registry fan-out rides the SAME writer pipeline as the
                # loss scalars (telemetry/metrics.py publish): per-phase
                # host-ms counters + step histogram land in CSV/TB/WandB
                # at the print cadence (every step would 5x the writer
                # volume for numbers that only move slowly)
                if self.global_steps % self.config.steps_per_print == 0:
                    self.metrics.publish(self.monitor, self.global_steps)
            metrics = fetched
        return metrics

    def eval_batch(self, batch, rng: Optional[jax.Array] = None):
        if self._stream is not None:
            return np.asarray(self._stream.eval_batch(
                batch, rng if rng is not None else jax.random.PRNGKey(0)))
        if self._eval_step_fn is None:
            fn = self.eval_fn or self.loss_fn
            # a pipelined 1F1B loss exposes a forward-only schedule for
            # evaluation (its primal otherwise pays full fwd+bwd cost)
            fn = getattr(fn, "eval_fn", fn)
            # PLD/random-LTD losses expose a hook-free eval variant (no
            # theta column in eval batches, no token dropping)
            fn = getattr(fn, "base_eval", None) or fn

            def eval_step(master, batch, rng):
                cparams = self._compute_params(master)
                out = fn(cparams, batch, rng)
                return out[0] if isinstance(out, tuple) else out

            self._eval_step_fn = jax.jit(
                eval_step, in_shardings=(self.master_shardings, None, None))
        if rng is None:
            rng = jax.random.PRNGKey(0)
        batch = self.shard_batch(batch, accumulate=False)
        try:
            out = np.asarray(self._eval_step_fn(self.state.master, batch, rng))
        except jax.errors.JaxRuntimeError as e:
            if not self.offload_active or self._offload_validated:
                raise
            self._disable_offload(e)
            return self.eval_batch(batch, rng)
        self._offload_validated = True
        return out

    def _write_flops_profile(self, batch, rng) -> None:
        """Engine flops-profiler hook (reference: engine.py:288,1850 —
        module-hook profiler; here: compiled-HLO cost analysis + the step
        wall time already measured, no extra execution)."""
        if self._stream is not None:
            logger.warning("flops_profiler: param-streamed steps run as "
                           "many per-layer programs; HLO cost analysis "
                           "of the monolithic step is unavailable")
            return
        from ..profiling import FlopsProfiler, analyze_fn

        stats = analyze_fn(self._train_step_fn or self._nvme_step_fn,
                           self.state, batch, rng)
        stats["params"] = float(param_count(self.state.master))
        # total_elapsed_time only counts steps after tput.start_step
        counted = self.tput.global_step_count - self.tput.start_step
        if counted > 0 and self.tput.total_elapsed_time:
            stats["latency_s"] = self.tput.total_elapsed_time / counted
            if stats.get("flops"):
                stats["tflops_per_s"] = (
                    stats["flops"] / stats["latency_s"] / 1e12)
        report = FlopsProfiler.report(stats,
                                      batch_size=self.train_batch_size)
        log_dist("\n" + report)
        if self.config.flops_profiler.output_file:
            with open(self.config.flops_profiler.output_file, "w") as f:
                f.write(report + "\n")

    def _disable_offload(self, err: Exception) -> None:
        """Fall back to device-resident optimizer state.

        The pinned_host placement compiles on real TPU but some backends
        (notably multi-device CPU SPMD, used by the virtual test mesh)
        cannot partition memory-space transfer annotations at all; detect
        that at first compile and keep training instead of dying."""
        logger.warning(
            "optimizer offload unsupported on this backend (%s); "
            "falling back to device-resident optimizer state",
            str(err).splitlines()[0][:120])
        self.offload_active = False
        to_dev = lambda sh: NamedSharding(self.topology.mesh, sh.spec)
        self.master_shardings = jax.tree.map(to_dev, self.master_shardings)
        self.opt_shardings = jax.tree.map(to_dev, self.opt_shardings)
        self.state = TrainState(
            step=self.state.step,
            master=jax.device_put(self.state.master, self.master_shardings),
            opt_state=jax.device_put(self.state.opt_state, self.opt_shardings),
            loss_scale=self.state.loss_scale,
            skipped=self.state.skipped)
        # drop every jit compiled against the host-placed shardings
        self._train_step_fn = None
        self._warmup_step_fn = None
        self._eval_step_fn = None
        self._nvme_step_fn = None
        if hasattr(self, "_compute_params_fn"):
            del self._compute_params_fn

    def shard_batch(self, batch, accumulate: bool = True):
        """Device-put host batch with [B] → sharded over data axes; with
        gas>1 reshape leaves to [gas, micro_global, ...].

        Idempotent: an already-staged batch (e.g. from
        ``PrefetchingLoader``, which uploads batch N+1 during step N)
        passes through untouched — but only for the staging mode it was
        built with (train batches are gas-reshaped; eval ones are not)."""
        if isinstance(batch, _StagedBatch):
            if batch.accumulate != (accumulate and self.gas > 1):
                raise ValueError(
                    "batch was staged for "
                    f"{'training' if batch.accumulate else 'eval'} "
                    "(gas reshape mismatch); re-stage the host batch "
                    "instead of reusing the staged one")
            return batch
        gas = self.gas if accumulate else 1
        sp = self.topology.sp_size
        from ..comm.mesh import SEQ_AXIS

        pc = jax.process_count()
        data_shards = (self.topology.mesh.shape[DATA_AXIS]
                       * self.topology.mesh.shape[FSDP_AXIS])

        def put(x):
            x = np.asarray(x)
            b = x.shape[0]
            if b % gas or (b * pc) % (gas * data_shards):
                raise ValueError(
                    f"batch dim {b} (x {pc} processes) not divisible by "
                    f"gas={gas} x data shards {data_shards}; for a "
                    "partial tail batch use eval or drop_last=True")
            # dim after batch is the sequence: shard it over the seq axis
            seq_entry = (SEQ_AXIS,) if (sp > 1 and x.ndim >= 2) else ()
            if gas > 1:
                x = x.reshape((gas, x.shape[0] // gas) + x.shape[1:])
                spec = P(None, (DATA_AXIS, FSDP_AXIS), *seq_entry)
                batch_dim = 1
            else:
                spec = P((DATA_AXIS, FSDP_AXIS), *seq_entry)
                batch_dim = 0
            sharding = NamedSharding(self.topology.mesh, spec)
            if pc > 1:
                # x is this process's host-local slice (DataLoader yields
                # per-process batch shards; every other dim — notably the
                # sequence — is fully present locally).  Assemble the
                # global array with an explicit global_shape scaling ONLY
                # the batch dim: inference would scale every sharded dim
                # by its cross-process extent and silently double a
                # process-spanning SEQ_AXIS.
                gshape = list(x.shape)
                gshape[batch_dim] *= pc
                return jax.make_array_from_process_local_data(
                    sharding, x, tuple(gshape))
            return jax.device_put(x, sharding)

        out = jax.tree.map(put, batch)
        if isinstance(out, dict):
            out = _StagedBatch(out)
            out.accumulate = gas > 1
        return out

    # ------------------------------------------------------------------
    # introspection / params access
    # ------------------------------------------------------------------
    @property
    def compute_params(self):
        """Current params in compute dtype (jitted gather+cast, cached)."""
        if self._stream is not None:
            raise ConfigError(
                "compute_params is unavailable under param streaming "
                "(offload_param.device=nvme): the full compute tree "
                "never materializes — stream layers via "
                "engine._stream or load a checkpoint instead")
        if not hasattr(self, "_compute_params_fn"):
            self._compute_params_fn = jax.jit(
                self._compute_params, in_shardings=(self.master_shardings,))
        return self._compute_params_fn(self.state.master)

    def get_lr(self) -> float:
        # schedule position = optimizer steps actually applied (state.step
        # excludes overflow-skipped steps; global_steps would drift under fp16)
        return float(self.lr_schedule(
            np.asarray(self.state.step).astype(np.float32)))

    def get_global_grad_norm(self) -> Optional[float]:
        if getattr(self, "_last_metrics", None) is None:
            return None
        if self._last_metrics_host is None:
            # one transfer, cached until the next step overwrites it
            self._last_metrics_host = jax.device_get(self._last_metrics)
        return float(self._last_metrics_host["grad_norm"])

    # ------------------------------------------------------------------
    # checkpointing (delegates to deepspeed_tpu.checkpoint)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None):
        from ..checkpoint.engine import save_checkpoint as _save
        if self.config.checkpoint.async_save and self._nvme is None \
                and jax.process_count() == 1:
            # Nebula-style background persistence: snapshot shards to
            # host now, write files on a worker thread.  Multi-host runs
            # save synchronously: save_tree's cross-host barriers are
            # device collectives that must not race the main thread's
            # training collectives (divergent issue order deadlocks).
            from ..checkpoint.engine import (AsyncCheckpointSaver,
                                             save_checkpoint_async)
            if not hasattr(self, "_async_saver"):
                self._async_saver = AsyncCheckpointSaver()
            return save_checkpoint_async(
                self, self._async_saver, save_dir, tag=tag,
                client_state=client_state or {})
        if self._nvme is None:
            return _save(self, save_dir, tag=tag,
                         client_state=client_state or {})
        # ZeRO-Infinity: checkpoint the *fp32* NVMe state, not the bf16
        # working copy, so resume (on any config) is lossless — the same
        # fragment format as every other run.  Lazy leaves stream one
        # swap group at a time through host RAM (state may exceed DRAM).
        from .optimizers import AdamState
        source = self._stream if self._stream is not None else self._nvme
        master, m, v = source.state_trees(lazy=True)
        saved = self.state
        self.state = TrainState(
            step=saved.step, master=master,
            opt_state=AdamState(m=m, v=v),
            loss_scale=saved.loss_scale, skipped=saved.skipped)
        try:
            return _save(self, save_dir, tag=tag,
                         client_state=client_state or {})
        finally:
            self.state = saved

    def wait_checkpoint(self) -> None:
        """Join an in-flight async checkpoint save (no-op otherwise);
        re-raises a failed save's error."""
        if hasattr(self, "_async_saver"):
            self._async_saver.wait()

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None):
        from ..checkpoint.engine import load_checkpoint as _load
        self.wait_checkpoint()        # never read a half-written save
        if self._nvme is None:
            return _load(self, load_dir, tag=tag)
        return self._load_checkpoint_nvme(load_dir, tag)

    def _load_checkpoint_nvme(self, load_dir: str, tag: Optional[str]):
        """Load a fragment checkpoint into the NVMe state store: fp32
        master + moments go to NVMe files, the device gets a fresh bf16
        working copy.  Checkpoints from non-Infinity runs load too (same
        master/AdamState key layout)."""
        import os

        from ..checkpoint.engine import LATEST, load_tree_host
        from .optimizers import AdamState
        if tag is None:
            latest = os.path.join(load_dir, LATEST)
            if not os.path.exists(latest):
                raise FileNotFoundError(f"No {LATEST} file in {load_dir}")
            with open(latest) as f:
                tag = f.read().strip()
        ckpt_dir = os.path.join(load_dir, tag)

        f32 = lambda tree: jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.float32), tree)
        scalar = lambda dt: jax.ShapeDtypeStruct((), dt)
        master_tpl = (self._stream.master_template()
                      if self._stream is not None
                      else f32(self.state.master))
        template = TrainState(
            step=scalar(np.int32),
            master=master_tpl,
            opt_state=AdamState(m=master_tpl, v=master_tpl),
            loss_scale=LossScaleState(scalar(np.float32), scalar(np.int32),
                                      scalar(np.int32)),
            skipped=scalar(np.int32))
        host, meta = load_tree_host(template, ckpt_dir)
        if self._stream is not None:
            self._stream.restore(host.master, host.opt_state.m,
                                 host.opt_state.v)
            master = self._stream.resident
        else:
            self._nvme.restore(host.master, host.opt_state.m,
                               host.opt_state.v)
            flat = jax.tree_util.tree_leaves(host.master)
            flat_sh = jax.tree_util.tree_leaves(
                self.master_shardings,
                is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
            dev_leaves = [jax.device_put(m.astype(self.compute_dtype), sh)
                          for m, sh in zip(flat, flat_sh)]
            master = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(self.state.master), dev_leaves)
        self.state = TrainState(
            step=jnp.asarray(host.step, jnp.int32),
            master=master, opt_state=(),
            loss_scale=LossScaleState(
                *[jnp.asarray(x) for x in host.loss_scale]),
            skipped=jnp.asarray(host.skipped, jnp.int32))
        self.global_steps = int(meta.get("global_steps", 0))
        self.global_samples = int(meta.get("global_samples", 0))
        log_dist(f"loaded checkpoint {ckpt_dir} into NVMe state "
                 f"(step {self.global_steps})")
        return ckpt_dir, meta.get("client_state", {})


def initialize(loss_fn: Callable = None,
               params: Any = None,
               config: Any = None,
               topology: Optional[MeshTopology] = None,
               param_axes: Any = None,
               sharding_rules: Optional[Dict] = None,
               model: Any = None,
               **kwargs) -> Engine:
    """Build an :class:`Engine` (reference: deepspeed.initialize
    deepspeed/__init__.py:69).

    Either pass ``loss_fn`` + ``params`` directly, or a ``model`` object
    exposing ``.loss_fn``, ``.params`` (and optionally ``.param_axes``,
    ``.sharding_rules``) — the models in ``deepspeed_tpu.models`` do.
    """
    cfg = load_config(config)
    if (cfg.mesh.expert > 1 and model is not None
            and getattr(getattr(model, "config", None), "moe_dispatch",
                        None) == "ragged"):
        # ragged_dot contracts against expert-sharded weights: GSPMD
        # would all-gather every expert's weights per layer
        raise ConfigError(
            "moe_dispatch='ragged' (dropless grouped GEMM) does not "
            "compose with expert parallelism; use the scatter dispatch "
            "on expert meshes")
    de_routing = cfg.data_efficiency.enabled \
        and cfg.data_efficiency.data_routing.enabled \
        and cfg.data_efficiency.data_routing.random_ltd.enabled
    if (cfg.progressive_layer_drop.enabled or de_routing) \
            and loss_fn is None:
        # PLD / random-LTD modify the transformer forward — they need
        # the model path (reference wires them by module surgery,
        # engine.py:346-356; here the loss is rebuilt with the hooks)
        if model is None or not hasattr(model, "config"):
            raise ConfigError(
                "progressive_layer_drop / random_ltd need model= with a "
                "TransformerConfig (the loss must expose the layer stack)")
        if de_routing and model.config.position == "alibi":
            # LTD gathers a token subset; the ALiBi bias uses compressed
            # key indices and would silently distort distances (rope
            # threads original positions; the alibi wrapper cannot)
            raise ConfigError(
                "random_ltd does not compose with position='alibi' "
                "(the distance bias would see gathered, not original, "
                "token positions)")
        if max(cfg.mesh.pipe, cfg.pipeline.stages) > 1 \
                or max(cfg.mesh.seq, cfg.sequence_parallel.size) > 1:
            raise ConfigError(
                "progressive_layer_drop / random_ltd are not composable "
                "with pipeline or sequence parallelism yet")
        from ..models import layers as _L
        from ..models.transformer import lm_loss_fn

        attn = getattr(model, "attention_fn", None) or _L.causal_attention
        loss_fn = lm_loss_fn(model.config, attn,
                             pld=cfg.progressive_layer_drop.enabled)
    if model is not None:
        params = params if params is not None else model.params
        param_axes = param_axes if param_axes is not None else getattr(
            model, "param_axes", None)
        sharding_rules = sharding_rules or getattr(model, "sharding_rules", None)
        # sequence parallelism: swap the model's attention for the
        # Ulysses/ring wrapper over this run's mesh
        seq_size = max(cfg.mesh.seq, cfg.sequence_parallel.size)
        pipe_size = max(cfg.mesh.pipe, cfg.pipeline.stages)
        is_alibi = getattr(getattr(model, "config", None),
                           "position", None) == "alibi"
        # seq parallel WITHOUT pipeline: swap attention in the plain loss.
        # With pipeline, make_pipelined_loss_fn composes seq itself.
        if loss_fn is None and seq_size > 1 and pipe_size == 1 \
                and hasattr(model, "config"):
            from ..parallel.sequence import make_attention
            from ..models.transformer import lm_loss_fn

            topology = topology or MeshTopology.build(cfg.mesh)
            kw = {}
            if is_alibi:
                # bypass the model's plain ALiBi wrapper: the bias must
                # be built INSIDE the Ulysses shard_map with this
                # shard's global head offset
                kw["alibi_heads"] = model.config.num_heads
                kw["alibi_scale"] = model.config.attn_scale
            else:
                base = getattr(model, "attention_fn", None)
                if base is not None:
                    kw["base_attention"] = base
            attn = make_attention(topology, cfg.sequence_parallel.mode,
                                  **kw)
            loss_fn = lm_loss_fn(model.config, attn)
        # pipeline parallelism (gpipe/1f1b) over the pipe axis; seq > 1
        # composes via per-shard Ulysses inside the pipeline shard_map
        if loss_fn is None and pipe_size > 1 and hasattr(model, "config"):
            if seq_size > 1 and cfg.sequence_parallel.mode != "ulysses":
                raise NotImplementedError(
                    f"sequence_parallel.mode="
                    f"{cfg.sequence_parallel.mode!r} is not composable "
                    "with pipeline parallelism (only 'ulysses' is)")
            from ..parallel.pipeline import make_pipelined_loss_fn

            topology = topology or MeshTopology.build(cfg.mesh)
            M = cfg.pipeline.num_microbatches or pipe_size
            kw = {"schedule": cfg.pipeline.schedule}
            model_attn = getattr(model, "attention_fn", None)
            if model_attn is not None:
                kw["attention_fn"] = model_attn
            loss_fn = make_pipelined_loss_fn(model.config, topology, M, **kw)
        loss_fn = loss_fn or model.loss_fn
    if loss_fn is None or params is None:
        raise ValueError("initialize() needs loss_fn+params or model=")
    return Engine(loss_fn=loss_fn, params=params, config=cfg,
                  topology=topology, param_axes=param_axes,
                  sharding_rules=sharding_rules, model=model, **kwargs)
