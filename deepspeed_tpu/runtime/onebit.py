"""1-bit optimizers: error-compensated compressed-momentum Adam/LAMB.

TPU-native equivalents of the reference 1-bit family
(``runtime/fp16/onebit/adam.py`` OnebitAdam, ``zoadam.py`` ZeroOneAdam,
``lamb.py`` OnebitLamb; compressed-allreduce backends ``runtime/comm/
nccl.py:16`` cupy bit-packing).

Algorithm (1-bit Adam paper, faithfully reproduced):
* warmup (``freeze_step`` steps): exact Adam, variance v accumulates.
* after freeze: v is FROZEN; only momentum moves, and the momentum
  update is compressed to sign(x)*||x||_1/n with a persistent error
  buffer e — the worker+server error feedback that keeps the compressed
  trajectory unbiased.

Comm mapping: the reference compresses the momentum allreduce between
DP ranks.  Under XLA SPMD, gradients are already mean-reduced when the
optimizer runs on the (sharded) momentum, so compression here reproduces
the reference's *numerics* (compression noise + error feedback on every
momentum update).  Driving the wire-level volume down additionally rides
the qgZ quantized reduce-scatter (ops/quant.quantized_psum_scatter).

ZeroOneAdam (``zoadam.py``): variance update policy — v refreshes on an
interval schedule (``var_update_scaler``) instead of freezing once, and
1-bit compression applies between refreshes ("0/1 Adam").
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .optimizers import Optimizer, _tree_unzip, _tzeros


def _compress_1bit(x: jax.Array, err: jax.Array
                   ) -> Tuple[jax.Array, jax.Array]:
    """sign * mean|.| compression with error feedback
    (reference: compressed_allreduce cupy packing, nccl.py:16)."""
    c = x + err
    scale = jnp.mean(jnp.abs(c))
    q = jnp.where(c >= 0, scale, -scale)
    return q, c - q


class OnebitAdamState(NamedTuple):
    m: Any
    v: Any
    err: Any           # error-feedback buffers (worker+server combined)


def onebit_adam(lr, betas=(0.9, 0.999), eps: float = 1e-8,
                weight_decay: float = 0.0,
                freeze_step: int = 100,
                compress: bool = True) -> Optimizer:
    """(reference: runtime/fp16/onebit/adam.py OnebitAdam).

    ``compress=False`` keeps the frozen-variance Adam math but skips the
    in-optimizer momentum compression — used when the ENGINE already
    compresses the gradient reduction on the wire
    (``Engine._onebit_reduce``): compressing twice compounds the noise."""
    b1, b2 = betas
    lr_fn = lr if callable(lr) else (lambda _: jnp.asarray(lr, jnp.float32))

    def init(params):
        return OnebitAdamState(m=_tzeros(params, jnp.float32),
                               v=_tzeros(params, jnp.float32),
                               err=_tzeros(params, jnp.float32))

    def update(grads, state: OnebitAdamState, params, step):
        step_f = step.astype(jnp.float32)
        lr_t = lr_fn(step_f)
        frozen = step > freeze_step

        def upd(g, m, v, e, p):
            g32 = g.astype(jnp.float32)
            m_exact = b1 * m + (1 - b1) * g32
            if compress:
                # compress the new momentum w/ error feedback
                m_comp, e_new = _compress_1bit(m_exact, e)
                m_ = jnp.where(frozen, m_comp, m_exact)
                e_ = jnp.where(frozen, e_new, e)
            else:
                m_, e_ = m_exact, e
            v_ = jnp.where(frozen, v, b2 * v + (1 - b2) * (g32 * g32))
            # bias correction only during warmup: the reference's frozen
            # phase is uncorrected exp_avg/(sqrt(exp_avg_sq)+eps)
            # (reference adam.py:198,230)
            c1 = jnp.where(frozen, 1.0, 1 - b1 ** step_f)
            c2 = jnp.where(frozen, 1.0, 1 - b2 ** step_f)
            delta = -lr_t * (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)
            if weight_decay:
                delta = delta - lr_t * weight_decay * p.astype(jnp.float32)
            return delta, m_, v_, e_

        out = jax.tree.map(upd, grads, state.m, state.v, state.err, params)
        updates, m, v, err = _tree_unzip(out, grads, 4)
        return updates, OnebitAdamState(m=m, v=v, err=err)

    return Optimizer(init, update)


class ZeroOneAdamState(NamedTuple):
    m: Any
    v: Any
    err: Any


def zero_one_adam(lr, betas=(0.9, 0.999), eps: float = 1e-8,
                  weight_decay: float = 0.0,
                  var_freeze_step: int = 100,
                  var_update_scaler: int = 16,
                  local_step_scaler: int = 32768,
                  local_step_clipper: int = 16,
                  compress: bool = True) -> Optimizer:
    """0/1 Adam (reference: runtime/fp16/onebit/zoadam.py ZeroOneAdam):
    variance refreshes on an exponentially-spaced interval — the k-th
    refresh happens at step ``var_update_scaler * 2^k`` with the exponent
    capped at ``local_step_clipper`` (and never past
    min(var_freeze_step, local_step_scaler)); compressed momentum in
    between."""
    b1, b2 = betas
    lr_fn = lr if callable(lr) else (lambda _: jnp.asarray(lr, jnp.float32))
    freeze = min(var_freeze_step, local_step_scaler)

    def init(params):
        return ZeroOneAdamState(m=_tzeros(params, jnp.float32),
                                v=_tzeros(params, jnp.float32),
                                err=_tzeros(params, jnp.float32))

    def update(grads, state: ZeroOneAdamState, params, step):
        step_f = step.astype(jnp.float32)
        lr_t = lr_fn(step_f)
        # v refreshes every step through the first interval (warm start),
        # then at exponentially-spaced steps scaler*2^k (k capped at
        # local_step_clipper) until the freeze point
        q = jnp.maximum(step // var_update_scaler, 1)
        is_pow2 = (q & (q - 1)) == 0
        capped = q <= (1 << local_step_clipper)
        on_schedule = jnp.logical_and(step % var_update_scaler == 0,
                                      jnp.logical_and(is_pow2, capped))
        refresh = jnp.logical_or(
            step <= var_update_scaler,
            jnp.logical_and(on_schedule, step <= freeze))

        def upd(g, m, v, e, p):
            g32 = g.astype(jnp.float32)
            m_exact = b1 * m + (1 - b1) * g32
            if compress:
                m_comp, e_new = _compress_1bit(m_exact, e)
                m_ = jnp.where(refresh, m_exact, m_comp)
                e_ = jnp.where(refresh, e, e_new)
            else:
                m_, e_ = m_exact, e
            v_ = jnp.where(refresh, b2 * v + (1 - b2) * (g32 * g32), v)
            # deliberate deviation from the uncorrected reference update:
            # always-on bias correction decays smoothly to 1, avoiding
            # both per-step LR flicker (gating on `refresh`) and a ~6x
            # one-time cliff (gating on a warm-start window) while
            # matching the uncorrected asymptotics
            c1 = 1 - b1 ** step_f
            c2 = 1 - b2 ** step_f
            delta = -lr_t * (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)
            if weight_decay:
                delta = delta - lr_t * weight_decay * p.astype(jnp.float32)
            return delta, m_, v_, e_

        out = jax.tree.map(upd, grads, state.m, state.v, state.err, params)
        updates, m, v, err = _tree_unzip(out, grads, 4)
        return updates, ZeroOneAdamState(m=m, v=v, err=err)

    return Optimizer(init, update)


def onebit_lamb(lr, betas=(0.9, 0.999), eps: float = 1e-6,
                weight_decay: float = 0.0, freeze_step: int = 100,
                min_trust: float = 0.01, max_trust: float = 10.0,
                compress: bool = True) -> Optimizer:
    """(reference: runtime/fp16/onebit/lamb.py OnebitLamb — compressed
    momentum + per-tensor trust ratio after freeze)."""
    b1, b2 = betas
    lr_fn = lr if callable(lr) else (lambda _: jnp.asarray(lr, jnp.float32))

    def init(params):
        return OnebitAdamState(m=_tzeros(params, jnp.float32),
                               v=_tzeros(params, jnp.float32),
                               err=_tzeros(params, jnp.float32))

    def update(grads, state: OnebitAdamState, params, step):
        step_f = step.astype(jnp.float32)
        lr_t = lr_fn(step_f)
        frozen = step > freeze_step

        def upd(g, m, v, e, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_exact = b1 * m + (1 - b1) * g32
            if compress:
                m_comp, e_new = _compress_1bit(m_exact, e)
                m_ = jnp.where(frozen, m_comp, m_exact)
                e_ = jnp.where(frozen, e_new, e)
            else:
                m_, e_ = m_exact, e
            v_ = jnp.where(frozen, v, b2 * v + (1 - b2) * (g32 * g32))
            # uncorrected after freeze, matching the reference (see
            # onebit_adam)
            c1 = jnp.where(frozen, 1.0, 1 - b1 ** step_f)
            c2 = jnp.where(frozen, 1.0, 1 - b2 ** step_f)
            u = (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)
            if weight_decay:
                u = u + weight_decay * p32
            w_norm = jnp.linalg.norm(p32.ravel())
            u_norm = jnp.linalg.norm(u.ravel())
            trust = jnp.where((w_norm > 0) & (u_norm > 0),
                              jnp.clip(w_norm / u_norm, min_trust,
                                       max_trust), 1.0)
            return -lr_t * trust * u, m_, v_, e_

        out = jax.tree.map(upd, grads, state.m, state.v, state.err, params)
        updates, m, v, err = _tree_unzip(out, grads, 4)
        return updates, OnebitAdamState(m=m, v=v, err=err)

    return Optimizer(init, update)
