"""Per-layer NVMe parameter streaming for *training* (ZeRO-Infinity).

TPU-native analog of the reference's partitioned parameter swapper
(``runtime/swap_tensor/partitioned_param_swapper.py:290`` — swap-in on
fetch, swap-out on release; engine hookup ``runtime/zero/stage3.py:614``
``_configure_tensor_swapping``): model parameters live on NVMe in the
compute dtype and stream through HBM one layer at a time, so models
larger than HBM *and* host DRAM can train.  The serving-side mechanism
(:mod:`deepspeed_tpu.inference.weight_stream`) fetches layers inside a
compiled scan via ``io_callback``; training additionally needs gradients
*out* per layer and an optimizer update *back in*, which io_callback
cannot express — so the training path hoists the layer loop to the host
(the role the reference's module hooks play) and keeps each per-layer
forward/VJP a compiled SPMD program over the engine's mesh:

* **forward sweep** — fetch layer ``l+1``'s params from NVMe (async,
  double-buffered through the aio pool) while layer ``l``'s jitted
  forward runs; keep only per-layer activation checkpoints.
* **backward sweep** — re-fetch params in reverse order, run the
  per-layer VJP (recomputing the layer forward: activation
  checkpointing), spill the fp32 layer grads to the NVMe grad store,
  accumulate grad-norm/overflow terms on the fly.
* **update sweep** — the grouped NVMe optimizer
  (:class:`~deepspeed_tpu.runtime.zero_infinity.NVMeOptimizer`) walks
  fp32 master+moments group-by-group with prefetch, consumes the layer
  grads lazily (one grad group resident), applies the HostAdam update,
  and refreshes the bf16 param store per layer.

HBM ever holds: resident params (embed/norms/head) + two layers' weights
+ the activation checkpoints.  Host DRAM ever holds: one optimizer swap
group + one layer's grads (tracked by :class:`ResidencyMeter`; asserted
``< full-model bf16`` in tests).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config.config import ConfigError
from ..utils.logging import log_dist
from .swap_tensor import OptimizerSwapper


class ResidencyMeter:
    """Tracks bytes of live host buffers in the streaming path (the
    honesty instrument behind "peak host DRAM < full-model bf16")."""

    def __init__(self):
        self.cur = 0
        self.peak = 0

    def alloc(self, n: int) -> None:
        self.cur += int(n)
        self.peak = max(self.peak, self.cur)

    def free(self, n: int) -> None:
        self.cur -= int(n)


def _tree_bytes(tree) -> int:
    return sum(int(np.prod(np.shape(x)) or 1) * np.dtype(
        getattr(x, "dtype", np.float32)).itemsize
        for x in jax.tree.leaves(tree))


class StreamedInfinityTrainer:
    """Owns the NVMe state and the host-orchestrated streamed step for
    one engine.  Built by the engine when ``offload_param.device=nvme``
    and a stacked-layer model (``models.transformer``) is available."""

    def __init__(self, engine, model, params):
        self.eng = engine
        cfg = model.config
        self.cfg = cfg
        self.attention_fn = getattr(model, "attention_fn", None)
        self._check_supported(engine, cfg)
        self.L = int(cfg.num_layers)
        self.meter = ResidencyMeter()

        off = engine.config.zero_optimization.offload_optimizer
        offp = engine.config.zero_optimization.offload_param
        # parameter/grad streams go where offload_param points them;
        # optimizer state stays under offload_optimizer.nvme_path
        root = os.path.join(offp.nvme_path or off.nvme_path,
                            "param_stream",
                            f"r{jax.process_index()}_{os.getpid()}_"
                            f"{id(self):x}")
        import shutil
        import weakref
        self._cleanup = weakref.finalize(self, shutil.rmtree, root, True)

        # ---- split params: stacked blocks vs resident --------------------
        if not (isinstance(params, dict) and "blocks" in params):
            raise ConfigError(
                "offload_param.device=nvme streaming needs the standard "
                "stacked-layer param layout (a 'blocks' subtree with a "
                "leading num_layers dim — models.transformer.init_params)")
        blocks = params["blocks"]
        resident = {k: v for k, v in params.items() if k != "blocks"}
        self._blocks_tpl = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.dtype(
                engine.compute_dtype)), blocks)
        self._layer_tpl = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype),
            self._blocks_tpl)
        self._layer_grad_tpl = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, np.float32),
            self._layer_tpl)
        self._res_grad_tpl = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.float32),
            resident)

        # per-layer compute shardings: stacked spec minus the layer dim
        mesh = engine.topology.mesh
        blk_specs = engine.param_specs["blocks"]
        self._layer_sh = jax.tree.map(
            lambda sp: NamedSharding(mesh, P(*list(sp)[1:])),
            blk_specs, is_leaf=lambda x: isinstance(x, P))
        self._res_sh = jax.tree.map(
            lambda sp: NamedSharding(mesh, sp),
            {k: engine.param_specs[k] for k in resident},
            is_leaf=lambda x: isinstance(x, P))

        # ---- multi-host: per-process fragment maps -----------------------
        # every process stores/streams only the shard fragments its own
        # devices address (reference: per-rank swap, stage3.py:614);
        # layers all share one per-leaf fragment map
        self._multi = jax.process_count() > 1
        from .zero_infinity import fragment_shape, shard_fragments
        if self._multi:
            self._lfrags, self._lowned = [], []
            for s, sh in zip(jax.tree.leaves(self._layer_tpl),
                             jax.tree.leaves(
                                 self._layer_sh,
                                 is_leaf=lambda x: isinstance(
                                     x, jax.sharding.Sharding))):
                f, o = shard_fragments(s.shape, sh)
                self._lfrags.append(f)
                self._lowned.append(o)
            self._rfrags, self._rowned = [], []
            for s, sh in zip(jax.tree.leaves(self._res_grad_tpl),
                             jax.tree.leaves(
                                 self._res_sh,
                                 is_leaf=lambda x: isinstance(
                                     x, jax.sharding.Sharding))):
                f, o = shard_fragments(s.shape, sh)
                self._rfrags.append(f)
                self._rowned.append(o)

        def frag_tpl(tpl_tree, frags, dtype=None):
            """Store template: per-leaf list of fragment SDS."""
            out = []
            for j, s in enumerate(jax.tree.leaves(tpl_tree)):
                out.append([jax.ShapeDtypeStruct(
                    fragment_shape(s.shape, idx), dtype or s.dtype)
                    for idx in frags[j]])
            return out

        if self._multi:
            self._pstore_tpl = frag_tpl(self._layer_tpl, self._lfrags)
            self._lgrad_tpl = frag_tpl(self._layer_tpl, self._lfrags,
                                       np.float32)
            self._rgrad_tpl = frag_tpl(self._res_grad_tpl, self._rfrags,
                                       np.float32)
        else:
            self._pstore_tpl = self._layer_tpl
            self._lgrad_tpl = self._layer_grad_tpl
            self._rgrad_tpl = self._res_grad_tpl

        # ---- NVMe stores -------------------------------------------------
        # bf16 working copies, one swap group per layer
        aio_cfg = engine.config.aio
        self._pstore = OptimizerSwapper(os.path.join(root, "params"),
                                        self.L, aio_config=aio_cfg)
        # fp32 grads: one group per layer + one for resident leaves
        self._gstore = OptimizerSwapper(os.path.join(root, "grads"),
                                        self.L + 1, aio_config=aio_cfg)
        # fp32 master + moments live in the engine's NVMeOptimizer,
        # initialized over the UNSTACKED tree (per-layer leaves => swap
        # groups align with layers instead of one giant stacked leaf);
        # multi-host: partitioned into per-rank fragments along the SAME
        # layouts the trainer spills grads in
        self._opt = engine._nvme
        self._opt.meter = self.meter
        unstacked = {"layers": [jax.tree.map(lambda x: x[l], blocks)
                                for l in range(self.L)],
                     "resident": resident}
        unstacked_sh = {"layers": [self._layer_sh] * self.L,
                        "resident": self._res_sh} if self._multi else None
        self._opt.initialize(unstacked, shardings=unstacked_sh)
        # flat-leaf index map of the unstacked tree: leaf i -> (kind, l, j)
        leaves, self._udef = jax.tree_util.tree_flatten(unstacked)
        self._leafmap: List[Tuple[str, int, int]] = []
        counts: Dict[Tuple[str, int], int] = {}
        for path, _ in jax.tree_util.tree_flatten_with_path(unstacked)[0]:
            if path[0].key == "layers":
                key = ("layer", path[1].idx)
            else:
                key = ("resident", -1)
            j = counts.get(key, 0)
            counts[key] = j + 1
            self._leafmap.append((key[0], key[1], j))

        # spill bf16 per-layer working copies; resident stays on device
        dt = engine.compute_dtype
        for l in range(self.L):
            if self._multi:
                lp = [[np.asarray(x[l])[idx].astype(dt) if idx
                       else np.asarray(x[l]).astype(dt)
                       for idx in self._lfrags[j]]
                      for j, x in enumerate(jax.tree.leaves(blocks))]
            else:
                lp = jax.tree.map(lambda x: np.asarray(x[l]).astype(dt),
                                  blocks)
            self._pstore.write_group(l, lp)
        self.resident = jax.tree.map(
            lambda x, sh: jax.device_put(np.asarray(x).astype(dt), sh),
            resident, self._res_sh)
        self._res_bytes = _tree_bytes(resident)
        self._layer_bytes = _tree_bytes(self._layer_tpl)
        # this process's actual host buffer per layer fetch (== the full
        # layer single-host; only the local fragments multi-host)
        self._pstore_bytes = _tree_bytes(self._pstore_tpl)
        self._fns: Dict[Any, Any] = {}
        self._cos_sin = None
        log_dist(
            f"ZeRO-Infinity param streaming: {self.L} layers "
            f"({self._layer_bytes/1e6:.1f} MB/layer bf16) stream via "
            f"{root}; resident {self._res_bytes/1e6:.1f} MB stays in HBM")

    @staticmethod
    def _check_supported(engine, model_cfg) -> None:
        cfg = engine.config
        bad = []
        if max(cfg.mesh.pipe, cfg.pipeline.stages) > 1:
            bad.append("pipeline parallelism")
        if max(cfg.mesh.seq, cfg.sequence_parallel.size) > 1:
            bad.append("sequence parallelism")
        if cfg.progressive_layer_drop.enabled:
            bad.append("progressive_layer_drop")
        if cfg.data_efficiency.enabled \
                and cfg.data_efficiency.data_routing.enabled \
                and cfg.data_efficiency.data_routing.random_ltd.enabled:
            bad.append("random_ltd")
        if cfg.quantize_training.enabled:
            bad.append("quantize_training (MoQ)")
        if "onebit" in cfg.optimizer.type.lower() \
                or "zeroone" in cfg.optimizer.type.lower():
            bad.append("1-bit optimizers")
        if cfg.zero_optimization.zero_quantized_weights \
                or cfg.zero_optimization.zero_quantized_gradients:
            bad.append("ZeRO++ quantized collectives")
        if cfg.sparse_gradients:
            bad.append("sparse_gradients")
        if getattr(model_cfg, "num_experts", 1) > 1:
            # the streamed layer sweep discards block_apply's metrics, so
            # the MoE load-balancing aux loss would be silently dropped
            bad.append("MoE (the streamed sweep cannot carry the "
                       "load-balancing aux loss)")
        if engine.eval_fn is not None:
            # eval_batch streams the built-in LM loss; silently replacing
            # a custom eval metric would report the wrong quantity
            bad.append("a custom eval_fn")
        if bad:
            raise ConfigError(
                "offload_param.device=nvme (per-layer param streaming) "
                f"does not compose with: {', '.join(bad)}")

    # ------------------------------------------------------------------
    # jitted per-layer programs (cached per batch signature)
    # ------------------------------------------------------------------
    def _cos_sin_arrays(self):
        if self._cos_sin is None:
            from ..models import layers as Lx
            cfg = self.cfg
            if cfg.position == "rope":
                cos, sin = Lx.rope_freqs(cfg.rotary_dim, cfg.max_seq_len,
                                         cfg.rope_theta)
            else:
                cos = sin = jnp.zeros((1, 1), jnp.float32)
            self._cos_sin = (cos, sin)
        return self._cos_sin

    def _programs(self, has_mask: bool):
        key = has_mask
        if key in self._fns:
            return self._fns[key]
        from ..models import layers as Lx
        from ..models import transformer as T
        cfg = self.cfg
        dt = self.eng.compute_dtype
        attn = self.attention_fn or Lx.causal_attention
        norm = T._norm(cfg)

        def embed_f(resident, ids):
            x = Lx.embed(resident["embed"], ids).astype(dt)
            if cfg.embed_norm:
                x = norm(resident["ln_embed"], x)
            if cfg.position == "learned":
                x = x + resident["pos_embed"]["table"][:ids.shape[1]] \
                    .astype(dt)
            return x

        def layer_f(lp, x, cos, sin, mask):
            y, _ = T.block_apply(cfg, lp, x, cos, sin, mask=mask,
                                 attention_fn=attn)
            return y

        def head_f(resident, x, ids, mask, scale):
            xh = norm(resident["ln_f"], x)
            if cfg.tie_embeddings:
                logits = xh @ resident["embed"]["table"].astype(dt).T
            else:
                logits = xh @ resident["lm_head"]["kernel"].astype(dt)
                if cfg.head_bias:
                    logits = logits + resident["lm_head"]["bias"].astype(dt)
            labels, tmask = T.rolled_lm_targets(ids, mask)
            loss = T.cross_entropy_loss(logits, labels, tmask)
            return loss * scale, loss

        def head_bwd(resident, x, ids, mask, scale):
            (_, loss), g = jax.value_and_grad(
                head_f, argnums=(0, 1), has_aux=True)(
                    resident, x, ids, mask, scale)
            d_res, d_x = g
            # param grads leave the graph in fp32 (the grad store's
            # dtype); the activation grad keeps the compute dtype
            d_res = jax.tree.map(lambda t: t.astype(jnp.float32), d_res)
            return loss, d_res, d_x

        def layer_bwd(lp, x, cos, sin, mask, dy):
            _, vjp = jax.vjp(
                lambda lp_, x_: layer_f(lp_, x_, cos, sin, mask), lp, x)
            d_lp, d_x = vjp(dy)
            d_lp = jax.tree.map(lambda g: g.astype(jnp.float32), d_lp)
            return d_lp, d_x

        def embed_bwd(resident, ids, dx):
            _, vjp = jax.vjp(lambda r: embed_f(r, ids), resident)
            (d_res,) = vjp(dx)
            return jax.tree.map(lambda g: g.astype(jnp.float32), d_res)

        # multi-host: pin output layouts so spilled grads land in the
        # SAME shardings the NVMe fragment maps were built from (single
        # host leaves XLA free — validated layouts, no relayout risk)
        jkw: Dict[str, Dict[str, Any]] = {k: {} for k in (
            "embed", "layer", "head_loss", "head_bwd", "layer_bwd",
            "embed_bwd")}
        if self._multi:
            from ..comm.mesh import DATA_AXIS, FSDP_AXIS
            mesh = self.eng.topology.mesh
            x_sh = NamedSharding(mesh, P((DATA_AXIS, FSDP_AXIS)))
            repl = NamedSharding(mesh, P())
            jkw["embed"] = {"out_shardings": x_sh}
            jkw["layer"] = {"out_shardings": x_sh}
            jkw["head_loss"] = {"out_shardings": repl}
            jkw["head_bwd"] = {"out_shardings": (repl, self._res_sh,
                                                 x_sh)}
            jkw["layer_bwd"] = {"out_shardings": (self._layer_sh, x_sh)}
            jkw["embed_bwd"] = {"out_shardings": self._res_sh}
        fns = dict(
            embed=jax.jit(embed_f, **jkw["embed"]),
            # NOTE: no donation on the layer forward — the caller keeps
            # x alive as the activation checkpoint
            layer=jax.jit(layer_f, **jkw["layer"]),
            head_loss=jax.jit(
                lambda r, x, ids, mask: head_f(r, x, ids, mask, 1.0)[1],
                **jkw["head_loss"]),
            head_bwd=jax.jit(head_bwd, **jkw["head_bwd"]),
            layer_bwd=jax.jit(layer_bwd, donate_argnums=(5,),
                              **jkw["layer_bwd"]),
            embed_bwd=jax.jit(embed_bwd, **jkw["embed_bwd"]),
        )
        self._fns[key] = fns
        return fns

    # ------------------------------------------------------------------
    # the streamed step
    # ------------------------------------------------------------------
    def _unstage(self, batch, gas: int):
        """Accept a pre-staged batch (PrefetchingLoader) by fetching it
        back to host rows — the streamed host loop slices and re-stages
        micro-batches itself."""
        from .engine import _StagedBatch
        if not isinstance(batch, _StagedBatch):
            return batch

        def back(x):
            a = np.asarray(x)
            if gas > 1 and a.ndim >= 2:          # undo the gas reshape
                a = a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:])
            return a
        return {k: back(v) for k, v in dict(batch).items()}

    def _fetch_layer(self, l: int):
        """Blocking read of layer l's bf16 params (prefetched when the
        sweep is in order), placed onto the mesh.  Multi-host: each
        process uploads only its own fragments; the global arrays are
        assembled from per-device buffers."""
        host = self._pstore.read_group(l, self._pstore_tpl)
        self.meter.alloc(self._pstore_bytes)
        if self._multi:
            flat_sh = jax.tree.leaves(
                self._layer_sh,
                is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
            tpl_flat = jax.tree.leaves(self._layer_tpl)
            leaves = [self._assemble(host[j], tpl_flat[j].shape,
                                     flat_sh[j], self._lfrags[j],
                                     tpl_flat[j].dtype)
                      for j in range(len(tpl_flat))]
            dev = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(self._layer_tpl), leaves)
        else:
            dev = jax.tree.map(jax.device_put, host, self._layer_sh)
        # hold the host buffers (and their meter count) until the async
        # device transfer has actually consumed them
        jax.block_until_ready(dev)
        self.meter.free(self._pstore_bytes)
        return dev

    @staticmethod
    def _assemble(frag_list, shape, sharding, frags, dtype):
        fragmap = dict(zip(frags, frag_list))
        imap = sharding.devices_indices_map(tuple(shape))
        bufs = [jax.device_put(
            np.asarray(fragmap[tuple(imap[d])]).astype(dtype), d)
            for d in sharding.addressable_devices]
        return jax.make_array_from_single_device_arrays(
            tuple(shape), sharding, bufs)

    def train_batch(self, batch, rng) -> Dict[str, Any]:
        eng = self.eng
        gas = eng.gas
        batch = self._unstage(batch, gas)
        # host-local micro-batch rows per accumulation step (the batch
        # arg carries this process's train_batch_size/process_count rows)
        rows = int(np.shape(batch["input_ids"])[0])
        if rows % gas:
            raise ValueError(
                f"batch dim {rows} not divisible by gas={gas}")
        micro = rows // gas
        use_scaling = eng.precision == "fp16"
        scale = float(np.asarray(eng.state.loss_scale.scale)) \
            if use_scaling else 1.0
        denom = scale * (eng.config.gradient_predivide_factor
                         if eng.config.prescale_gradients else 1.0)

        ids_all = np.asarray(batch["input_ids"])
        mask_all = batch.get("attention_mask")
        mask_all = None if mask_all is None else np.asarray(mask_all)
        has_mask = mask_all is not None
        fns = self._programs(has_mask)
        cos, sin = self._cos_sin_arrays()

        losses = []
        sq_norm = 0.0
        finite = True
        for mb in range(gas):
            sl = slice(mb * micro, (mb + 1) * micro)
            ids = eng.shard_batch({"input_ids": ids_all[sl]},
                                  accumulate=False)["input_ids"]
            mask = None if mask_all is None else eng.shard_batch(
                {"m": mask_all[sl]}, accumulate=False)["m"]
            last = mb == gas - 1
            loss, sq, ok = self._micro_fwd_bwd(
                fns, cos, sin, ids, mask, scale, denom, mb, gas, last)
            losses.append(loss)
            sq_norm += sq
            finite = finite and ok

        if self._multi:
            # each process summed only its save-owned fragments; the
            # global grad norm / overflow flag need the cross-process sum
            from jax.experimental import multihost_utils
            g = multihost_utils.process_allgather(
                np.asarray([sq_norm, 0.0 if finite else 1.0],
                           np.float64))
            sq_norm = float(np.asarray(g)[..., 0].sum())
            finite = float(np.asarray(g)[..., 1].sum()) == 0.0
        gnorm = float(np.sqrt(sq_norm))
        metrics: Dict[str, Any] = {
            "loss": jnp.float32(float(np.mean(losses))),
            "grad_norm": jnp.float32(gnorm),
            "loss_scale": jnp.float32(scale),
            "overflow": jnp.int32(0 if finite else 1),
        }
        new_scale_state = eng.scaler.update(
            eng.state.loss_scale, jnp.asarray(not finite))

        step_next = int(np.asarray(eng.state.step)) + 1
        lr = float(np.asarray(eng.lr_schedule(np.float32(step_next))))
        metrics["lr"] = jnp.float32(lr)
        if finite:
            clip = eng.config.gradient_clipping
            factor = 1.0 if not clip or clip <= 0 else min(
                1.0, clip / (gnorm + 1e-6))
            self._update_sweep(lr, step_next, factor / gas)
            new_step = jnp.asarray(step_next, jnp.int32)
            skipped = eng.state.skipped
        else:
            new_step = eng.state.step
            skipped = eng.state.skipped + 1
        from .engine import TrainState
        eng.state = TrainState(
            step=new_step, master=self.resident, opt_state=(),
            loss_scale=new_scale_state, skipped=skipped)
        return metrics

    def _micro_fwd_bwd(self, fns, cos, sin, ids, mask, scale, denom,
                       mb: int, gas: int, last: bool
                       ) -> Tuple[float, float, bool]:
        """One micro-batch: forward sweep, head, backward sweep with grad
        spill/accumulate.  Returns (loss, sq_norm_contrib, finite) —
        sq_norm/finite only computed on the last micro-batch."""
        L = self.L
        # ---- forward sweep: layer l computes while l+1 reads -------------
        acts = [None] * L
        x = fns["embed"](self.resident, ids)
        if L:
            self._pstore.prefetch_group(0, self._pstore_tpl)
        for l in range(L):
            lp = self._fetch_layer(l)
            if l + 1 < L:
                self._pstore.prefetch_group(l + 1, self._pstore_tpl)
            acts[l] = x
            x = fns["layer"](lp, x, cos, sin, mask)
            del lp
        loss, d_res, d_x = fns["head_bwd"](self.resident, x, ids, mask,
                                           jnp.float32(scale))
        if self._multi:
            from .zero_infinity import dedup_addressable_frags
            res_grads = [dedup_addressable_frags(g, self._rfrags[j])
                         for j, g in enumerate(jax.tree.leaves(d_res))]
        else:
            res_grads = jax.tree.map(np.asarray, d_res)
        # ---- backward sweep (reverse order, prefetch l-1) ----------------
        sq = 0.0
        finite = True
        if L:
            self._pstore.prefetch_group(L - 1, self._pstore_tpl)
        for l in range(L - 1, -1, -1):
            lp = self._fetch_layer(l)
            if l - 1 >= 0:
                self._pstore.prefetch_group(l - 1, self._pstore_tpl)
            d_lp, d_x = fns["layer_bwd"](lp, acts[l], cos, sin, mask, d_x)
            acts[l] = None
            del lp
            s, f = self._spill_layer_grads(l, d_lp, denom, mb, last, gas)
            sq += s
            finite = finite and f
        d_res2 = fns["embed_bwd"](self.resident, ids, d_x)
        if self._multi:
            from .zero_infinity import dedup_addressable_frags
            for j, g in enumerate(jax.tree.leaves(d_res2)):
                add = dedup_addressable_frags(g, self._rfrags[j])
                res_grads[j] = [a + b
                                for a, b in zip(res_grads[j], add)]
        else:
            for k in d_res2:
                res_grads[k] = jax.tree.map(
                    lambda a, b: a + np.asarray(b), res_grads[k],
                    d_res2[k])
        s, f = self._spill_resident_grads(res_grads, denom, mb, last, gas)
        return float(np.asarray(loss)), sq + s, finite and f

    def _accum_spill(self, group: int, tpl, new_host, denom: float,
                     mb: int, last: bool, gas: int,
                     owned=None) -> Tuple[float, bool]:
        """Write (or accumulate into) a grad-store group; on the last
        micro-batch compute the sq-norm/finite stats of the (sum/gas).
        ``owned``: multi-host save-ownership flags per leaf fragment —
        replica fragments are excluded so the cross-process norm sum
        counts each region exactly once."""
        nbytes = _tree_bytes(tpl)
        self.meter.alloc(nbytes)
        try:
            # unscale THIS micro-batch's grads before accumulating (the
            # stored partial sums are already unscaled)
            if denom != 1.0:
                new_host = jax.tree.map(lambda a: a / denom, new_host)
            if mb > 0:
                prev = self._gstore.read_group(group, tpl)
                self.meter.alloc(nbytes)
                new_host = jax.tree.map(
                    lambda a, b: a + b, prev, new_host)
                self.meter.free(nbytes)
            sq, finite = 0.0, True
            if last:
                leaves = jax.tree.leaves(new_host)
                flags = ([True] * len(leaves) if owned is None
                         else [o for sub in owned for o in sub])
                for g, own in zip(leaves, flags):
                    if not own:
                        continue
                    ga = g / gas
                    s = float(np.sum(ga.astype(np.float64) ** 2))
                    sq += s
                    finite = finite and np.isfinite(s)
            self._gstore.write_group(group, new_host)
            return sq, finite
        finally:
            self.meter.free(nbytes)

    def _spill_layer_grads(self, l: int, d_lp, denom, mb, last, gas):
        if self._multi:
            from .zero_infinity import dedup_addressable_frags
            host = [dedup_addressable_frags(g, self._lfrags[j])
                    for j, g in enumerate(jax.tree.leaves(d_lp))]
            return self._accum_spill(l, self._lgrad_tpl, host, denom,
                                     mb, last, gas, owned=self._lowned)
        host = jax.tree.map(np.asarray, d_lp)
        return self._accum_spill(l, self._lgrad_tpl, host, denom,
                                 mb, last, gas)

    def _spill_resident_grads(self, res_grads, denom, mb, last, gas):
        return self._accum_spill(
            self.L, self._rgrad_tpl, res_grads, denom, mb, last, gas,
            owned=self._rowned if self._multi else None)

    # ------------------------------------------------------------------
    # update sweep
    # ------------------------------------------------------------------
    def _update_sweep(self, lr: float, step_num: int,
                      grad_scale: float) -> None:
        """Grouped NVMe master update consuming the grad store lazily;
        fresh bf16 leaves stream back to the param store per layer."""
        trainer = self

        class _LazyGrad:
            __slots__ = ("i",)
            _cache: Dict[Any, Any] = {}
            _cache_bytes: int = 0

            def __init__(self, i):
                self.i = i

            def _group(self):
                kind, l, j = trainer._leafmap[self.i]
                gkey = l if kind == "layer" else trainer.L
                if gkey not in _LazyGrad._cache:
                    tpl = (trainer._lgrad_tpl if kind == "layer"
                           else trainer._rgrad_tpl)
                    _LazyGrad._cache.clear()
                    trainer.meter.free(_LazyGrad._cache_bytes)
                    arr = trainer._gstore.read_group(gkey, tpl)
                    _LazyGrad._cache[gkey] = arr
                    _LazyGrad._cache_bytes = _tree_bytes(tpl)
                    trainer.meter.alloc(_LazyGrad._cache_bytes)
                return _LazyGrad._cache[gkey], kind, j

            def __array__(self, dtype=None, copy=None):
                if trainer._multi:
                    # fragments never materialize a full leaf; the
                    # optimizer consumes them via frag_map()
                    raise TypeError(
                        "multi-host lazy grads are fragment-only")
                arr, kind, j = self._group()
                g = jax.tree.leaves(arr)[j] * grad_scale
                return g.astype(dtype) if dtype is not None and \
                    np.dtype(dtype) != g.dtype else g

            def frag_map(self):
                """Multi-host: this leaf's grad fragments keyed by shard
                index (the NVMeOptimizer fragment contract)."""
                arr, kind, j = self._group()
                frags = (trainer._lfrags if kind == "layer"
                         else trainer._rfrags)[j]
                return {idx: arr[j][k] * grad_scale
                        for k, idx in enumerate(frags)}

        grads = [_LazyGrad(i) for i in range(len(self._leafmap))]
        dt = self.eng.compute_dtype
        staging: Dict[int, Dict[int, np.ndarray]] = {}
        new_resident: Dict[int, np.ndarray] = {}
        n_layer_leaves = len(jax.tree.leaves(self._layer_tpl))
        n_res_leaves = len(jax.tree.leaves(self._res_grad_tpl))

        def cast(p):
            if isinstance(p, list):            # multi-host fragment list
                return [f.astype(dt) for f in p]
            return p.astype(dt)

        def consume(i: int, p_new) -> None:
            kind, l, j = self._leafmap[i]
            if kind == "layer":
                lay = staging.setdefault(l, {})
                lay[j] = cast(p_new)
                if len(lay) == n_layer_leaves:
                    flat = [lay[j2] for j2 in range(n_layer_leaves)]
                    tree = (flat if self._multi else
                            jax.tree.unflatten(
                                jax.tree.structure(self._layer_tpl),
                                flat))
                    self._pstore.write_group(l, tree)
                    del staging[l]
            else:
                new_resident[j] = cast(p_new)

        self._opt.step(grads, lr, step_num, consume=consume)
        _LazyGrad._cache.clear()
        self.meter.free(_LazyGrad._cache_bytes)
        assert not staging and len(new_resident) == n_res_leaves
        flat = [new_resident[j] for j in range(n_res_leaves)]
        if self._multi:
            flat_sh = jax.tree.leaves(
                self._res_sh,
                is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
            tpl_flat = jax.tree.leaves(self._res_grad_tpl)
            leaves = [self._assemble(flat[j], tpl_flat[j].shape,
                                     flat_sh[j], self._rfrags[j], dt)
                      for j in range(n_res_leaves)]
        else:
            flat_sh = jax.tree.leaves(
                self._res_sh,
                is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
            leaves = [jax.device_put(flat[j], flat_sh[j])
                      for j in range(n_res_leaves)]
        self.resident = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self._res_grad_tpl), leaves)

    # ------------------------------------------------------------------
    # eval / checkpoint surface
    # ------------------------------------------------------------------
    def eval_batch(self, batch, rng) -> jax.Array:
        fns = self._programs("attention_mask" in batch)
        cos, sin = self._cos_sin_arrays()
        eng = self.eng
        ids = eng.shard_batch({"input_ids": np.asarray(batch["input_ids"])},
                              accumulate=False)["input_ids"]
        mask = batch.get("attention_mask")
        mask = None if mask is None else eng.shard_batch(
            {"m": np.asarray(mask)}, accumulate=False)["m"]
        x = fns["embed"](self.resident, ids)
        if self.L:
            self._pstore.prefetch_group(0, self._pstore_tpl)
        for l in range(self.L):
            lp = self._fetch_layer(l)
            if l + 1 < self.L:
                self._pstore.prefetch_group(l + 1, self._pstore_tpl)
            x = fns["layer"](lp, x, cos, sin, mask)
        return fns["head_loss"](self.resident, x, ids, mask)

    def master_template(self):
        """fp32 ShapeDtypeStruct tree in the ORIGINAL stacked structure
        (the checkpoint template)."""
        f32 = lambda s: jax.ShapeDtypeStruct(s.shape, np.float32)
        return {**jax.tree.map(f32, self._res_grad_tpl),
                "blocks": jax.tree.map(f32, self._blocks_tpl)}

    def state_trees(self, lazy: bool = False):
        """fp32 (master, m, v) in the ORIGINAL stacked param structure
        (checkpoint compatibility with non-streamed runs).  Stacked
        leaves materialize one at a time (peak host = one stacked leaf);
        ``lazy`` defers each leaf's read+stack to ``np.asarray``.
        Multi-host: stacked HostShards — each process contributes its
        save-owned (layer, fragment) regions, read lazily."""
        un_m, un_mo, un_v = self._opt.state_trees(lazy=lazy)

        def restack(un):
            if self._multi:
                from ..checkpoint.engine import HostShards

                def stack_hs(*ls):
                    hs = HostShards.__new__(HostShards)
                    hs.shape = (len(ls),) + tuple(ls[0].shape)
                    hs.dtype = ls[0].dtype

                    def gen(_ls=ls):
                        for l, sub in enumerate(_ls):
                            for idx, data in sub.shards:
                                yield ((slice(l, l + 1),) + tuple(idx),
                                       data[None])
                    hs.shards = gen()
                    return hs

                blocks = jax.tree.map(
                    stack_hs, *un["layers"],
                    is_leaf=lambda x: not isinstance(x, (dict, list)))
                return {**un["resident"], "blocks": blocks}
            blocks = jax.tree.map(
                lambda *ls: _LazyStack(ls) if lazy
                else np.stack([np.asarray(x) for x in ls]),
                *un["layers"])
            return {**un["resident"], "blocks": blocks}

        return restack(un_m), restack(un_mo), restack(un_v)

    def restore(self, master, m=None, v=None) -> None:
        """Load full stacked fp32 trees into the NVMe stores and refresh
        the bf16 working copies (checkpoint load)."""
        blocks = master["blocks"]
        resident = {k: v2 for k, v2 in master.items() if k != "blocks"}

        def unstack(tree):
            if tree is None:
                return None
            b = tree["blocks"]
            return {"layers": [jax.tree.map(lambda x: np.asarray(x)[l], b)
                               for l in range(self.L)],
                    "resident": {k: v2 for k, v2 in tree.items()
                                 if k != "blocks"}}

        self._opt.restore(unstack(master), unstack(m), unstack(v))
        dt = self.eng.compute_dtype
        for l in range(self.L):
            if self._multi:
                lp = [[np.asarray(x)[l][idx].astype(dt) if idx
                       else np.asarray(x)[l].astype(dt)
                       for idx in self._lfrags[j]]
                      for j, x in enumerate(jax.tree.leaves(blocks))]
            else:
                lp = jax.tree.map(
                    lambda x: np.asarray(x)[l].astype(dt), blocks)
            self._pstore.write_group(l, lp)
        self.resident = jax.tree.map(
            lambda a, sh: jax.device_put(np.asarray(a).astype(dt), sh),
            resident, self._res_sh)


class _LazyStack:
    """A lazily-stacked checkpoint leaf over per-layer lazy NVMe leaves;
    materializes [L, ...] only when np.asarray touches it."""

    __slots__ = ("_leaves", "shape", "dtype")

    def __init__(self, leaves):
        self._leaves = leaves
        self.shape = (len(leaves),) + tuple(leaves[0].shape)
        self.dtype = np.dtype(leaves[0].dtype)

    def __array__(self, dtype=None, copy=None):
        out = np.stack([np.asarray(x) for x in self._leaves])
        return out.astype(dtype) if dtype is not None and \
            np.dtype(dtype) != out.dtype else out
