"""ZeRO-Infinity: NVMe-backed optimizer state wired into the engine step.

TPU-native re-design of the reference's NVMe offload orchestration
(``runtime/swap_tensor/partitioned_param_swapper.py:37``,
``pipelined_optimizer_swapper.py``, engine hookup ``stage3.py:614``
``_configure_tensor_swapping``): fp32 master parameters and Adam moments
live in aligned files on NVMe (written through the native aio thread
pool, ``native/aio.cpp``), only the bf16 working copy of the parameters
stays on device, and the optimizer update runs group-by-group on the host
with double-buffered prefetch — group g+1's NVMe read is in flight while
group g's update computes, the pipelined schedule of
``pipelined_optimizer_swapper.py``.

The host update itself is the ``cpu_adam`` analog (``csrc/adam/
cpu_adam_impl.cpp`` AVX loops): numpy's vectorized kernels over fp32
buffers, numerically identical to the in-graph fused AdamW
(:mod:`.optimizers`), so an NVMe run tracks a no-offload run to float
tolerance.

Division of labor with the engine: the engine's jitted step produces
unscaled, clipped, ZeRO-layout gradients (and the overflow flag); this
module owns everything below — group partitioning, swap files, the host
update, and handing back fresh bf16 leaves for the device working copy.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..config.config import ConfigError
from ..utils.logging import log_dist, logger
from .swap_tensor import OptimizerSwapper


class HostAdam:
    """Numpy host optimizer mirroring :mod:`runtime.optimizers` exactly —
    the DeepSpeedCPUAdam/CPUAdagrad/CPULion family (reference:
    csrc/adam/cpu_adam_impl.cpp, csrc/adagrad, csrc/lion) for
    NVMe-offloaded state.  All variants keep the (m, v) slot pair so the
    group swapper's on-disk layout is uniform; adagrad uses v as its
    accumulator, lion/sgd leave v untouched."""

    SUPPORTED = ("adam", "adamw", "lion", "adagrad", "sgd")

    def __init__(self, opt_type: str, params: Dict[str, Any]):
        t = opt_type.lower()
        if t not in self.SUPPORTED:
            raise ConfigError(
                f"offload_optimizer.device=nvme supports "
                f"{'/'.join(self.SUPPORTED)}, got {opt_type!r}")
        self.type = t
        default_betas = (0.9, 0.99) if t == "lion" else (0.9, 0.999)
        self.b1, self.b2 = params.get("betas", default_betas)
        self.eps = params.get(
            "eps", 1e-10 if t == "adagrad" else 1e-8)
        default_wd = 0.01 if t == "adamw" else 0.0
        self.weight_decay = params.get("weight_decay", default_wd)
        self.adam_w_mode = params.get("adam_w_mode", t == "adamw")
        self.bias_correction = params.get("bias_correction", True)
        self.momentum = params.get("momentum", 0.0)
        self.nesterov = params.get("nesterov", False)

    def update(self, p: np.ndarray, m: np.ndarray, v: np.ndarray,
               g: np.ndarray, lr: float, step: int) -> None:
        """In-place fp32 update of (p, m, v) with gradient g."""
        g = g.astype(np.float32, copy=False)
        if self.type == "lion":
            # mirrors optimizers.lion: sign step on interpolated moment,
            # decoupled decay (against the PRE-step p), moment EMA after
            delta = lr * np.sign(self.b1 * m + (1.0 - self.b1) * g)
            if self.weight_decay:
                delta = delta + lr * self.weight_decay * p
            p -= delta
            np.multiply(m, self.b2, out=m)
            m += (1.0 - self.b2) * g
            return
        if self.type == "adagrad":
            if self.weight_decay:
                g = g + self.weight_decay * p
            v += np.square(g)
            p -= lr * g / (np.sqrt(v) + self.eps)
            return
        if self.type == "sgd":
            if self.weight_decay:
                g = g + self.weight_decay * p
            if self.momentum:
                np.multiply(m, self.momentum, out=m)
                m += g
                # nesterov mirrors optimizers.sgd: d = g + mu * b_new
                g = g + self.momentum * m if self.nesterov else m
            p -= lr * g
            return
        if not self.adam_w_mode and self.weight_decay:
            g = g + self.weight_decay * p
        np.multiply(m, self.b1, out=m)
        m += (1.0 - self.b1) * g
        np.multiply(v, self.b2, out=v)
        v += (1.0 - self.b2) * np.square(g)
        if self.bias_correction:
            c1 = 1.0 - self.b1 ** step
            c2 = 1.0 - self.b2 ** step
        else:
            c1 = c2 = 1.0
        denom = np.sqrt(v / c2)
        denom += self.eps
        if self.adam_w_mode and self.weight_decay:
            p *= 1.0 - lr * self.weight_decay
        p -= lr * (m / c1) / denom


class LazyNVMeLeaf:
    """A checkpoint leaf that reads its swap group from NVMe only when
    materialized (``np.asarray``) — the streamed >host-DRAM save path.
    Carries .shape/.dtype so the fragment writer never has to touch the
    payload for metadata."""

    __slots__ = ("_read", "_g", "_col", "_j", "shape", "dtype")

    def __init__(self, read, g: int, col: int, j: int, shape, dtype):
        self._read = read
        self._g, self._col, self._j = g, col, j
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    def __array__(self, dtype=None, copy=None):
        arr = self._read(self._g, self._col, self._j)
        if dtype is not None and np.dtype(dtype) != arr.dtype:
            return arr.astype(dtype)            # astype copies
        # the cache owns `arr`; honor an explicit copy request so a
        # caller's mutation can never corrupt sibling leaves
        return arr.copy() if copy else arr


class NVMeOptimizer:
    """Group-partitioned NVMe state store + pipelined host update."""

    def __init__(self, nvme_path: str, opt_type: str,
                 opt_params: Dict[str, Any],
                 buffer_size: int = 100_000_000):
        if not nvme_path:
            raise ConfigError(
                "offload_optimizer.device=nvme requires nvme_path")
        if jax.process_count() > 1:
            # the host update consumes globally-assembled arrays
            # (np.asarray of sharded grads), which a multi-controller run
            # cannot fetch; per-host local-shard swapping is future work
            raise ConfigError(
                "offload_optimizer.device=nvme is single-controller only "
                "for now (use device=cpu on multi-host runs)")
        # namespace by process + a per-engine token so two runs (or two
        # engines) sharing one NVMe mount never overwrite each other's
        # state (the reference swapper namespaces by rank the same way)
        token = f"r{jax.process_index()}_{os.getpid()}_{id(self):x}"
        self.dir = os.path.join(nvme_path, "zero_infinity", token)
        import shutil
        import weakref
        # swap files are scratch state — reclaim the NVMe space when the
        # engine goes away (weakref.finalize also fires at exit)
        self._cleanup = weakref.finalize(
            self, shutil.rmtree, self.dir, True)
        self.adam = HostAdam(opt_type, opt_params)
        self.buffer_size = max(int(buffer_size), 1)
        self.groups: List[List[int]] = []      # leaf indices per group
        self.swapper: Optional[OptimizerSwapper] = None
        self._treedef = None
        self._leaf_meta: List[Tuple[tuple, Any]] = []

    # ------------------------------------------------------------------
    def initialize(self, params: Any) -> None:
        """Partition leaves into ~buffer_size groups; write fp32 master +
        zero moments to NVMe (the zero.Init-time partitioning analog)."""
        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        self._leaf_meta = [(tuple(np.shape(x)), np.float32) for x in leaves]
        self.groups = []
        cur, cur_bytes = [], 0
        for i, leaf in enumerate(leaves):
            nbytes = int(np.prod(np.shape(leaf)) or 1) * 4
            if cur and cur_bytes + nbytes > self.buffer_size:
                self.groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            self.groups.append(cur)
        self.swapper = OptimizerSwapper(self.dir, len(self.groups))
        for g, idxs in enumerate(self.groups):
            ps = [np.asarray(leaves[i], np.float32) for i in idxs]
            ms = [np.zeros_like(p) for p in ps]
            vs = [np.zeros_like(p) for p in ps]
            self.swapper.write_group(g, (ps, ms, vs))
        log_dist(f"ZeRO-Infinity: {len(leaves)} leaves in "
                 f"{len(self.groups)} NVMe swap groups under {self.dir}")

    def _template(self, g: int):
        shapes = [self._leaf_meta[i] for i in self.groups[g]]
        mk = lambda: [jax.ShapeDtypeStruct(s, d) for s, d in shapes]
        return (mk(), mk(), mk())

    # ------------------------------------------------------------------
    def step(self, grad_leaves: Sequence[Any], lr: float,
             step_num: int) -> List[np.ndarray]:
        """One optimizer step over all groups with double-buffered
        prefetch.  ``grad_leaves``: flat leaves (device arrays; fetched
        lazily per group).  Returns flat fp32 master leaves."""
        assert self.swapper is not None, "initialize() first"
        new_leaves: List[Optional[np.ndarray]] = [None] * len(self._leaf_meta)
        G = len(self.groups)
        if G:
            self.swapper.prefetch_group(0, self._template(0))
        for g, idxs in enumerate(self.groups):
            if g + 1 < G:       # overlap: next group's read behind update
                self.swapper.prefetch_group(g + 1, self._template(g + 1))
            ps, ms, vs = self.swapper.read_group(g, self._template(g))
            for j, i in enumerate(idxs):
                gnp = np.asarray(grad_leaves[i], np.float32)
                self.adam.update(ps[j], ms[j], vs[j], gnp, lr, step_num)
                new_leaves[i] = ps[j]
            self.swapper.write_group(g, (ps, ms, vs))
        return new_leaves  # type: ignore[return-value]

    # ------------------------------------------------------------------
    # checkpoint support: materialize / restore the full fp32 state
    #
    # ------------------------------------------------------------------
    def state_trees(self, lazy: bool = False) -> Tuple[Any, Any, Any]:
        """(master, m, v) full trees in one pass over the swap groups.

        ``lazy=True`` returns trees of :class:`LazyNVMeLeaf` — each leaf
        reads its swap group from NVMe only when ``np.asarray`` touches
        it, with a one-group cache.  The checkpoint writer walks leaves
        sequentially, so peak host RAM is ONE swap group instead of the
        whole fp32 state (the >host-DRAM checkpoint path)."""
        if lazy:
            cache: Dict[Tuple[int, int], list] = {}

            def read(g: int, col: int, j: int) -> np.ndarray:
                # per-(group, COLUMN) reads: the checkpoint walk is
                # column-major (all master leaves, then m, then v), so a
                # whole-group read would fetch 3x the bytes per pass;
                # reading one column's keys keeps total IO at 1x state
                if (g, col) not in cache:
                    cache.clear()                # one column-group resident
                    cache[(g, col)] = self._read_column(g, col)
                return cache[(g, col)][j]

            cols = [[None] * len(self._leaf_meta) for _ in range(3)]
            for g, idxs in enumerate(self.groups):
                for col in range(3):
                    for j, i in enumerate(idxs):
                        shape, dtype = self._leaf_meta[i]
                        cols[col][i] = LazyNVMeLeaf(read, g, col, j,
                                                    shape, dtype)
            return tuple(jax.tree_util.tree_unflatten(self._treedef, col)
                         for col in cols)
        cols = [[None] * len(self._leaf_meta) for _ in range(3)]
        for g, idxs in enumerate(self.groups):
            parts = self.swapper.read_group(g, self._template(g))
            for col, vals in zip(cols, parts):
                for j, i in enumerate(idxs):
                    col[i] = vals[j]
        return tuple(jax.tree_util.tree_unflatten(self._treedef, col)
                     for col in cols)

    def _read_column(self, g: int, col: int) -> list:
        """Read one column (0=master, 1=m, 2=v) of swap group ``g``.

        The group template is the (ps, ms, vs) tuple, so its flat key
        order is column-contiguous — the column's keys are one slice."""
        tmpl = self._template(g)
        keys = self.swapper._keys(g, tmpl)
        n = len(keys) // 3
        sw = self.swapper._swapper(g)
        # batch the column's reads through the aio queue (a sync
        # swap_in per leaf would serialize NVMe latency per leaf)
        bufs = [sw.swap_in(k, async_op=True)
                for k in keys[col * n:(col + 1) * n]]
        sw.wait()
        return bufs

    def master_tree(self) -> Any:
        return self.state_trees()[0]

    def restore(self, master: Any, m: Any = None, v: Any = None) -> None:
        """Overwrite NVMe state from full trees (checkpoint load)."""
        p_leaves = jax.tree_util.tree_leaves(master)
        m_leaves = jax.tree_util.tree_leaves(m) if m is not None else None
        v_leaves = jax.tree_util.tree_leaves(v) if v is not None else None
        for g, idxs in enumerate(self.groups):
            ps = [np.asarray(p_leaves[i], np.float32) for i in idxs]
            ms = ([np.asarray(m_leaves[i], np.float32) for i in idxs]
                  if m_leaves else [np.zeros_like(p) for p in ps])
            vs = ([np.asarray(v_leaves[i], np.float32) for i in idxs]
                  if v_leaves else [np.zeros_like(p) for p in ps])
            self.swapper.write_group(g, (ps, ms, vs))
