"""ZeRO-Infinity: NVMe-backed optimizer state wired into the engine step.

TPU-native re-design of the reference's NVMe offload orchestration
(``runtime/swap_tensor/partitioned_param_swapper.py:37``,
``pipelined_optimizer_swapper.py``, engine hookup ``stage3.py:614``
``_configure_tensor_swapping``): fp32 master parameters and Adam moments
live in aligned files on NVMe (written through the native aio thread
pool, ``native/aio.cpp``), only the bf16 working copy of the parameters
stays on device, and the optimizer update runs group-by-group on the host
with double-buffered prefetch — group g+1's NVMe read is in flight while
group g's update computes, the pipelined schedule of
``pipelined_optimizer_swapper.py``.

The host update itself is the ``cpu_adam`` analog (``csrc/adam/
cpu_adam_impl.cpp`` AVX loops): numpy's vectorized kernels over fp32
buffers, numerically identical to the in-graph fused AdamW
(:mod:`.optimizers`), so an NVMe run tracks a no-offload run to float
tolerance.

Division of labor with the engine: the engine's jitted step produces
unscaled, clipped, ZeRO-layout gradients (and the overflow flag); this
module owns everything below — group partitioning, swap files, the host
update, and handing back fresh bf16 leaves for the device working copy.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..config.config import ConfigError
from ..utils.logging import log_dist, logger
from .swap_tensor import OptimizerSwapper


class HostAdam:
    """Numpy host optimizer mirroring :mod:`runtime.optimizers` exactly —
    the DeepSpeedCPUAdam/CPUAdagrad/CPULion family (reference:
    csrc/adam/cpu_adam_impl.cpp, csrc/adagrad, csrc/lion) for
    NVMe-offloaded state.  All variants keep the (m, v) slot pair so the
    group swapper's on-disk layout is uniform; adagrad uses v as its
    accumulator, lion/sgd leave v untouched."""

    SUPPORTED = ("adam", "adamw", "lion", "adagrad", "sgd")

    def __init__(self, opt_type: str, params: Dict[str, Any]):
        t = opt_type.lower()
        if t not in self.SUPPORTED:
            raise ConfigError(
                f"offload_optimizer.device=nvme supports "
                f"{'/'.join(self.SUPPORTED)}, got {opt_type!r}")
        self.type = t
        default_betas = (0.9, 0.99) if t == "lion" else (0.9, 0.999)
        self.b1, self.b2 = params.get("betas", default_betas)
        self.eps = params.get(
            "eps", 1e-10 if t == "adagrad" else 1e-8)
        default_wd = 0.01 if t == "adamw" else 0.0
        self.weight_decay = params.get("weight_decay", default_wd)
        self.adam_w_mode = params.get("adam_w_mode", t == "adamw")
        self.bias_correction = params.get("bias_correction", True)
        self.momentum = params.get("momentum", 0.0)
        self.nesterov = params.get("nesterov", False)

    def update(self, p: np.ndarray, m: np.ndarray, v: np.ndarray,
               g: np.ndarray, lr: float, step: int) -> None:
        """In-place fp32 update of (p, m, v) with gradient g."""
        g = g.astype(np.float32, copy=False)
        if self.type == "lion":
            # mirrors optimizers.lion: sign step on interpolated moment,
            # decoupled decay (against the PRE-step p), moment EMA after
            delta = lr * np.sign(self.b1 * m + (1.0 - self.b1) * g)
            if self.weight_decay:
                delta = delta + lr * self.weight_decay * p
            p -= delta
            np.multiply(m, self.b2, out=m)
            m += (1.0 - self.b2) * g
            return
        if self.type == "adagrad":
            if self.weight_decay:
                g = g + self.weight_decay * p
            v += np.square(g)
            p -= lr * g / (np.sqrt(v) + self.eps)
            return
        if self.type == "sgd":
            if self.weight_decay:
                g = g + self.weight_decay * p
            if self.momentum:
                np.multiply(m, self.momentum, out=m)
                m += g
                # nesterov mirrors optimizers.sgd: d = g + mu * b_new
                g = g + self.momentum * m if self.nesterov else m
            p -= lr * g
            return
        if not self.adam_w_mode and self.weight_decay:
            g = g + self.weight_decay * p
        np.multiply(m, self.b1, out=m)
        m += (1.0 - self.b1) * g
        np.multiply(v, self.b2, out=v)
        v += (1.0 - self.b2) * np.square(g)
        if self.bias_correction:
            c1 = 1.0 - self.b1 ** step
            c2 = 1.0 - self.b2 ** step
        else:
            c1 = c2 = 1.0
        denom = np.sqrt(v / c2)
        denom += self.eps
        if self.adam_w_mode and self.weight_decay:
            p *= 1.0 - lr * self.weight_decay
        p -= lr * (m / c1) / denom


class LazyNVMeLeaf:
    """A checkpoint leaf that reads its swap group from NVMe only when
    materialized (``np.asarray``) — the streamed >host-DRAM save path.
    Carries .shape/.dtype so the fragment writer never has to touch the
    payload for metadata."""

    __slots__ = ("_read", "_g", "_col", "_j", "shape", "dtype")

    def __init__(self, read, g: int, col: int, j: int, shape, dtype):
        self._read = read
        self._g, self._col, self._j = g, col, j
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    def __array__(self, dtype=None, copy=None):
        arr = self._read(self._g, self._col, self._j)
        if dtype is not None and np.dtype(dtype) != arr.dtype:
            return arr.astype(dtype)            # astype copies
        # the cache owns `arr`; honor an explicit copy request so a
        # caller's mutation can never corrupt sibling leaves
        return arr.copy() if copy else arr


def shard_fragments(shape, sharding) -> Tuple[List[tuple], List[bool]]:
    """This process's distinct shard fragments of an array with ``shape``
    under ``sharding``: (fragment shard-indices, save-ownership flags).

    Fragments are the deduped addressable shard indices; exactly one
    process globally "save-owns" each index (the one holding its
    lowest-id device) so checkpoint writers emit each region once
    (reference: per-rank swap-file ownership, stage3.py:614)."""
    my_devs = {d.id for d in jax.local_devices()}
    by_idx: Dict[tuple, List[int]] = {}
    for d, idx in sharding.devices_indices_map(tuple(shape)).items():
        by_idx.setdefault(tuple(idx), []).append(d.id)
    frags, owned = [], []
    for idx in sorted(by_idx, key=lambda t: min(by_idx[t])):
        holders = by_idx[idx]
        if not my_devs.intersection(holders):
            continue
        frags.append(idx)
        owned.append(min(holders) in my_devs)
    return frags, owned


def fragment_shape(shape, idx) -> tuple:
    if not idx:
        return tuple(shape)
    return tuple(
        (sl.stop if sl.stop is not None else dim)
        - (sl.start if sl.start is not None else 0)
        for sl, dim in zip(idx, shape))


def dedup_addressable_frags(arr: jax.Array, frags: Sequence[tuple],
                            dtype=np.float32) -> List[np.ndarray]:
    """Fetch ``arr``'s local shards matching ``frags`` (order preserved);
    raises if the array's layout doesn't produce a required index."""
    by_idx: Dict[tuple, Any] = {}
    for sh in arr.addressable_shards:
        by_idx.setdefault(tuple(sh.index), sh.data)
    out = []
    for idx in frags:
        if idx not in by_idx:
            raise ValueError(
                f"array layout mismatch: no addressable shard at {idx} "
                f"(have {sorted(by_idx)[:4]}...)")
        out.append(np.asarray(by_idx[idx], dtype))
    return out


class NVMeOptimizer:
    """Group-partitioned NVMe state store + pipelined host update."""

    def __init__(self, nvme_path: str, opt_type: str,
                 opt_params: Dict[str, Any],
                 buffer_size: int = 100_000_000,
                 aio_config=None):
        if not nvme_path:
            raise ConfigError(
                "offload_optimizer.device=nvme requires nvme_path")
        # namespace by process + a per-engine token so two runs (or two
        # engines) sharing one NVMe mount never overwrite each other's
        # state (the reference swapper namespaces by rank the same way)
        token = f"r{jax.process_index()}_{os.getpid()}_{id(self):x}"
        self.dir = os.path.join(nvme_path, "zero_infinity", token)
        import shutil
        import weakref
        # swap files are scratch state — reclaim the NVMe space when the
        # engine goes away (weakref.finalize also fires at exit)
        self._cleanup = weakref.finalize(
            self, shutil.rmtree, self.dir, True)
        self.adam = HostAdam(opt_type, opt_params)
        self.buffer_size = max(int(buffer_size), 1)
        self.aio_config = aio_config
        self.groups: List[List[int]] = []      # leaf indices per group
        self.swapper: Optional[OptimizerSwapper] = None
        self._treedef = None
        self._leaf_meta: List[Tuple[tuple, Any]] = []
        # optional ResidencyMeter (param_stream.py) accounting the host
        # bytes of the in-flight swap group
        self.meter = None
        # multi-host: per-leaf addressable fragments (reference: per-rank
        # swap files in stage3.py:614 — every process swaps only the
        # shards its own devices hold)
        self._multi = jax.process_count() > 1
        self._frags: List[List[tuple]] = []        # leaf -> [shard index]
        self._save_owned: List[List[bool]] = []    # leaf -> [this proc saves]
        self._shardings: Optional[List[Any]] = None

    # ------------------------------------------------------------------
    def initialize(self, params: Any, shardings: Any = None) -> None:
        """Partition leaves into ~buffer_size groups; write fp32 master +
        zero moments to NVMe (the zero.Init-time partitioning analog).

        Multi-host: ``shardings`` (a matching tree of NamedShardings —
        the layout the step's gradients arrive in) is required; each
        process stores only the fragments its own devices address
        (reference: per-rank swap files, stage3.py:614), deduplicating
        replicas within the process."""
        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        self._leaf_meta = [(tuple(np.shape(x)), np.float32) for x in leaves]
        if self._multi:
            if shardings is None:
                raise ConfigError(
                    "multi-host NVMe state needs the gradient shardings "
                    "(engine wires these automatically)")
            self._shardings = jax.tree_util.tree_leaves(
                shardings,
                is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
            self._frags, self._save_owned = [], []
            for (shape, _), sh in zip(self._leaf_meta, self._shardings):
                frags, owned = shard_fragments(shape, sh)
                self._frags.append(frags)
                self._save_owned.append(owned)
        leaf_bytes = [
            (sum(int(np.prod(self._frag_shape(i, k)) or 1) * 4
                 for k in range(len(self._frags[i]))) if self._multi
             else int(np.prod(self._leaf_meta[i][0]) or 1) * 4)
            for i in range(len(leaves))]
        self.groups = []
        cur, cur_bytes = [], 0
        for i, leaf in enumerate(leaves):
            nbytes = leaf_bytes[i]
            if cur and cur_bytes + nbytes > self.buffer_size:
                self.groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            self.groups.append(cur)
        # p+m+v resident bytes per group (fragment-aware in multi-host
        # mode — leaf_bytes already counts only this rank's fragments)
        self._group_bytes = [3 * sum(leaf_bytes[i] for i in idxs)
                             for idxs in self.groups]
        self.swapper = OptimizerSwapper(self.dir, len(self.groups),
                                        aio_config=self.aio_config)
        for g, idxs in enumerate(self.groups):
            ps = [self._leaf_payload(leaves[i], i) for i in idxs]
            ms = [jax.tree.map(np.zeros_like, p) for p in ps]
            vs = [jax.tree.map(np.zeros_like, p) for p in ps]
            self.swapper.write_group(g, (ps, ms, vs))
        log_dist(f"ZeRO-Infinity: {len(leaves)} leaves in "
                 f"{len(self.groups)} NVMe swap groups under {self.dir}"
                 + (" (per-process shard fragments)" if self._multi
                    else ""))

    def _frag_shape(self, i: int, k: int) -> tuple:
        return fragment_shape(self._leaf_meta[i][0], self._frags[i][k])

    @staticmethod
    def _covering_slice(shard_idx, frag_idx, shape):
        """If ``shard_idx`` covers ``frag_idx``, return the relative
        slices of the fragment within the shard; else None.  Extents are
        normalized against ``shape`` so ``slice(None)`` and an explicit
        ``slice(0, dim)`` compare equal (a shard that is genuinely
        partial on a dim the fragment spans must NOT be declared
        covering — it would yield a wrong-shaped fragment)."""
        rel = []
        for ss, fs, dim in zip(shard_idx, frag_idx, shape):
            s0 = ss.start or 0
            f0 = fs.start or 0
            s1 = dim if ss.stop is None else min(ss.stop, dim)
            f1 = dim if fs.stop is None else min(fs.stop, dim)
            if f0 < s0 or f1 > s1:
                return None
            rel.append(slice(f0 - s0, f1 - s0))
        return tuple(rel)

    def _leaf_payload(self, leaf, i: int):
        """fp32 host payload of one leaf: the whole array (single-host)
        or the list of this process's fragments (multi-host).  A device
        leaf in a DIFFERENT layout than the fragment partition (e.g.
        replicated params at init) is served by slicing any addressable
        shard that covers the fragment."""
        if not self._multi:
            return np.asarray(leaf, np.float32)
        out = []
        for idx in self._frags[i]:
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                data = None
                for sh in leaf.addressable_shards:
                    if tuple(sh.index) == idx:
                        data = np.asarray(sh.data, np.float32)
                        break
                if data is None:
                    for sh in leaf.addressable_shards:
                        rel = self._covering_slice(tuple(sh.index), idx,
                                                   np.shape(leaf))
                        if rel is not None:
                            data = np.asarray(sh.data,
                                              np.float32)[rel]
                            break
                if data is None:
                    raise ValueError(
                        f"leaf {i}: no addressable shard matches or "
                        f"covers fragment {idx}")
                out.append(data)
            else:
                out.append(np.asarray(leaf, np.float32)[idx]
                           if idx else np.asarray(leaf, np.float32))
        return out

    def _template(self, g: int):
        if self._multi:
            mk = lambda: [
                [jax.ShapeDtypeStruct(self._frag_shape(i, k), np.float32)
                 for k in range(len(self._frags[i]))]
                for i in self.groups[g]]
        else:
            shapes = [self._leaf_meta[i] for i in self.groups[g]]
            mk = lambda: [jax.ShapeDtypeStruct(s, d) for s, d in shapes]
        return (mk(), mk(), mk())

    # ------------------------------------------------------------------
    def step(self, grad_leaves: Sequence[Any], lr: float,
             step_num: int,
             consume: Optional[Callable[[int, np.ndarray], None]] = None
             ) -> Optional[List[np.ndarray]]:
        """One optimizer step over all groups with double-buffered
        prefetch.  ``grad_leaves``: flat leaves (device arrays or lazy
        readers; fetched per group).  Returns flat fp32 master leaves —
        unless ``consume`` is given, in which case each fresh master leaf
        is handed to ``consume(leaf_index, p_new)`` and released (the
        param-streaming path: the full fp32 tree never materializes)."""
        assert self.swapper is not None, "initialize() first"
        new_leaves: List[Optional[np.ndarray]] = \
            None if consume else [None] * len(self._leaf_meta)
        G = len(self.groups)

        group_bytes = self._group_bytes.__getitem__

        if G:
            self.swapper.prefetch_group(0, self._template(0))
        for g, idxs in enumerate(self.groups):
            if g + 1 < G:       # overlap: next group's read behind update
                self.swapper.prefetch_group(g + 1, self._template(g + 1))
            if self.meter is not None:
                self.meter.alloc(group_bytes(g)
                                 + (group_bytes(g + 1) if g + 1 < G else 0))
            ps, ms, vs = self.swapper.read_group(g, self._template(g))
            for j, i in enumerate(idxs):
                if self._multi:
                    gmap = self._grad_frags(grad_leaves[i], i)
                    for k, idx in enumerate(self._frags[i]):
                        self.adam.update(ps[j][k], ms[j][k], vs[j][k],
                                         gmap[idx], lr, step_num)
                else:
                    gnp = np.asarray(grad_leaves[i], np.float32)
                    self.adam.update(ps[j], ms[j], vs[j], gnp, lr,
                                     step_num)
                if consume is not None:
                    consume(i, ps[j])
                else:
                    new_leaves[i] = ps[j]
            self.swapper.write_group(g, (ps, ms, vs))
            if self.meter is not None:
                self.meter.free(group_bytes(g)
                                + (group_bytes(g + 1) if g + 1 < G else 0))
        return new_leaves  # type: ignore[return-value]

    def _grad_frags(self, g, i: int) -> Dict[tuple, np.ndarray]:
        """This process's gradient fragments for leaf i, keyed by shard
        index.  A jax array must carry the layout the masters were
        partitioned by (the engine guarantees this; a mismatch is a hard
        error, not silent corruption).  Lazy readers (the param-stream
        grad store) provide fragments via a ``frag_map`` hook."""
        if hasattr(g, "frag_map"):
            return g.frag_map()
        if isinstance(g, jax.Array) and not g.is_fully_addressable:
            by_idx: Dict[tuple, Any] = {}
            for sh in g.addressable_shards:
                by_idx.setdefault(tuple(sh.index), sh.data)
            out = {}
            for idx in self._frags[i]:
                if idx not in by_idx:
                    raise ValueError(
                        f"leaf {i}: gradient sharding does not match the "
                        f"NVMe fragment layout (missing index {idx}); "
                        "the grad layout changed after initialize()")
                out[idx] = np.asarray(by_idx[idx], np.float32)
            return out
        arr = np.asarray(g, np.float32)
        return {idx: (arr[idx] if idx else arr) for idx in self._frags[i]}

    # ------------------------------------------------------------------
    # checkpoint support: materialize / restore the full fp32 state
    #
    # ------------------------------------------------------------------
    def state_trees(self, lazy: bool = False) -> Tuple[Any, Any, Any]:
        """(master, m, v) full trees in one pass over the swap groups.

        ``lazy=True`` returns trees of :class:`LazyNVMeLeaf` — each leaf
        reads its swap group from NVMe only when ``np.asarray`` touches
        it, with a one-group cache.  The checkpoint writer walks leaves
        sequentially, so peak host RAM is ONE swap group instead of the
        whole fp32 state (the >host-DRAM checkpoint path).

        Multi-host: no process can materialize a full leaf — returns
        trees of :class:`~deepspeed_tpu.checkpoint.engine.HostShards`
        snapshots carrying only this process's save-owned fragments
        (read from NVMe lazily), which is exactly what the fragment
        checkpoint writer consumes."""
        if self._multi:
            return self._state_trees_multi()
        if lazy:
            cache: Dict[Tuple[int, int], list] = {}

            def read(g: int, col: int, j: int) -> np.ndarray:
                # per-(group, COLUMN) reads: the checkpoint walk is
                # column-major (all master leaves, then m, then v), so a
                # whole-group read would fetch 3x the bytes per pass;
                # reading one column's keys keeps total IO at 1x state
                if (g, col) not in cache:
                    cache.clear()                # one column-group resident
                    cache[(g, col)] = self._read_column(g, col)
                return cache[(g, col)][j]

            cols = [[None] * len(self._leaf_meta) for _ in range(3)]
            for g, idxs in enumerate(self.groups):
                for col in range(3):
                    for j, i in enumerate(idxs):
                        shape, dtype = self._leaf_meta[i]
                        cols[col][i] = LazyNVMeLeaf(read, g, col, j,
                                                    shape, dtype)
            return tuple(jax.tree_util.tree_unflatten(self._treedef, col)
                         for col in cols)
        cols = [[None] * len(self._leaf_meta) for _ in range(3)]
        for g, idxs in enumerate(self.groups):
            parts = self.swapper.read_group(g, self._template(g))
            for col, vals in zip(cols, parts):
                for j, i in enumerate(idxs):
                    col[i] = vals[j]
        return tuple(jax.tree_util.tree_unflatten(self._treedef, col)
                     for col in cols)

    def _read_column(self, g: int, col: int) -> list:
        """Read one column (0=master, 1=m, 2=v) of swap group ``g``.

        The group template is the (ps, ms, vs) tuple, so its flat key
        order is column-contiguous — the column's keys are one slice."""
        tmpl = self._template(g)
        keys = self.swapper._keys(g, tmpl)
        n = len(keys) // 3
        sw = self.swapper._swapper(g)
        # batch the column's reads through the aio queue (a sync
        # swap_in per leaf would serialize NVMe latency per leaf)
        bufs = [sw.swap_in(k, async_op=True)
                for k in keys[col * n:(col + 1) * n]]
        sw.wait()
        return bufs

    def _frag_key(self, g: int, col: int, j: int, k: int) -> str:
        """Swap key of one fragment — the (ps, ms, vs) template's flat
        path ``[col][j][k]`` under group g (matches OptimizerSwapper's
        keystr-derived keys)."""
        return f"g{g}[{col}][{j}][{k}]"

    def _state_trees_multi(self) -> Tuple[Any, Any, Any]:
        from ..checkpoint.engine import HostShards
        cols = [[None] * len(self._leaf_meta) for _ in range(3)]
        for g, idxs in enumerate(self.groups):
            for col in range(3):
                for j, i in enumerate(idxs):
                    hs = HostShards.__new__(HostShards)
                    hs.shape = self._leaf_meta[i][0]
                    hs.dtype = np.dtype(np.float32)
                    hs.shards = self._owned_shard_iter(g, col, j, i)
                    cols[col][i] = hs
        return tuple(jax.tree_util.tree_unflatten(self._treedef, col)
                     for col in cols)

    def _owned_shard_iter(self, g: int, col: int, j: int, i: int):
        """Lazily yield (index, fragment) for the save-owned fragments of
        leaf i — each fragment is read from NVMe only when the writer
        reaches it (peak host RAM: one fragment)."""
        shape = self._leaf_meta[i][0]
        for k, idx in enumerate(self._frags[i]):
            if not self._save_owned[i][k]:
                continue
            sw = self.swapper._swapper(g)
            data = sw.swap_in(self._frag_key(g, col, j, k))
            full = tuple(slice(0, d) for d in shape)
            yield (idx if idx else full, data)

    def master_tree(self) -> Any:
        return self.state_trees()[0]

    def restore(self, master: Any, m: Any = None, v: Any = None) -> None:
        """Overwrite NVMe state from full trees (checkpoint load).
        Multi-host: each process slices out and stores only its own
        fragments of the (host-assembled) full leaves."""
        p_leaves = jax.tree_util.tree_leaves(master)
        m_leaves = jax.tree_util.tree_leaves(m) if m is not None else None
        v_leaves = jax.tree_util.tree_leaves(v) if v is not None else None
        for g, idxs in enumerate(self.groups):
            ps = [self._leaf_payload(p_leaves[i], i) for i in idxs]
            ms = ([self._leaf_payload(m_leaves[i], i) for i in idxs]
                  if m_leaves else
                  [jax.tree.map(np.zeros_like, p) for p in ps])
            vs = ([self._leaf_payload(v_leaves[i], i) for i in idxs]
                  if v_leaves else
                  [jax.tree.map(np.zeros_like, p) for p in ps])
            self.swapper.write_group(g, (ps, ms, vs))
