"""Data efficiency: curriculum learning, difficulty sampling, random-LTD.

TPU-native equivalents of the reference data-efficiency suite
(``runtime/data_pipeline/`` — ``curriculum_scheduler.py``
CurriculumScheduler with fixed_linear/fixed_root/fixed_discrete/custom
schedules; ``data_sampling/data_sampler.py`` DeepSpeedDataSampler
difficulty-indexed batches; ``data_routing/basic_layer.py:113`` RandomLTD
layerwise token dropping + its scheduler; ``csrc/random_ltd/`` gather/
scatter kernels — jnp.take_along_axis subsumes them, SURVEY §2.2).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import logger


# --------------------------------------------------------------------------
# Curriculum scheduler (reference: curriculum_scheduler.py)
# --------------------------------------------------------------------------

class CurriculumScheduler:
    """difficulty(step): min_difficulty -> max_difficulty.

    schedule_type: fixed_linear | fixed_root | fixed_discrete | custom
    (reference: CurriculumScheduler.__init__ legal types).
    """

    def __init__(self, config: Dict):
        self.min = int(config["min_difficulty"])
        self.max = int(config["max_difficulty"])
        self.type = config["schedule_type"]
        cfg = config.get("schedule_config", {})
        self.cfg = cfg
        self.custom_fn: Optional[Callable[[int], int]] = config.get(
            "custom_fn")
        if self.type in ("fixed_linear", "fixed_root"):
            self.total_step = int(cfg["total_curriculum_step"])
            self.diff_step = int(cfg.get("difficulty_step", 1))
            self.root = float(cfg.get("root_degree",
                                      1 if self.type == "fixed_linear"
                                      else 2))
        elif self.type == "fixed_discrete":
            self.difficulties: List[int] = list(cfg["difficulty"])
            self.max_steps: List[int] = list(cfg["max_step"])
            assert len(self.difficulties) == len(self.max_steps) + 1
        elif self.type == "custom":
            assert self.custom_fn is not None, "custom schedule needs fn"
        else:
            raise ValueError(f"unknown schedule_type {self.type!r}")

    def get_difficulty(self, step: int) -> int:
        if self.type == "custom":
            return int(self.custom_fn(step))
        if self.type == "fixed_discrete":
            for d, s in zip(self.difficulties, self.max_steps):
                if step <= s:
                    return d
            return self.difficulties[-1]
        frac = min(1.0, max(step, 1) / self.total_step) ** (1.0 / self.root)
        diff = self.min + (self.max - self.min) * frac
        diff = int(diff // self.diff_step) * self.diff_step
        return int(min(self.max, max(self.min, diff)))

    # reference parity
    update_difficulty = get_difficulty


def truncate_to_difficulty(batch: Dict[str, Any], difficulty: int,
                           seq_keys: Sequence[str] = ("input_ids", "labels",
                                                      "attention_mask"),
                           pad_to: Optional[int] = None) -> Dict[str, Any]:
    """Seqlen-based curriculum: truncate sequence keys to the current
    difficulty (reference: seqlen metric path in data_sampler;
    pad_to keeps shapes static across steps when given)."""
    out = dict(batch)
    for k in seq_keys:
        if k in out and np.ndim(out[k]) >= 2:
            v = out[k][:, :difficulty]
            if pad_to and pad_to > difficulty:
                pad = [(0, 0), (0, pad_to - difficulty)] + \
                    [(0, 0)] * (np.ndim(v) - 2)
                v = np.pad(np.asarray(v), pad)
            out[k] = v
    return out


# --------------------------------------------------------------------------
# Difficulty-indexed sampler (reference: data_sampler.py DeepSpeedDataSampler)
# --------------------------------------------------------------------------

class CurriculumDataSampler:
    """Yields sample indices whose difficulty metric is within the
    scheduler's current bound (reference: DeepSpeedDataSampler — the
    cluster-index machinery reduces to a sorted-metric cursor)."""

    def __init__(self, metric_values: Sequence[float],
                 scheduler: CurriculumScheduler,
                 batch_size: int, seed: int = 0):
        self.metric = np.asarray(metric_values)
        self.order = np.argsort(self.metric, kind="stable")
        self.sched = scheduler
        self.batch_size = batch_size
        self.seed = seed

    @classmethod
    def from_analyzer(cls, save_path: str, metric: str,
                      scheduler: CurriculumScheduler, batch_size: int,
                      seed: int = 0) -> "CurriculumDataSampler":
        """Build from an offline :class:`~deepspeed_tpu.runtime.
        data_analyzer.DataAnalyzer` index dir (reference: the
        DeepSpeedDataSampler consuming index_to_sample_path files)."""
        from .data_analyzer import load_metric
        idx = load_metric(save_path, metric)
        return cls(np.asarray(idx["sample_to_metric"]), scheduler,
                   batch_size, seed=seed)

    def batch_indices(self, step: int) -> np.ndarray:
        """Stateless in ``step``: the same (seed, step) always yields the
        same batch, so epoch replay / checkpoint resume reproduce the
        original data order (like the loader's epoch-seeded shuffle)."""
        difficulty = self.sched.get_difficulty(step)
        eligible_n = int(np.searchsorted(
            self.metric[self.order], difficulty, side="right"))
        eligible = self.order[:max(eligible_n, self.batch_size)]
        rng = np.random.RandomState(self.seed + step)
        return rng.choice(eligible, size=self.batch_size,
                          replace=len(eligible) < self.batch_size)


class DataAnalyzer:
    """Single-metric, in-memory convenience wrapper.  The full offline
    map-reduce analyzer (multi-metric, worker-sharded, mmap-corpus,
    sorted metric_to_sample indexes — reference:
    data_sampling/data_analyzer.py, 880 LoC) is
    :class:`deepspeed_tpu.runtime.data_analyzer.DataAnalyzer`; pair it
    with :mod:`deepspeed_tpu.runtime.indexed_dataset` for large corpora."""

    def __init__(self, metric_fn: Callable[[Any], float]):
        self.metric_fn = metric_fn

    def run(self, samples: Sequence[Any],
            save_path: Optional[str] = None) -> np.ndarray:
        vals = np.asarray([self.metric_fn(s) for s in samples], np.float32)
        if save_path:
            np.save(save_path, vals)
        return vals


# --------------------------------------------------------------------------
# Random-LTD (reference: data_routing/basic_layer.py + csrc/random_ltd)
# --------------------------------------------------------------------------

class RandomLTDScheduler:
    """Kept-token count schedule (reference: data_routing/scheduler.py —
    linear increase from min to full seqlen)."""

    def __init__(self, total_layers: int, start_tokens: int,
                 max_tokens: int, schedule_steps: int,
                 step_size: int = 16):
        self.total_layers = total_layers
        self.start = start_tokens
        self.max = max_tokens
        self.steps = schedule_steps
        self.step_size = step_size

    def kept_tokens(self, step: int) -> int:
        frac = min(1.0, step / max(1, self.steps))
        k = self.start + (self.max - self.start) * frac
        k = int(k // self.step_size) * self.step_size
        return int(min(self.max, max(self.start, k)))


def random_ltd_select(x: jax.Array, keep: int, rng: jax.Array
                      ) -> Tuple[jax.Array, jax.Array]:
    """Sample ``keep`` token positions per batch row (sorted, so causal
    order survives) and gather them (reference: token_sort_ +
    gather_tokens in csrc/random_ltd/pt_binding.cpp)."""
    B, S = x.shape[0], x.shape[1]
    noise = jax.random.uniform(rng, (B, S))
    idx = jnp.argsort(noise, axis=1)[:, :keep]
    idx = jnp.sort(idx, axis=1)
    gathered = jnp.take_along_axis(
        x, idx.reshape(B, keep, *(1,) * (x.ndim - 2)), axis=1)
    return gathered, idx


def random_ltd_scatter(full: jax.Array, processed: jax.Array,
                       idx: jax.Array) -> jax.Array:
    """Scatter processed tokens back into the full sequence; dropped
    positions keep their input value (reference: ScatterTokens — the
    residual bypass for dropped tokens)."""
    B, keep = idx.shape
    return full.at[jnp.arange(B)[:, None], idx].set(processed)
