"""Progressive layer drop (reference: ``runtime/progressive_layer_drop.py``
— PLD theta schedule theta(t) = (1-theta)·exp(-gamma·t)+theta; consumed
by the transformer's per-layer keep probability)."""

from __future__ import annotations

import math


class ProgressiveLayerDrop:
    """(reference: ProgressiveLayerDrop.__init__/update_state)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = ((1.0 - self.theta) *
                              math.exp(-self.gamma * global_step) +
                              self.theta)
        return self.current_theta

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def layer_keep_prob(self, layer_idx: int, num_layers: int) -> float:
        """Per-layer keep probability: deeper layers drop more
        (reference PLD paper schedule: 1 - (i/L)(1-theta))."""
        return 1.0 - (layer_idx / max(1, num_layers)) * \
            (1.0 - self.current_theta)
