"""Learning-rate schedules as pure ``step -> lr`` functions.

TPU-native equivalents of the reference's schedule classes
(``runtime/lr_schedules.py`` — LRRangeTest :267, OneCycle :370, WarmupLR
:634, WarmupDecayLR :723, WarmupCosineLR :774).  The reference mutates
optimizer param groups imperatively; here each schedule is a jit-safe pure
function of the (float32 traced) step counter, composed directly into the
optimizer update, so the schedule runs on-device with zero host sync.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def constant(lr: float) -> Schedule:
    def f(step):
        return jnp.asarray(lr, jnp.float32)
    return f


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Schedule:
    """Linearly/staircase-increasing LR probe (reference :267)."""
    def f(step):
        x = step / lr_range_test_step_size
        if lr_range_test_staircase:
            x = jnp.floor(x)
        return lr_range_test_min_lr * (1.0 + x * lr_range_test_step_rate)
    return f


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: int | None = None,
              decay_step_size: int = 0,
              decay_lr_rate: float = 0.0) -> Schedule:
    """Triangular one-cycle policy with optional post-cycle decay
    (reference :370)."""
    up = float(cycle_first_step_size)
    down = float(cycle_second_step_size if cycle_second_step_size else up)
    total = up + down

    def f(step):
        in_up = jnp.clip(step / up, 0.0, 1.0)
        in_down = jnp.clip((step - up) / down, 0.0, 1.0)
        tri = jnp.where(step <= up,
                        cycle_min_lr + (cycle_max_lr - cycle_min_lr) * in_up,
                        cycle_max_lr - (cycle_max_lr - cycle_min_lr) * in_down)
        if decay_step_size > 0:
            post = jnp.maximum(step - total, 0.0) / decay_step_size
            tri = jnp.where(step > total,
                            cycle_min_lr / (1.0 + post * decay_lr_rate), tri)
        return jnp.maximum(tri, 0.0)
    return f


def _warmup_factor(step, warmup_num_steps: int, warmup_type: str):
    w = jnp.maximum(float(warmup_num_steps), 1.0)
    frac = jnp.clip(step / w, 0.0, 1.0)
    if warmup_type == "log":
        # reference WarmupLR: log-spaced warmup (lr_schedules.py:671)
        return jnp.where(step >= w, 1.0,
                         jnp.log1p(step) / jnp.log1p(w))
    return frac


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000,
              warmup_type: str = "log") -> Schedule:
    """(reference :634)."""
    def f(step):
        fac = _warmup_factor(step, warmup_num_steps, warmup_type)
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * fac
    return f


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log") -> Schedule:
    """Warmup then linear decay to 0 (reference :723)."""
    def f(step):
        fac = _warmup_factor(step, warmup_num_steps, warmup_type)
        lr = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * fac
        decay = jnp.clip(
            (total_num_steps - step) /
            jnp.maximum(float(total_num_steps - warmup_num_steps), 1.0),
            0.0, 1.0)
        return jnp.where(step <= warmup_num_steps, lr, warmup_max_lr * decay)
    return f


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 1e-4,
                     warmup_type: str = "linear", lr: float = 1.0) -> Schedule:
    """Warmup (as ratio of peak) then cosine decay (reference :774)."""
    def f(step):
        fac = _warmup_factor(step, warmup_num_steps, warmup_type)
        warm = warmup_min_ratio + (1.0 - warmup_min_ratio) * fac
        progress = jnp.clip(
            (step - warmup_num_steps) /
            jnp.maximum(float(total_num_steps - warmup_num_steps), 1.0),
            0.0, 1.0)
        cos = cos_min_ratio + (1.0 - cos_min_ratio) * 0.5 * (
            1.0 + jnp.cos(math.pi * progress))
        return lr * jnp.where(step < warmup_num_steps, warm, cos)
    return f


SCHEDULES: Dict[str, Callable[..., Schedule]] = {
    "LRRangeTest": lr_range_test,
    "OneCycle": one_cycle,
    "WarmupLR": warmup_lr,
    "WarmupDecayLR": warmup_decay_lr,
    "WarmupCosineLR": warmup_cosine_lr,
    "Constant": constant,
}


def build_schedule(name: str, params: Dict[str, Any] | None = None) -> Schedule:
    if name not in SCHEDULES:
        raise ValueError(f"Unknown scheduler {name!r}; known: {sorted(SCHEDULES)}")
    return SCHEDULES[name](**(params or {}))
