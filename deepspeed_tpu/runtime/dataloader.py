"""Data loading: deterministic sharded batches for the engine.

TPU-native analog of ``DeepSpeedDataLoader`` (``runtime/dataloader.py`` —
DistributedSampler + curriculum hook via ``deepspeed_io`` engine.py:1743).

On TPU each *process* loads its slice of the global batch
(``jax.process_index()``-strided, like the reference's DistributedSampler
rank striding); the engine's ``shard_batch`` then lays it onto the mesh.
A ``batch_fn`` hook covers curriculum-style transforms
(reference: data_pipeline/curriculum_scheduler.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import jax
import numpy as np


class DataLoader:
    """Iterate epoch-shuffled, process-sharded global batches from a dict
    of arrays (or anything indexable)."""

    def __init__(self, data: Dict[str, Any], batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = True,
                 batch_fn: Optional[Callable[[Dict, int], Dict]] = None,
                 sampler: Optional[Any] = None):
        self.data = {k: np.asarray(v) for k, v in data.items()}
        sizes = {k: len(v) for k, v in self.data.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"Mismatched field lengths: {sizes}")
        self.n = next(iter(sizes.values()))
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.batch_fn = batch_fn
        # difficulty-indexed sampling (reference: DeepSpeedDataSampler via
        # deepspeed_io): any object with batch_indices(step) -> global ids
        # overrides the epoch shuffle, e.g. data_pipeline.
        # CurriculumDataSampler / engine.curriculum_sampler
        self.sampler = sampler
        self.epoch = 0
        tail = self.n % batch_size
        if not drop_last and tail and tail % jax.process_count():
            # a tail that stripes unevenly across processes would hand
            # shard_batch inconsistent local shapes — fail loudly here
            raise ValueError(
                f"drop_last=False: final batch of {tail} is not divisible "
                f"by process_count {jax.process_count()}")

    def __len__(self) -> int:
        if self.drop_last:
            return self.n // self.batch_size
        return -(-self.n // self.batch_size)

    def set_epoch(self, epoch: int) -> None:
        """(reference: DistributedSampler.set_epoch)."""
        self.epoch = epoch

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        order = None
        if self.sampler is None:
            order = np.arange(self.n)
            if self.shuffle:
                np.random.RandomState(self.seed + self.epoch).shuffle(order)
        # process-sharded: each host reads its interleaved slice of every
        # global batch (rank striding like the reference sampler)
        pc, pi = jax.process_count(), jax.process_index()
        if self.batch_size % pc:
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by "
                f"process_count {pc}")
        for step in range(len(self)):
            # torch convention: drop_last=False yields the short final
            # batch.  SPMD training wants drop_last=True (the default) —
            # shard_batch requires batch % mesh data axes == 0.
            if self.sampler is not None:
                sel = np.asarray(self.sampler.batch_indices(
                    step + self.epoch * len(self)))
            else:
                sel = order[step * self.batch_size:
                            (step + 1) * self.batch_size]
            if pc > 1:
                sel = sel[pi::pc]
            batch = {k: v[sel] for k, v in self.data.items()}
            if self.batch_fn is not None:
                batch = self.batch_fn(batch, step)
            yield batch


class PrefetchingLoader:
    """Wrap any batch iterator with device prefetch: batch N+1 uploads
    (``engine.shard_batch``) while step N computes, hiding host->device
    latency — the pinned-buffer async copy of the reference's loaders,
    with XLA's async transfer doing the pipelining.

    Usage::

        for dev_batch in PrefetchingLoader(loader, engine):
            engine.train_batch(dev_batch)
    """

    def __init__(self, loader, engine, depth: int = 2):
        self.loader = loader
        self.engine = engine
        self.depth = max(1, depth)

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        import collections
        q = collections.deque()
        it = iter(self.loader)
        try:
            for _ in range(self.depth):
                q.append(self.engine.shard_batch(next(it)))
        except StopIteration:
            pass
        while q:
            out = q.popleft()
            try:
                q.append(self.engine.shard_batch(next(it)))
            except StopIteration:
                pass
            yield out


def synthetic_lm_data(vocab_size: int, n_samples: int, seq_len: int,
                      seed: int = 0) -> Dict[str, np.ndarray]:
    """Random-token corpus for tests/benches (reference: the random-data
    loaders in tests/unit/simple_model.py)."""
    r = np.random.RandomState(seed)
    return {"input_ids": r.randint(0, vocab_size, (n_samples, seq_len))}
