"""Hessian eigenvalue estimation by power iteration.

TPU-native equivalent of ``runtime/eigenvalue.py`` (power iteration over
autograd Hessian-vector products, used to schedule MoQ quantization
periods).  jax gives exact HVPs via forward-over-reverse
(``jvp(grad(f))``) — no double-backward graph bookkeeping.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from .runtime_utils import global_norm


class Eigenvalue:
    """(reference: Eigenvalue.__init__ — verbose, max_iter, tol,
    stability, gas_boundary_resolution)."""

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution

    def compute_eigenvalue(self, loss_fn: Callable[[Any], jax.Array],
                           params: Any,
                           rng: jax.Array) -> Tuple[float, Any]:
        """Dominant Hessian eigenvalue of ``loss_fn`` at ``params``.

        Returns (eigenvalue, eigenvector-pytree).
        """
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        # random unit start vector
        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree.unflatten(treedef, [
            jax.random.normal(k, jnp.shape(l)) for k, l in zip(keys, leaves)])
        eig_prev = 0.0
        for i in range(self.max_iter):
            n = global_norm(v) + self.stability
            v = jax.tree.map(lambda x: x / n, v)
            hv = hvp(v)
            eig = float(sum(jnp.vdot(a, b) for a, b in zip(
                jax.tree.leaves(v), jax.tree.leaves(hv))))
            if i > 0 and abs(eig) > 0 and \
                    abs(eig - eig_prev) / abs(eig) < self.tol:
                break
            eig_prev = eig
            v = hv
        return eig, v
