"""Hybrid engine: one set of weights serving both RLHF training and fast
generation.

TPU-native re-design of the reference DeepSpeedHybridEngine
(``runtime/hybrid_engine.py:32`` — ``generate()`` :363 flips the actor
into inference containers sharing (gathered) ZeRO-3 weights, LoRA
fuse/unfuse :141-158, then flips back for the PPO update).

The XLA redesign is simpler because weights are immutable pytrees:

* the training half is the ordinary :class:`~.engine.Engine` (ZeRO
  sharded fp32 masters, single donated train step);
* the generation half is the FastGen :class:`~..inference.InferenceEngine`
  (paged KV, SplitFuse continuous batching, Pallas decode kernel);
* ``generate()`` refreshes the serving weights from the training masters
  when they are stale — one jitted gather+cast (``Engine.compute_params``
  — under ZeRO-3 this is the same all-gather a training step performs)
  followed by an optional LoRA **fuse** (``linear.merge_lora``).  Nothing
  is mutated, so the reference's unfuse/"release & re-partition" dance
  (:141,:158) has no analog: the training masters were never touched.
* stale KV from a previous policy is never reused: a refresh flushes all
  live sequences (each RLHF rollout starts against the new policy).

Usage (the DeepSpeed-Chat actor loop)::

    he = HybridEngine(model, config, inference_config=InferenceConfig(...))
    rollouts = he.generate({0: prompt_tokens}, SamplingParams(...))
    metrics = he.train_batch(ppo_batch)       # ZeRO train step
    rollouts = he.generate(...)               # sees the updated policy
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..utils.logging import logger


def fuse_lora_tree(params: Any, lora_config) -> Any:
    """Merge every ``{weight, lora_a, lora_b}`` node into a plain fused
    weight (reference: fuse_lora hybrid_engine.py:141 — here producing a
    new tree; the trainable factors are untouched)."""
    from ..linear.optimized_linear import merge_lora

    def fuse(node):
        if isinstance(node, dict) and "lora_a" in node:
            merged = dict(node)
            merged["base"] = merge_lora(node, lora_config)  # dense fused
            merged.pop("lora_a"), merged.pop("lora_b")
            return merged
        return node

    return jax.tree.map(
        fuse, params,
        is_leaf=lambda n: isinstance(n, dict) and "lora_a" in n)


class HybridEngine:
    def __init__(self, model, config, inference_config=None,
                 lora_config=None, **engine_kw):
        from .. import initialize
        from ..inference import InferenceConfig, InferenceEngine

        self.model = model
        self.engine = initialize(model=model, config=config, **engine_kw)
        self.lora_config = lora_config
        self._icfg = inference_config or InferenceConfig()
        self._infer: Optional[InferenceEngine] = None
        self._params_step = -1          # train step the serving params match

    # ------------------------------------------------------------ training
    def train_batch(self, batch):
        """One PPO/actor optimizer step (plain engine delegation)."""
        return self.engine.train_batch(batch)

    def eval_batch(self, batch):
        return self.engine.eval_batch(batch)

    def save_checkpoint(self, *a, **kw):
        return self.engine.save_checkpoint(*a, **kw)

    def load_checkpoint(self, *a, **kw):
        out = self.engine.load_checkpoint(*a, **kw)
        self._params_step = -1          # serving weights are now stale
        return out

    # ---------------------------------------------------------- generation
    def _serving_params(self):
        """Training masters -> serving weights: jitted gather+cast, then
        LoRA fuse (reference: fuse_lora hybrid_engine.py:141)."""
        params = self.engine.compute_params
        if self.lora_config is not None:
            params = fuse_lora_tree(params, self.lora_config)
        return params

    def _refresh(self):
        step = int(np.asarray(self.engine.state.step))
        if self._infer is not None and step == self._params_step:
            return
        from ..inference import InferenceEngine

        params = self._serving_params()
        if self._infer is None:
            self._infer = InferenceEngine(self.model, self._icfg)
        else:
            # a new policy invalidates every live sequence's KV
            for uid in list(self._infer.state.seqs):
                self._infer.flush(uid)
        # refresh_params re-casts AND re-quantizes under weight_quant —
        # a bare params assignment would keep serving the old quantized
        # weights captured in the step closure
        self._infer.refresh_params(params)
        self._params_step = step
        logger.info(f"hybrid-engine: serving weights refreshed @ step {step}")

    def generate(self, prompts: Dict[int, Sequence[int]], sampling=None,
                 rng: Optional[jax.Array] = None) -> Dict[int, List[int]]:
        """FastGen generation against the CURRENT policy weights
        (reference: HybridEngine.generate :363)."""
        from ..inference.sampler import SamplingParams

        self._refresh()
        return self._infer.generate(prompts, sampling or SamplingParams(),
                                    rng=rng)

    @property
    def inference_engine(self):
        """The live serving engine (refreshed; for put/step-level use)."""
        self._refresh()
        return self._infer
