"""Runtime numeric utilities.

TPU-native analogs of ``deepspeed/runtime/utils.py`` (global grad norm w/
MoE+TP awareness :315/:826, ``clip_grad_norm_`` :1028, ``partition_balanced``
:583, ``see_memory_usage`` :771).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp


def global_norm(tree: Any) -> jnp.ndarray:
    """L2 norm over a whole pytree, computed in fp32 (one fused reduction).

    Under jit with sharded leaves, XLA inserts the partial-norm psum
    automatically — the SPMD analog of the reference's
    ``get_global_norm_of_tensors`` (runtime/utils.py:826) which manually
    all-reduces across model-parallel groups.
    """
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    return jnp.sqrt(sq)


def clip_by_global_norm(tree: Any, max_norm: float,
                        norm: jnp.ndarray | None = None) -> Tuple[Any, jnp.ndarray]:
    """(reference: clip_grad_norm_ runtime/utils.py:1028)."""
    if norm is None:
        norm = global_norm(tree)
    if not max_norm or max_norm <= 0:
        return tree, norm
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda x: x * factor, tree), norm


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Split `weights` into `num_parts` contiguous chunks minimizing the max
    chunk weight (reference: partition_balanced runtime/utils.py:583 — used
    by the pipeline module partitioner).  Returns part boundaries of length
    num_parts+1.  O(n * P * log(sum)) binary search + greedy check."""
    n = len(weights)
    if num_parts <= 0:
        raise ValueError("num_parts must be positive")
    if num_parts >= n:
        bounds = list(range(n + 1))
        bounds += [n] * (num_parts - n)
        return bounds
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + float(w))

    def parts_needed(cap: float) -> int:
        parts, cur = 1, 0.0
        for w in weights:
            w = float(w)
            if w > cap:
                return num_parts + 1
            if cur + w > cap:
                parts += 1
                cur = w
            else:
                cur += w
        return parts

    lo, hi = max(map(float, weights)), prefix[-1]
    for _ in range(64):
        mid = (lo + hi) / 2
        if parts_needed(mid) <= num_parts:
            hi = mid
        else:
            lo = mid
    cap = hi
    bounds = [0]
    cur = 0.0
    for i, w in enumerate(weights):
        w = float(w)
        if cur + w > cap and len(bounds) < num_parts:
            bounds.append(i)
            cur = w
        else:
            cur += w
    bounds += [n] * (num_parts + 1 - len(bounds))
    return bounds


def see_memory_usage(message: str = "", force: bool = False) -> dict:
    """Device memory stats (reference: see_memory_usage runtime/utils.py:771)."""
    from ..utils.logging import logger
    stats = {}
    for d in jax.local_devices():
        try:
            s = d.memory_stats()
        except Exception as e:
            logger.debug("memory_stats unavailable on %s: %r", d, e)
            s = None
        if s:
            stats[str(d.id)] = {
                "bytes_in_use": s.get("bytes_in_use", 0),
                "peak_bytes_in_use": s.get("peak_bytes_in_use", 0),
                "bytes_limit": s.get("bytes_limit", 0),
            }
    if force and stats:
        total = sum(v["bytes_in_use"] for v in stats.values())
        peak = sum(v["peak_bytes_in_use"] for v in stats.values())
        logger.info("%s | mem in_use=%.2fGB peak=%.2fGB", message,
                    total / 2**30, peak / 2**30)
    return stats


def param_count(tree: Any) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(tree))
