"""Sparse gradient reduction for embedding-heavy models.

TPU-native analog of the reference's sparse-gradient path
(``runtime/sparse_tensor.py`` SparseTensor, ``engine.py:2518-2587``
``sparse_allreduce_bucket`` — embedding grads travel as (indices, values)
instead of the dense [vocab, d] table).

XLA needs static shapes, so sparsity is expressed as a fixed row
``capacity`` per shard: each data-parallel shard picks its ``capacity``
highest-mass rows (all nonzero rows fit whenever capacity >= tokens in
the shard's batch — the embedding gradient touches at most one row per
token, so the default is lossless), all-gathers only (ids, rows), and
scatter-adds the gathered contributions into the dense result.

Wire volume: ``DP * capacity * (d + 1)`` vs the dense ring-allreduce's
``~2 * vocab * d`` — e.g. GPT-2's [50257, 768] table with an 8k-token
shard batch moves ~8x less.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def sparse_psum(g: jax.Array, axis_name, capacity: int) -> jax.Array:
    """Sum a row-sparse gradient over ``axis_name`` shards.

    g: [V, d] (or [V]) per-shard dense gradient whose nonzero rows are
    few; returns the dense sum, numerically identical to ``psum`` as
    long as every shard has <= capacity nonzero rows (rows beyond the
    capacity — lowest row mass first — are dropped, so size capacity to
    the shard's token count)."""
    V = g.shape[0]
    capacity = min(int(capacity), V)
    flat = g.reshape(V, -1)
    mass = jnp.abs(flat).sum(axis=1)                      # [V]
    _, ids = lax.top_k(mass, capacity)                    # [cap]
    rows = flat[ids]                                      # [cap, d]
    # zero-mass picks contribute zeros — harmless in the scatter-add
    all_ids = lax.all_gather(ids, axis_name, tiled=True)  # [DP*cap]
    all_rows = lax.all_gather(rows, axis_name, axis=0,
                              tiled=True)                 # [DP*cap, d]
    dense = jnp.zeros_like(flat).at[all_ids].add(all_rows)
    return dense.reshape(g.shape)


def is_sparse_leaf(axes) -> bool:
    """Only 2-D vocab-leading leaves — embedding TABLES — qualify: the
    lookup gradient touches one row per token.  1-D vocab leaves (an
    lm_head bias) and vocab-trailing projections receive DENSE gradients
    (every vocab entry gets softmax mass) and must reduce densely."""
    return (isinstance(axes, tuple) and len(axes) >= 2
            and axes[0] == "vocab")


def default_capacity(batch_tokens: int, vocab: int) -> int:
    """Lossless default: one gradient row per token in the shard batch."""
    return min(vocab, max(1, batch_tokens))
