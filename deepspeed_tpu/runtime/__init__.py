from .engine import Engine, TrainState, initialize
from .optimizers import (Optimizer, build_optimizer, adam, adamw, lion, lamb,
                         adagrad, sgd, OPTIMIZERS)
from .lr_schedules import build_schedule, SCHEDULES
from .loss_scaler import LossScaler, LossScaleState, all_finite
from .runtime_utils import (global_norm, clip_by_global_norm,
                            partition_balanced, see_memory_usage, param_count)
from .dataloader import DataLoader, PrefetchingLoader, synthetic_lm_data
from .data_analyzer import (DataAnalyzer as OfflineDataAnalyzer,
                            difficulty_buckets, samples_up_to_difficulty)
from .hybrid_engine import HybridEngine
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder

__all__ = [
    "Engine", "TrainState", "initialize",
    "Optimizer", "build_optimizer", "adam", "adamw", "lion", "lamb",
    "adagrad", "sgd", "OPTIMIZERS",
    "build_schedule", "SCHEDULES",
    "LossScaler", "LossScaleState", "all_finite",
    "global_norm", "clip_by_global_norm", "partition_balanced",
    "see_memory_usage", "param_count",
    "DataLoader", "PrefetchingLoader", "synthetic_lm_data",
    "OfflineDataAnalyzer", "difficulty_buckets",
    "samples_up_to_difficulty",
    "HybridEngine",
    "MMapIndexedDataset", "MMapIndexedDatasetBuilder",
]
