"""NVMe tensor swapping (ZeRO-Infinity storage layer).

TPU-native re-design of the reference swap machinery
(``runtime/swap_tensor/`` — ``AsyncPartitionedParameterSwapper``
partitioned_param_swapper.py:37, ``OptimizerSwapper`` +
``pipelined_optimizer_swapper.py`` double-buffered async variant,
``async_swapper.py``): pytree leaves are spilled to aligned files on
NVMe through the native aio pool and prefetched back with double
buffering, so the read of step N+1's shard overlaps the optimizer math
of step N.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..ops.aio import AsyncIOHandle
from ..utils.logging import logger


class TensorSwapper:
    """Spill/restore named numpy buffers to NVMe-backed files."""

    def __init__(self, swap_dir: str, aio: Optional[AsyncIOHandle] = None):
        self.dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.aio = aio or AsyncIOHandle()
        self._meta: Dict[str, Tuple[tuple, np.dtype]] = {}

    def _path(self, key: str) -> str:
        safe = key.replace("/", "_").replace("'", "").replace("[", "_") \
            .replace("]", "_")
        return os.path.join(self.dir, f"{safe}.swp")

    # ---- write-out -------------------------------------------------------
    def swap_out(self, key: str, array, async_op: bool = False) -> None:
        buf = np.ascontiguousarray(np.asarray(array))
        self._meta[key] = (buf.shape, buf.dtype)
        self._hold = getattr(self, "_hold", {})
        self._hold[key] = buf                     # keep alive until wait()
        # full-file rewrite: truncate so a shrunk leaf leaves no stale tail
        self.aio.async_pwrite(buf, self._path(key), truncate=True)
        if not async_op:
            self.wait()

    # ---- read-in ---------------------------------------------------------
    def swap_in(self, key: str, async_op: bool = False,
                out: Optional[np.ndarray] = None) -> np.ndarray:
        shape, dtype = self._meta[key]
        buf = out if out is not None else np.empty(shape, dtype)
        self.aio.async_pread(buf, self._path(key))
        if not async_op:
            self.wait()
        return buf

    def wait(self) -> None:
        errs = self.aio.wait()
        self._hold = {}
        if errs:
            raise IOError(f"{errs} swap chunks failed in {self.dir}")

    def remove(self, key: str) -> None:
        self._meta.pop(key, None)
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


class OptimizerSwapper:
    """Double-buffered optimizer-state swapping over sub-groups.

    The reference pipelines (gather fp32 from NVMe → step → scatter back)
    per sub-group (stage3.py:2049 + pipelined_optimizer_swapper.py); the
    same schedule here: ``prefetch(g+1)`` is issued before ``step(g)``
    consumes group g, so NVMe latency hides behind compute.
    """

    def __init__(self, swap_dir: str, num_groups: int,
                 aio: Optional[AsyncIOHandle] = None,
                 aio_config=None):
        # Two swappers (own aio pools) alternate over even/odd groups, so
        # waiting on group g's reads never drains the in-flight prefetch
        # of group g+1 — true double buffering.
        if aio is None and aio_config is not None:
            # engine-config-driven pools (reference: aio block read at
            # partitioned_param_swapper.py:83)
            self._swappers = (
                TensorSwapper(swap_dir,
                              AsyncIOHandle.from_config(aio_config)),
                TensorSwapper(swap_dir,
                              AsyncIOHandle.from_config(aio_config)))
        else:
            self._swappers = (TensorSwapper(swap_dir, aio),
                              TensorSwapper(swap_dir))
        self.num_groups = num_groups
        self._buffers: Dict[int, Any] = {}

    def _swapper(self, group: int) -> TensorSwapper:
        return self._swappers[group % 2]

    def _keys(self, group: int, tree) -> List[str]:
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        return [f"g{group}{jax.tree_util.keystr(p)}" for p, _ in flat]

    def write_group(self, group: int, tree: Any) -> None:
        sw = self._swapper(group)
        flat, treedef = jax.tree_util.tree_flatten(tree)
        self._treedef = treedef
        for key, leaf in zip(self._keys(group, tree), flat):
            sw.swap_out(key, leaf, async_op=True)
        sw.wait()

    def prefetch_group(self, group: int, template: Any) -> None:
        """Start async reads for a group (double buffering)."""
        sw = self._swapper(group)
        flat, treedef = jax.tree_util.tree_flatten(template)
        bufs = [sw.swap_in(k, async_op=True)
                for k in self._keys(group, template)]
        self._buffers[group] = (bufs, treedef)

    def read_group(self, group: int, template: Any = None) -> Any:
        sw = self._swapper(group)
        if group in self._buffers:
            sw.wait()
            bufs, treedef = self._buffers.pop(group)
            return jax.tree_util.tree_unflatten(treedef, bufs)
        assert template is not None, "no prefetch and no template"
        flat, treedef = jax.tree_util.tree_flatten(template)
        bufs = [sw.swap_in(k)
                for k in self._keys(group, template)]
        return jax.tree_util.tree_unflatten(treedef, bufs)
