"""Dynamic loss scaling as pure functional state.

TPU-native equivalent of the reference's ``LossScaler``/``DynamicLossScaler``
(``runtime/fp16/loss_scaler.py``) and the overflow machinery
(``CheckOverflow`` runtime/utils.py:181, ``has_overflow`` stage3.py:2171).

The reference checks overflow by syncing grads to host and allreducing a
flag; in jax there is no global state, so the scaler lives *inside* the
jitted train step: scale the loss, compute grads, check all-finite with a
single fused reduction, and either apply the update or skip it with
``jnp.where`` — no host round-trip, no recompilation on overflow.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar, current loss scale
    good_steps: jnp.ndarray     # i32 scalar, consecutive non-overflow steps
    hysteresis: jnp.ndarray     # i32 scalar, remaining overflow tolerance


class LossScaler(NamedTuple):
    """Static config; state travels through the step function."""
    dynamic: bool
    init_scale: float
    scale_window: int
    scale_factor: float
    min_scale: float
    max_hysteresis: int
    consecutive_hysteresis: bool

    @classmethod
    def from_config(cls, fp16_cfg) -> "LossScaler":
        if not fp16_cfg.enabled:
            return cls(dynamic=False, init_scale=1.0, scale_window=1000,
                       scale_factor=2.0, min_scale=1.0, max_hysteresis=2,
                       consecutive_hysteresis=False)
        if fp16_cfg.dynamic_loss_scale:
            return cls(dynamic=True,
                       init_scale=float(2.0 ** fp16_cfg.initial_scale_power),
                       scale_window=fp16_cfg.loss_scale_window,
                       scale_factor=2.0,
                       min_scale=fp16_cfg.min_loss_scale,
                       max_hysteresis=fp16_cfg.hysteresis,
                       consecutive_hysteresis=fp16_cfg.consecutive_hysteresis)
        return cls(dynamic=False, init_scale=float(fp16_cfg.loss_scale),
                   scale_window=1000, scale_factor=2.0, min_scale=1.0,
                   max_hysteresis=2, consecutive_hysteresis=False)

    def init(self) -> LossScaleState:
        return LossScaleState(
            scale=jnp.asarray(self.init_scale, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            hysteresis=jnp.asarray(self.max_hysteresis, jnp.int32))

    def update(self, state: LossScaleState,
               overflow: jnp.ndarray) -> LossScaleState:
        """Advance scaler state given this step's overflow flag
        (reference: DynamicLossScaler.update_scale loss_scaler.py)."""
        if not self.dynamic:
            return state
        # overflow: if hysteresis is exhausted drop the scale, else spend one
        # hysteresis credit (reference: update_scale — delayed_shift)
        drop = overflow & (state.hysteresis <= 1)
        hyst = jnp.where(overflow & (state.hysteresis > 1),
                         state.hysteresis - 1, state.hysteresis)
        new_scale = jnp.where(
            drop, jnp.maximum(state.scale / self.scale_factor, self.min_scale),
            state.scale)
        good = jnp.where(overflow, 0, state.good_steps + 1)
        grow = (~overflow) & (good >= self.scale_window)
        new_scale = jnp.where(grow, new_scale * self.scale_factor, new_scale)
        good = jnp.where(grow, 0, good)
        # hysteresis refill: consecutive_hysteresis=True refills on every
        # good step (only *consecutive* overflows deplete it); False refills
        # only when the scale grows — matching the reference exactly.
        refill = jnp.asarray(self.max_hysteresis, jnp.int32)
        if self.consecutive_hysteresis:
            hyst = jnp.where(~overflow, refill, hyst)
        else:
            hyst = jnp.where(grow, refill, hyst)
        return LossScaleState(scale=new_scale, good_steps=good, hysteresis=hyst)


def all_finite(tree: Any) -> jnp.ndarray:
    """Single fused finite-check over a pytree (the CheckOverflow analog)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    flags = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(flags).all()


