"""Memory-mapped indexed dataset (.bin/.idx pair).

TPU-native analog of the reference's
``runtime/data_pipeline/data_sampling/indexed_dataset.py`` (627 LoC,
megatron-style MMapIndexedDataset): token corpora as two flat files —
``.bin`` holding the raw sample arrays back to back, ``.idx`` holding
dtype + per-sample lengths and byte offsets — read through ``np.memmap``
so a multi-hundred-GB corpus costs no RSS and every sample access is one
page-in.  The host-side loader feeds ``engine.shard_batch`` exactly like
the in-memory ``DataLoader``.

The format is self-describing but deliberately NOT byte-compatible with
megatron's (no legacy variants to carry); ``MMapIndexedDatasetBuilder``
writes it, ``MMapIndexedDataset`` reads it.
"""

from __future__ import annotations

import os
import struct
from typing import Optional, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX\x01"

_DTYPES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
    6: np.float32, 7: np.float64, 8: np.uint16, 9: np.uint32,
}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Append samples, then ``finalize()`` writes the index
    (reference: MMapIndexedDatasetBuilder indexed_dataset.py)."""

    def __init__(self, prefix: str, dtype=np.int32):
        self.prefix = prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._bin = open(data_file_path(prefix), "wb")
        self._sizes: list = []

    def add_item(self, arr) -> None:
        a = np.asarray(arr, dtype=self.dtype)
        if a.ndim != 1:
            raise ValueError(f"samples must be 1-D, got shape {a.shape}")
        self._bin.write(a.tobytes(order="C"))
        self._sizes.append(len(a))

    def merge_file(self, other_prefix: str) -> None:
        """Append another shard's samples (the reduce step of parallel
        corpus building)."""
        other = MMapIndexedDataset(other_prefix)
        if other.dtype != self.dtype:
            raise ValueError("dtype mismatch in merge")
        with open(data_file_path(other_prefix), "rb") as f:
            while True:
                chunk = f.read(1 << 24)
                if not chunk:
                    break
                self._bin.write(chunk)
        self._sizes.extend(other.sizes.tolist())

    def finalize(self) -> None:
        self._bin.close()
        sizes = np.asarray(self._sizes, np.int64)
        offsets = np.zeros(len(sizes) + 1, np.int64)
        np.cumsum(sizes * self.dtype.itemsize, out=offsets[1:])
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<BQ", _DTYPE_CODES[self.dtype],
                                len(sizes)))
            f.write(sizes.tobytes())
            f.write(offsets.tobytes())


class MMapIndexedDataset:
    """Zero-copy sample access over the .bin via np.memmap."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"bad index magic in {prefix}.idx")
            code, n = struct.unpack("<BQ", f.read(9))
            self.dtype = np.dtype(_DTYPES[code])
            self.sizes = np.frombuffer(f.read(8 * n), np.int64)
            self.offsets = np.frombuffer(f.read(8 * (n + 1)), np.int64)
        self._data = np.memmap(data_file_path(prefix), mode="r",
                               dtype=np.uint8)

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        lo, hi = self.offsets[i], self.offsets[i + 1]
        return np.frombuffer(self._data[lo:hi], dtype=self.dtype)

    def batch(self, indices: Sequence[int], seq_len: int,
              pad_id: int = 0) -> np.ndarray:
        """Gather samples into a right-padded/truncated [B, seq_len]
        batch (host-side; feeds shard_batch)."""
        out = np.full((len(indices), seq_len), pad_id, self.dtype)
        for r, i in enumerate(indices):
            s = self[i][:seq_len]
            out[r, :len(s)] = s
        return out

    @property
    def total_tokens(self) -> int:
        return int(self.sizes.sum())
