"""Offline map-reduce data analyzer for curriculum learning.

TPU-native analog of the reference's
``runtime/data_pipeline/data_sampling/data_analyzer.py`` (880 LoC): run
per-sample metric functions over a (possibly huge, mmap-backed) corpus in
parallel worker shards (*map*), then merge the shards into two on-disk
artifacts per metric (*reduce*):

* ``<metric>/sample_to_metric.npy`` — ``[N]`` metric value per sample;
* ``<metric>/metric_sorted_samples.npy`` — sample ids sorted ascending
  by metric value (+ ``metric_sorted_values.npy`` alongside), which is
  the ``metric_to_sample`` index the curriculum scheduler consumes via
  :func:`samples_up_to_difficulty` / :func:`difficulty_buckets`.

Workers are plain processes (launch N copies with ``worker_id=i``, then
one ``run_reduce``) — the same shape as the reference's
``run_map``/``run_reduce`` split, with numpy files instead of torch
serialization.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np


class DataAnalyzer:
    def __init__(self, dataset, metric_functions: Dict[str, Callable],
                 save_path: str, num_workers: int = 1, worker_id: int = 0,
                 batch_size: int = 1024):
        self.dataset = dataset
        self.metric_functions = dict(metric_functions)
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size
        os.makedirs(save_path, exist_ok=True)

    # ---------------------------------------------------------------- map
    def _shard_range(self):
        n = len(self.dataset)
        per = -(-n // self.num_workers)
        lo = min(n, self.worker_id * per)      # late workers: empty shard
        return lo, min(n, lo + per)

    def _worker_file(self, metric: str, worker: int) -> str:
        return os.path.join(self.save_path,
                            f"{metric}.worker{worker}.npy")

    def run_map(self) -> None:
        """Compute every metric over this worker's contiguous shard and
        persist ``(indices, values)`` (reference: run_map_helper)."""
        lo, hi = self._shard_range()
        vals = {m: np.empty(hi - lo, np.float64)
                for m in self.metric_functions}
        for i in range(lo, hi):
            sample = self.dataset[i]
            for m, fn in self.metric_functions.items():
                vals[m][i - lo] = float(fn(sample))
        for m, v in vals.items():
            np.save(self._worker_file(m, self.worker_id),
                    {"lo": lo, "values": v}, allow_pickle=True)

    # ------------------------------------------------------------- reduce
    def run_reduce(self) -> None:
        """Merge all workers' shards into the per-metric index files
        (reference: run_reduce / merge_map_results)."""
        n = len(self.dataset)
        for m in self.metric_functions:
            full = np.full(n, np.nan)
            for w in range(self.num_workers):
                d = np.load(self._worker_file(m, w),
                            allow_pickle=True).item()
                full[d["lo"]:d["lo"] + len(d["values"])] = d["values"]
            if np.isnan(full).any():
                raise RuntimeError(
                    f"metric {m!r}: missing worker shards "
                    f"({int(np.isnan(full).sum())} samples uncovered)")
            mdir = os.path.join(self.save_path, m)
            os.makedirs(mdir, exist_ok=True)
            np.save(os.path.join(mdir, "sample_to_metric.npy"), full)
            order = np.argsort(full, kind="stable")
            np.save(os.path.join(mdir, "metric_sorted_samples.npy"), order)
            np.save(os.path.join(mdir, "metric_sorted_values.npy"),
                    full[order])
            with open(os.path.join(mdir, "summary.json"), "w") as f:
                json.dump({"num_samples": int(n),
                           "min": float(full.min()),
                           "max": float(full.max()),
                           "mean": float(full.mean())}, f)

    def run(self) -> None:
        """Single-process convenience: map + reduce."""
        if self.num_workers != 1 or self.worker_id != 0:
            raise ValueError("run() is the single-worker path; use "
                             "run_map() per worker then run_reduce()")
        self.run_map()
        self.run_reduce()


# ----------------------------------------------------------- consumption

def load_metric(save_path: str, metric: str) -> Dict[str, np.ndarray]:
    mdir = os.path.join(save_path, metric)
    return {
        "sample_to_metric": np.load(
            os.path.join(mdir, "sample_to_metric.npy"), mmap_mode="r"),
        "sorted_samples": np.load(
            os.path.join(mdir, "metric_sorted_samples.npy"), mmap_mode="r"),
        "sorted_values": np.load(
            os.path.join(mdir, "metric_sorted_values.npy"), mmap_mode="r"),
    }


def samples_up_to_difficulty(save_path: str, metric: str,
                             max_value: float) -> np.ndarray:
    """Sample ids whose metric <= max_value — the curriculum scheduler's
    per-step candidate pool (reference: CurriculumScheduler consuming
    index_to_sample files)."""
    idx = load_metric(save_path, metric)
    k = int(np.searchsorted(idx["sorted_values"], max_value, side="right"))
    return np.asarray(idx["sorted_samples"][:k])


def difficulty_buckets(save_path: str, metric: str,
                       num_buckets: int) -> list:
    """Equal-count buckets of sample ids, easiest first."""
    idx = load_metric(save_path, metric)
    return [np.asarray(b) for b in
            np.array_split(np.asarray(idx["sorted_samples"]), num_buckets)]
