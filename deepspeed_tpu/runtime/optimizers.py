"""Optimizers as pure gradient transformations.

TPU-native equivalents of the reference's native optimizer kernels
(``csrc/adam/multi_tensor_adam.cu`` FusedAdam, ``csrc/adam/cpu_adam.cpp``
DeepSpeedCPUAdam, ``csrc/lamb/fused_lamb_cuda_kernel.cu``, ``csrc/lion``,
``csrc/adagrad``; Python wrappers ``deepspeed/ops/adam/fused_adam.py:18``
etc. and engine selection ``runtime/engine.py:1322``).

On TPU there is nothing to fuse by hand: the whole update is a few
elementwise ops that XLA fuses into one kernel over the (possibly
fsdp-sharded) state.  Each optimizer is an ``(init_fn, update_fn)`` pair —
optax-compatible shape, but self-contained so the framework owns its
semantics (notably: master-weight dtype policy and multi-precision state).

``update_fn(grads, state, params) -> (updates, state)`` where ``updates``
are *deltas* to add to (master) params.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]   # step -> lr


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any, jnp.ndarray], Tuple[Any, Any]]
    # update(grads, state, params, step) -> (updates, new_state)


def _tzeros(params, dtype=None):
    return jax.tree.map(
        lambda p: jnp.zeros_like(p, dtype=dtype or p.dtype), params)


def _bias_correction(beta: float, step: jnp.ndarray) -> jnp.ndarray:
    return 1.0 - jnp.asarray(beta, jnp.float32) ** step


def _tree_unzip(tree_of_tuples, template, n):
    """Split a tree whose leaves are n-tuples into n trees, using the
    template tree's structure (robust to tuple-valued containers)."""
    treedef = jax.tree.structure(template)
    flat = treedef.flatten_up_to(tree_of_tuples)
    return tuple(jax.tree.unflatten(treedef, [t[i] for t in flat])
                 for i in range(n))


# --------------------------------------------------------------------------
# Adam / AdamW  (reference: FusedAdam csrc/adam/multi_tensor_adam.cu,
#                DeepSpeedCPUAdam csrc/adam/cpu_adam_impl.cpp)
# --------------------------------------------------------------------------

class AdamState(NamedTuple):
    m: Any
    v: Any


def adamw(lr: Schedule | float, betas=(0.9, 0.999), eps: float = 1e-8,
          weight_decay: float = 0.01, adam_w_mode: bool = True,
          bias_correction: bool = True,
          moment_dtype=jnp.float32) -> Optimizer:
    """AdamW (adam_w_mode=True) or Adam with L2 (False) — matching the mode
    switch in the reference's FusedAdam (deepspeed/ops/adam/fused_adam.py)."""
    b1, b2 = betas
    lr_fn = lr if callable(lr) else (lambda _: jnp.asarray(lr, jnp.float32))

    def init(params):
        return AdamState(m=_tzeros(params, moment_dtype),
                         v=_tzeros(params, moment_dtype))

    def update(grads, state: AdamState, params, step):
        step_f = step.astype(jnp.float32)
        lr_t = lr_fn(step_f)
        c1 = _bias_correction(b1, step_f) if bias_correction else 1.0
        c2 = _bias_correction(b2, step_f) if bias_correction else 1.0

        def upd(g, m, v, p):
            g32 = g.astype(jnp.float32)
            if not adam_w_mode and weight_decay:          # classic L2
                g32 = g32 + weight_decay * p.astype(jnp.float32)
            m_ = b1 * m.astype(jnp.float32) + (1 - b1) * g32
            v_ = b2 * v.astype(jnp.float32) + (1 - b2) * (g32 * g32)
            mh = m_ / c1
            vh = v_ / c2
            delta = -lr_t * mh / (jnp.sqrt(vh) + eps)
            if adam_w_mode and weight_decay:              # decoupled decay
                delta = delta - lr_t * weight_decay * p.astype(jnp.float32)
            return delta, m_.astype(moment_dtype), v_.astype(moment_dtype)

        out = jax.tree.map(upd, grads, state.m, state.v, params)
        updates, m, v = _tree_unzip(out, grads, 3)
        return updates, AdamState(m=m, v=v)

    return Optimizer(init, update)


def adam(lr, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, **kw) -> Optimizer:
    return adamw(lr, betas, eps, weight_decay, adam_w_mode=False, **kw)


# --------------------------------------------------------------------------
# Lion  (reference: csrc/lion/fused_lion_frontend.cpp, cpu_lion)
# --------------------------------------------------------------------------

class LionState(NamedTuple):
    m: Any


def lion(lr, betas=(0.9, 0.99), weight_decay: float = 0.0,
         moment_dtype=jnp.float32) -> Optimizer:
    b1, b2 = betas
    lr_fn = lr if callable(lr) else (lambda _: jnp.asarray(lr, jnp.float32))

    def init(params):
        return LionState(m=_tzeros(params, moment_dtype))

    def update(grads, state: LionState, params, step):
        lr_t = lr_fn(step.astype(jnp.float32))

        def upd(g, m, p):
            g32 = g.astype(jnp.float32)
            m32 = m.astype(jnp.float32)
            delta = -lr_t * jnp.sign(b1 * m32 + (1 - b1) * g32)
            if weight_decay:
                delta = delta - lr_t * weight_decay * p.astype(jnp.float32)
            m_ = b2 * m32 + (1 - b2) * g32
            return delta, m_.astype(moment_dtype)

        out = jax.tree.map(upd, grads, state.m, params)
        updates, m = _tree_unzip(out, grads, 2)
        return updates, LionState(m=m)

    return Optimizer(init, update)


# --------------------------------------------------------------------------
# Adagrad  (reference: csrc/adagrad/cpu_adagrad.cpp)
# --------------------------------------------------------------------------

class AdagradState(NamedTuple):
    acc: Any


def adagrad(lr, eps: float = 1e-10, weight_decay: float = 0.0,
            initial_accumulator: float = 0.0) -> Optimizer:
    lr_fn = lr if callable(lr) else (lambda _: jnp.asarray(lr, jnp.float32))

    def init(params):
        return AdagradState(acc=jax.tree.map(
            lambda p: jnp.full_like(p, initial_accumulator, dtype=jnp.float32),
            params))

    def update(grads, state: AdagradState, params, step):
        lr_t = lr_fn(step.astype(jnp.float32))

        def upd(g, a, p):
            g32 = g.astype(jnp.float32)
            if weight_decay:
                g32 = g32 + weight_decay * p.astype(jnp.float32)
            a_ = a + g32 * g32
            return -lr_t * g32 / (jnp.sqrt(a_) + eps), a_

        out = jax.tree.map(upd, grads, state.acc, params)
        updates, acc = _tree_unzip(out, grads, 2)
        return updates, AdagradState(acc=acc)

    return Optimizer(init, update)


# --------------------------------------------------------------------------
# LAMB  (reference: csrc/lamb/fused_lamb_cuda_kernel.cu; FusedLamb wrapper)
# --------------------------------------------------------------------------

def lamb(lr, betas=(0.9, 0.999), eps: float = 1e-6, weight_decay: float = 0.0,
         min_trust: float = 0.01, max_trust: float = 10.0) -> Optimizer:
    """Layer-wise adaptive moments: per-tensor trust ratio
    ||p|| / ||update|| scales the step (large-batch training)."""
    b1, b2 = betas
    lr_fn = lr if callable(lr) else (lambda _: jnp.asarray(lr, jnp.float32))

    def init(params):
        return AdamState(m=_tzeros(params, jnp.float32),
                         v=_tzeros(params, jnp.float32))

    def update(grads, state: AdamState, params, step):
        step_f = step.astype(jnp.float32)
        lr_t = lr_fn(step_f)
        c1 = _bias_correction(b1, step_f)
        c2 = _bias_correction(b2, step_f)

        def upd(g, m, v, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_ = b1 * m + (1 - b1) * g32
            v_ = b2 * v + (1 - b2) * (g32 * g32)
            u = (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)
            if weight_decay:
                u = u + weight_decay * p32
            w_norm = jnp.linalg.norm(p32.ravel())
            u_norm = jnp.linalg.norm(u.ravel())
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_trust, max_trust), 1.0)
            return -lr_t * trust * u, m_, v_

        out = jax.tree.map(upd, grads, state.m, state.v, params)
        updates, m, v = _tree_unzip(out, grads, 3)
        return updates, AdamState(m=m, v=v)

    return Optimizer(init, update)


# --------------------------------------------------------------------------
# SGD (momentum)
# --------------------------------------------------------------------------

class SGDState(NamedTuple):
    mom: Any


def sgd(lr, momentum: float = 0.0, weight_decay: float = 0.0,
        nesterov: bool = False) -> Optimizer:
    lr_fn = lr if callable(lr) else (lambda _: jnp.asarray(lr, jnp.float32))

    def init(params):
        return SGDState(mom=_tzeros(params, jnp.float32))

    def update(grads, state: SGDState, params, step):
        lr_t = lr_fn(step.astype(jnp.float32))

        def upd(g, b, p):
            g32 = g.astype(jnp.float32)
            if weight_decay:
                g32 = g32 + weight_decay * p.astype(jnp.float32)
            b_ = momentum * b + g32
            d = g32 + momentum * b_ if nesterov else b_
            return -lr_t * d, b_

        out = jax.tree.map(upd, grads, state.mom, params)
        updates, mom = _tree_unzip(out, grads, 2)
        return updates, SGDState(mom=mom)

    return Optimizer(init, update)


# --------------------------------------------------------------------------
# Registry (reference: engine._configure_basic_optimizer engine.py:1322)
# --------------------------------------------------------------------------

def _onebit(name):
    def build(lr, **kw):
        from . import onebit
        return getattr(onebit, name)(lr, **kw)
    return build


OPTIMIZERS: Dict[str, Callable[..., Optimizer]] = {
    "adam": adam,
    "adamw": adamw,
    "lion": lion,
    "lamb": lamb,
    "adagrad": adagrad,
    "sgd": sgd,
    # 1-bit family (reference: OnebitAdam/ZeroOneAdam/OnebitLamb,
    # engine.py:1322 name keys onebitadam/zerooneadam/onebitlamb)
    "onebitadam": _onebit("onebit_adam"),
    "zerooneadam": _onebit("zero_one_adam"),
    "onebitlamb": _onebit("onebit_lamb"),
}


def build_optimizer(name: str, lr, params_cfg: Optional[Dict] = None) -> Optimizer:
    name = name.lower()
    if name not in OPTIMIZERS:
        raise ValueError(f"Unknown optimizer {name!r}; known: {sorted(OPTIMIZERS)}")
    kw = dict(params_cfg or {})
    kw.pop("lr", None)
    # torch-style betas list
    if "betas" in kw:
        kw["betas"] = tuple(kw["betas"])
    return OPTIMIZERS[name](lr, **kw)
