"""MoQ: Mixture-of-Quantization progressive training quantizer.

TPU-native equivalent of ``runtime/quantize.py`` (Quantizer — progressive
target-bit schedule over training, optionally eigenvalue-paced) and
``compression/weight_quantizer.py``.  Quantization itself is the grouped
fake-quant from :mod:`deepspeed_tpu.compression`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax

from ..compression.compress import weight_quantization
from ..utils.logging import logger


class Quantizer:
    """(reference: runtime/quantize.py:~180 Quantizer — q_start_bits,
    q_target_bits, q_period per group, quantize_weight_in_forward)."""

    def __init__(self, q_start_bits: int = 16, q_target_bits: int = 8,
                 q_period: int = 1000, q_groups: int = 1,
                 use_quantizer_kernel: bool = False):
        self.start_bits = q_start_bits
        # the grouped int kernel supports 8- and 4-bit targets; the
        # reference's fp6/fp12 formats have no TPU dtype — round up
        if q_target_bits not in (4, 8) and q_target_bits < 16:
            rounded = 4 if q_target_bits <= 4 else 8
            logger.warning(
                "MoQ target_bits=%d unsupported (int4/int8 only); "
                "using %d", q_target_bits, rounded)
            q_target_bits = rounded
        self.target_bits = q_target_bits
        self.period = q_period
        self.groups = q_groups
        self.qsteps = 0

    def current_bits(self, step: Optional[int] = None) -> int:
        step = self.qsteps if step is None else step
        # halve precision each period until the target (reference:
        # quantize_highbit bit-reduction cadence)
        bits = self.start_bits
        periods = step // max(1, self.period)
        for _ in range(periods):
            if bits <= self.target_bits:
                break
            bits = max(self.target_bits, bits // 2)
        return bits

    def quantize(self, params: Any, step: Optional[int] = None) -> Any:
        bits = self.current_bits(step)
        self.qsteps = (step if step is not None else self.qsteps) + 1
        if bits > 8:            # above int8 there is nothing to fake-quant
            return params
        return jax.tree.map(
            lambda w: weight_quantization(w, bits=bits, groups=self.groups)
            if hasattr(w, "ndim") and w.ndim >= 1 and w.size % 2 == 0
            else w, params)
