"""Platform abstraction — the accelerator interface, TPU-native.

Analog of the reference's ``DeepSpeedAccelerator`` ABC
(``accelerator/abstract_accelerator.py:10``, ~70 methods) and
``get_accelerator()`` singleton (``accelerator/real_accelerator.py:51``).
Most of the ABC's surface (streams, events, graphs) has no TPU meaning —
XLA owns scheduling — so this interface keeps the parts that do: device
identity/count, memory stats, dtype support, RNG seeding, host ("pinned")
memory placement, and synchronization.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class TPUPlatform:
    """Singleton returned by :func:`get_platform`."""

    _name = "tpu"

    # ---- identity --------------------------------------------------------
    def device_name(self, device_index: Optional[int] = None) -> str:
        devs = jax.local_devices()
        if device_index is None:
            return self.platform_kind()
        return str(devs[device_index])

    def platform_kind(self) -> str:
        return jax.devices()[0].platform

    def is_available(self) -> bool:
        return len(jax.devices()) > 0

    def device_count(self) -> int:
        return jax.device_count()

    def local_device_count(self) -> int:
        return jax.local_device_count()

    def process_index(self) -> int:
        return jax.process_index()

    def process_count(self) -> int:
        return jax.process_count()

    def communication_backend_name(self) -> str:
        # XLA emits collectives directly; there is no separate comm library
        # (reference: abstract_accelerator.py:202 returns 'nccl').
        return "xla"

    # ---- synchronization -------------------------------------------------
    def synchronize(self) -> None:
        jax.effects_barrier()

    # ---- memory ----------------------------------------------------------
    def memory_stats(self, device_index: int = 0) -> Dict[str, Any]:
        try:
            return jax.local_devices()[device_index].memory_stats() or {}
        # capability probe on a hot path (polled per step by monitors)
        except Exception:  # tpulint: disable=silent-except
            return {}

    def memory_allocated(self, device_index: int = 0) -> int:
        return int(self.memory_stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: int = 0) -> int:
        return int(self.memory_stats(device_index).get("peak_bytes_in_use", 0))

    def total_memory(self, device_index: int = 0) -> int:
        return int(self.memory_stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: int = 0) -> int:
        s = self.memory_stats(device_index)
        return int(s.get("bytes_limit", 0)) - int(s.get("bytes_in_use", 0))

    # ---- host memory ("pinned") placement -------------------------------
    def host_sharding(self, sharding):
        """Host-DRAM variant of a sharding (for offloaded states)."""
        return sharding.with_memory_kind("pinned_host")

    def to_host(self, x):
        """Move an array to pinned host memory, keeping its layout."""
        return jax.device_put(
            x, jax.sharding.SingleDeviceSharding(
                jax.local_devices()[0], memory_kind="pinned_host"))

    def supports_host_offload(self) -> bool:
        try:
            dev = jax.local_devices()[0]
            return "pinned_host" in [m.kind for m in dev.addressable_memories()]
        except Exception:  # tpulint: disable=silent-except — capability probe
            return False

    # ---- dtypes ----------------------------------------------------------
    def is_bf16_supported(self) -> bool:
        return True  # every TPU generation we target

    def is_fp16_supported(self) -> bool:
        return True  # storage/compute dtype; MXU accumulates fp32 anyway

    def supported_dtypes(self) -> List[Any]:
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    def preferred_dtype(self):
        return jnp.bfloat16

    # ---- RNG -------------------------------------------------------------
    def rng_key(self, seed: int) -> jax.Array:
        return jax.random.key(seed)

    # ---- misc ------------------------------------------------------------
    def on_tpu(self) -> bool:
        return self.platform_kind() in ("tpu", "axon")

    def visible_devices_env(self) -> str:
        return os.environ.get("JAX_VISIBLE_DEVICES", "")


@functools.lru_cache(None)
def get_platform() -> TPUPlatform:
    """The ``get_accelerator()`` analog (reference: real_accelerator.py:51)."""
    return TPUPlatform()
