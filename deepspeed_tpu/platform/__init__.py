from .tpu import TPUPlatform, get_platform

__all__ = ["TPUPlatform", "get_platform"]
