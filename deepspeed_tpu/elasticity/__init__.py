from .elasticity import (ElasticityError, compute_elastic_config,
                         elasticity_fingerprint, ensure_immutable,
                         get_candidate_batch_sizes, get_valid_devices)

__all__ = ["compute_elastic_config", "get_candidate_batch_sizes",
           "get_valid_devices", "elasticity_fingerprint",
           "ensure_immutable", "ElasticityError"]
