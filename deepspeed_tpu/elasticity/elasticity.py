"""Elastic training: device-count-compatible batch size planning.

TPU-native equivalent of the reference elasticity module
(``elasticity/elasticity.py`` — candidate batch composition :83, valid
device counts :126, ``compute_elastic_config`` :233, config-immutability
enforcement :208).  The torchelastic agent (``elastic_agent.py:32``) has
no analog here: membership changes restart the job and resume from the
fragment checkpoint store (deepspeed_tpu/checkpoint — shape-shifting
resume is the default), so elasticity reduces to *planning*: pick a
train batch size divisible under every admissible device count.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Sequence, Tuple

from ..utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(ValueError):
    pass


def get_candidate_batch_sizes(base_list: Sequence[int],
                              max_acc_step: int) -> List[int]:
    """All micro_batch * gas products under the cap
    (reference: elasticity.py:83 get_candidate_batch_sizes)."""
    out = set()
    for base in base_list:
        for acc in range(1, max_acc_step + 1):
            out.add(base * acc)
    return sorted(out)


def get_valid_devices(batch_size: int, micro_batches: Sequence[int],
                      min_devices: int, max_devices: int) -> List[int]:
    """Device counts that evenly tile ``batch_size`` with some micro batch
    (reference: elasticity.py:126 get_valid_gpus)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        replicas = batch_size // mb
        for n in range(min_devices, max_devices + 1):
            if replicas % n == 0:
                valid.add(n)
    return sorted(valid)


def _best_candidate(candidates: Sequence[int], micro_batches: Sequence[int],
                    min_devices: int, max_devices: int,
                    prefer_larger: bool) -> Tuple[int, List[int]]:
    best_batch, best_valid = -1, []
    for b in sorted(candidates, reverse=prefer_larger):
        valid = get_valid_devices(b, micro_batches, min_devices, max_devices)
        if len(valid) > len(best_valid) or (
                len(valid) == len(best_valid) and best_batch < 0):
            best_batch, best_valid = b, valid
    if best_batch < 0 or not best_valid:
        raise ElasticityError(
            f"no compatible batch size for micro_batches={micro_batches} "
            f"devices [{min_devices}, {max_devices}]")
    return best_batch, best_valid


def compute_elastic_config(ds_config: Dict, target_deviation: float = 0.0,
                           world_size: int = 0):
    """(reference: elasticity.py:233 compute_elastic_config).

    Returns ``(final_batch_size, valid_device_counts, micro_batch)`` —
    micro batch only when ``world_size`` is given.
    """
    ecfg = ds_config.get("elasticity", {})
    if not ecfg.get("enabled", False):
        raise ElasticityError("elasticity block missing or disabled")
    version = float(ecfg.get("version", LATEST_ELASTICITY_VERSION))
    micro_batches = list(ecfg.get("micro_batch_sizes", [2, 4, 6]))
    max_batch = int(ecfg.get("max_train_batch_size", 2000))
    min_dev = int(ecfg.get("min_devices", ecfg.get("min_gpus", 1)))
    max_dev = int(ecfg.get("max_devices", ecfg.get("max_gpus", 10000)))
    prefer_larger = bool(ecfg.get("prefer_larger_batch", True))
    if version not in (0.1, 0.2):
        raise ElasticityError(f"unknown elasticity version {version}")
    if any(mb <= 0 for mb in micro_batches):
        raise ElasticityError(f"bad micro_batch_sizes {micro_batches}")

    if version >= 0.2:
        return _plan_v02(ecfg, micro_batches, max_batch, min_dev, max_dev,
                         prefer_larger, world_size)
    final_batch, valid = _plan_v01(micro_batches, max_batch, min_dev,
                                   max_dev, prefer_larger)
    if world_size > 0:
        if world_size not in valid:
            raise ElasticityError(
                f"world size {world_size} incompatible with elastic batch "
                f"{final_batch} (valid: {valid})")
        for mb in sorted(micro_batches, reverse=True):
            if final_batch % (mb * world_size) == 0:
                return final_batch, valid, mb
        raise ElasticityError(
            f"no micro batch fits batch={final_batch} world={world_size}")
    return final_batch, valid


def _plan_v01(micro_batches: Sequence[int], max_batch: int, min_dev: int,
              max_dev: int, prefer_larger: bool) -> Tuple[int, List[int]]:
    """v0.1 heuristic (reference: _get_compatible_gpus_v01): every
    micro batch scaled by every accumulation step up to the cap; keep
    the candidate with the most compatible device counts.  (The
    reference also seeds the LCM of the micro batches, but every
    lcm*k <= cap is already generated as min(micro)*k', so the extra
    base is provably redundant.)"""
    max_acc = max_batch // min(micro_batches)
    candidates = [b for b in
                  get_candidate_batch_sizes(micro_batches, max_acc)
                  if b <= max_batch]
    return _best_candidate(candidates, micro_batches, min_dev, max_dev,
                           prefer_larger)


def _plan_v02(ecfg: Dict, micro_batches: Sequence[int], max_batch: int,
              min_dev: int, max_dev: int, prefer_larger: bool,
              world_size: int):
    """v0.2 (reference: _get_compatible_gpus_v02): model-parallel-aware
    planning at NODE granularity — each node contributes
    ``devices_per_node // model_parallel_size`` data replicas, so the
    v0.1 search runs over node counts with the batch cap scaled down by
    the per-node DP degree, then scales back to device counts."""
    mp = int(ecfg.get("model_parallel_size", 1))
    dpn = int(ecfg.get("devices_per_node",
                       ecfg.get("num_gpus_per_node", 1)))
    if dpn % mp:
        raise ElasticityError(
            f"elasticity v0.2: devices_per_node={dpn} must divide by "
            f"model_parallel_size={mp}")
    dp_per_node = dpn // mp
    max_nodes = max_dev // dpn
    if max_nodes < 1:
        raise ElasticityError(
            f"elasticity v0.2: max_devices={max_dev} cannot fit one "
            f"{dpn}-device node")
    min_nodes = max(-(-min_dev // dpn), 1)      # ceiling: respect floor
    node_batch, valid_nodes = _plan_v01(
        micro_batches, max_batch // dp_per_node,
        min_nodes, max_nodes, prefer_larger)
    final_batch = node_batch * dp_per_node
    valid = [n * dpn for n in valid_nodes]
    if world_size > 0:
        if world_size not in valid:
            raise ElasticityError(
                f"world size {world_size} incompatible with elastic batch "
                f"{final_batch} (valid device counts: {valid})")
        dp_world = world_size // mp
        micro = None
        for mb in micro_batches:
            if (final_batch // dp_world) % mb == 0:
                if micro is None or (mb > micro if prefer_larger
                                     else mb < micro):
                    micro = mb
        if micro is None:
            raise ElasticityError(
                f"no micro batch fits batch={final_batch} "
                f"world={world_size} mp={mp}")
        return final_batch, valid, micro
    return final_batch, valid


def elasticity_fingerprint(ds_config: Dict) -> str:
    """Hash of the elasticity block — runs must not silently change it
    (reference: elasticity.py:208 enforced immutability)."""
    blob = json.dumps(ds_config.get("elasticity", {}), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def ensure_immutable(ds_config: Dict, recorded_fingerprint: str) -> None:
    fp = elasticity_fingerprint(ds_config)
    if fp != recorded_fingerprint:
        raise ElasticityError(
            "elasticity config changed across runs "
            f"({recorded_fingerprint} -> {fp}); elastic jobs must keep it "
            "fixed so every restart computes the same batch plan")
