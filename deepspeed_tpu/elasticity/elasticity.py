"""Elastic training: device-count-compatible batch size planning.

TPU-native equivalent of the reference elasticity module
(``elasticity/elasticity.py`` — candidate batch composition :83, valid
device counts :126, ``compute_elastic_config`` :233, config-immutability
enforcement :208).  The torchelastic agent (``elastic_agent.py:32``) has
no analog here: membership changes restart the job and resume from the
fragment checkpoint store (deepspeed_tpu/checkpoint — shape-shifting
resume is the default), so elasticity reduces to *planning*: pick a
train batch size divisible under every admissible device count.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Sequence, Tuple

from ..utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(ValueError):
    pass


def get_candidate_batch_sizes(base_list: Sequence[int],
                              max_acc_step: int) -> List[int]:
    """All micro_batch * gas products under the cap
    (reference: elasticity.py:83 get_candidate_batch_sizes)."""
    out = set()
    for base in base_list:
        for acc in range(1, max_acc_step + 1):
            out.add(base * acc)
    return sorted(out)


def get_valid_devices(batch_size: int, micro_batches: Sequence[int],
                      min_devices: int, max_devices: int) -> List[int]:
    """Device counts that evenly tile ``batch_size`` with some micro batch
    (reference: elasticity.py:126 get_valid_gpus)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        replicas = batch_size // mb
        for n in range(min_devices, max_devices + 1):
            if replicas % n == 0:
                valid.add(n)
    return sorted(valid)


def _best_candidate(candidates: Sequence[int], micro_batches: Sequence[int],
                    min_devices: int, max_devices: int,
                    prefer_larger: bool) -> Tuple[int, List[int]]:
    best_batch, best_valid = -1, []
    for b in sorted(candidates, reverse=prefer_larger):
        valid = get_valid_devices(b, micro_batches, min_devices, max_devices)
        if len(valid) > len(best_valid) or (
                len(valid) == len(best_valid) and best_batch < 0):
            best_batch, best_valid = b, valid
    if best_batch < 0 or not best_valid:
        raise ElasticityError(
            f"no compatible batch size for micro_batches={micro_batches} "
            f"devices [{min_devices}, {max_devices}]")
    return best_batch, best_valid


def compute_elastic_config(ds_config: Dict, target_deviation: float = 0.0,
                           world_size: int = 0):
    """(reference: elasticity.py:233 compute_elastic_config).

    Returns ``(final_batch_size, valid_device_counts, micro_batch)`` —
    micro batch only when ``world_size`` is given.
    """
    ecfg = ds_config.get("elasticity", {})
    if not ecfg.get("enabled", False):
        raise ElasticityError("elasticity block missing or disabled")
    version = float(ecfg.get("version", LATEST_ELASTICITY_VERSION))
    micro_batches = list(ecfg.get("micro_batch_sizes", [2, 4, 6]))
    max_batch = int(ecfg.get("max_train_batch_size", 2000))
    min_dev = int(ecfg.get("min_devices", ecfg.get("min_gpus", 1)))
    max_dev = int(ecfg.get("max_devices", ecfg.get("max_gpus", 10000)))
    prefer_larger = bool(ecfg.get("prefer_larger_batch", True))
    if version not in (0.1, 0.2):
        raise ElasticityError(f"unknown elasticity version {version}")
    if any(mb <= 0 for mb in micro_batches):
        raise ElasticityError(f"bad micro_batch_sizes {micro_batches}")

    max_acc = max_batch // min(micro_batches)
    candidates = [b for b in get_candidate_batch_sizes(micro_batches, max_acc)
                  if b <= max_batch]
    if version >= 0.2:
        # v0.2 restriction: device count must also satisfy the
        # min/max window exactly (reference: _get_compatible_gpus_v02)
        candidates = [b for b in candidates
                      if get_valid_devices(b, micro_batches, min_dev,
                                           max_dev)]
    final_batch, valid = _best_candidate(candidates, micro_batches,
                                         min_dev, max_dev, prefer_larger)

    if world_size > 0:
        if world_size not in valid:
            raise ElasticityError(
                f"world size {world_size} incompatible with elastic batch "
                f"{final_batch} (valid: {valid})")
        for mb in sorted(micro_batches, reverse=True):
            if final_batch % (mb * world_size) == 0:
                return final_batch, valid, mb
        raise ElasticityError(
            f"no micro batch fits batch={final_batch} world={world_size}")
    return final_batch, valid


def elasticity_fingerprint(ds_config: Dict) -> str:
    """Hash of the elasticity block — runs must not silently change it
    (reference: elasticity.py:208 enforced immutability)."""
    blob = json.dumps(ds_config.get("elasticity", {}), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def ensure_immutable(ds_config: Dict, recorded_fingerprint: str) -> None:
    fp = elasticity_fingerprint(ds_config)
    if fp != recorded_fingerprint:
        raise ElasticityError(
            "elasticity config changed across runs "
            f"({recorded_fingerprint} -> {fp}); elastic jobs must keep it "
            "fixed so every restart computes the same batch plan")
