// io_uring submission engine for the aio library — the DeepNVMe/libaio
// analog with a REAL kernel queue depth (reference:
// csrc/aio/py_lib/deepspeed_aio_thread.cpp drives libaio's
// io_submit/io_getevents; here the same role is played by io_uring,
// which supersedes libaio on modern kernels).
//
// Raw-syscall implementation (no liburing in the image): ring setup +
// mmap, SQE fill, io_uring_enter submit/reap.  Design:
//   * ONE ring of `queue_depth` entries; chunk submission blocks when
//     every kernel slot is in flight — queue_depth is the actual number
//     of I/Os the kernel juggles, not a user-space backpressure couter.
//   * a dedicated reaper thread waits for CQEs, handles short
//     reads/writes by resubmitting the remainder, and retires ops.
//   * O_DIRECT chunks use REGISTERED buffers (IORING_REGISTER_BUFFERS)
//     with IORING_OP_{READ,WRITE}_FIXED — one pinned aligned buffer per
//     ring slot, mapped once at init, the io_uring counterpart of the
//     reference's pinned-tensor pool (deepspeed_pin_tensor.cpp).
//   * filesystems that reject O_DIRECT (tmpfs) fall back per-op to the
//     buffered fd, same policy as the thread-pool engine.

#pragma once

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <fcntl.h>
#include <linux/io_uring.h>
#include <memory>
#include <mutex>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace uring {

inline int sys_setup(unsigned entries, struct io_uring_params *p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}
inline int sys_enter(int fd, unsigned to_submit, unsigned min_complete,
                     unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete,
                      flags, nullptr, 0);
}
inline int sys_register(int fd, unsigned opcode, const void *arg,
                        unsigned nr_args) {
  return (int)syscall(__NR_io_uring_register, fd, opcode, arg, nr_args);
}

// true iff the kernel/sandbox allows io_uring AND supports the opcodes
// the engine issues (IORING_OP_READ/WRITE and the _FIXED variants are
// 5.6+; io_uring_setup alone succeeds on 5.1-5.5 where they would all
// complete -EINVAL).  IORING_REGISTER_PROBE is itself 5.6+, so probe
// failure means "too old" either way.
inline bool available() {
  struct io_uring_params p;
  std::memset(&p, 0, sizeof(p));
  int fd = sys_setup(2, &p);
  if (fd < 0) return false;
  constexpr unsigned kOps = IORING_OP_WRITE + 1;
  char raw[sizeof(struct io_uring_probe) +
           kOps * sizeof(struct io_uring_probe_op)];
  std::memset(raw, 0, sizeof(raw));
  auto *probe = reinterpret_cast<struct io_uring_probe *>(raw);
  bool ok = sys_register(fd, IORING_REGISTER_PROBE, probe, kOps) == 0 &&
            probe->last_op >= IORING_OP_WRITE;
  if (ok) {
    auto supported = [&](unsigned op) {
      return (probe->ops[op].flags & IO_URING_OP_SUPPORTED) != 0;
    };
    ok = supported(IORING_OP_READ) && supported(IORING_OP_WRITE) &&
         supported(IORING_OP_READ_FIXED) &&
         supported(IORING_OP_WRITE_FIXED) && supported(IORING_OP_NOP);
  }
  close(fd);
  return ok;
}

// mmap'd ring state (raw pointers into the shared kernel mappings)
struct Ring {
  int fd = -1;
  unsigned entries = 0;
  // SQ
  std::atomic<unsigned> *sq_head = nullptr, *sq_tail = nullptr;
  unsigned sq_mask = 0;
  unsigned *sq_array = nullptr;
  struct io_uring_sqe *sqes = nullptr;
  // CQ
  std::atomic<unsigned> *cq_head = nullptr, *cq_tail = nullptr;
  unsigned cq_mask = 0;
  struct io_uring_cqe *cqes = nullptr;
  void *sq_ptr = nullptr, *cq_ptr = nullptr, *sqe_ptr = nullptr;
  size_t sq_sz = 0, cq_sz = 0, sqe_sz = 0;

  bool init(unsigned depth) {
    struct io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    fd = sys_setup(depth, &p);
    if (fd < 0) return false;
    entries = p.sq_entries;
    sq_sz = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_sz = p.cq_off.cqes + p.cq_entries * sizeof(struct io_uring_cqe);
    bool single = p.features & IORING_FEAT_SINGLE_MMAP;
    if (single) sq_sz = cq_sz = (sq_sz > cq_sz ? sq_sz : cq_sz);
    sq_ptr = mmap(nullptr, sq_sz, PROT_READ | PROT_WRITE,
                  MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
    if (sq_ptr == MAP_FAILED) return false;
    cq_ptr = single ? sq_ptr
                    : mmap(nullptr, cq_sz, PROT_READ | PROT_WRITE,
                           MAP_SHARED | MAP_POPULATE, fd,
                           IORING_OFF_CQ_RING);
    if (cq_ptr == MAP_FAILED) return false;
    sqe_sz = p.sq_entries * sizeof(struct io_uring_sqe);
    sqe_ptr = mmap(nullptr, sqe_sz, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES);
    if (sqe_ptr == MAP_FAILED) return false;
    auto b = static_cast<char *>(sq_ptr);
    sq_head = reinterpret_cast<std::atomic<unsigned> *>(b + p.sq_off.head);
    sq_tail = reinterpret_cast<std::atomic<unsigned> *>(b + p.sq_off.tail);
    sq_mask = *reinterpret_cast<unsigned *>(b + p.sq_off.ring_mask);
    sq_array = reinterpret_cast<unsigned *>(b + p.sq_off.array);
    auto c = static_cast<char *>(cq_ptr);
    cq_head = reinterpret_cast<std::atomic<unsigned> *>(c + p.cq_off.head);
    cq_tail = reinterpret_cast<std::atomic<unsigned> *>(c + p.cq_off.tail);
    cq_mask = *reinterpret_cast<unsigned *>(c + p.cq_off.ring_mask);
    cqes = reinterpret_cast<struct io_uring_cqe *>(c + p.cq_off.cqes);
    sqes = static_cast<struct io_uring_sqe *>(sqe_ptr);
    return true;
  }

  // caller serializes; returns false when the SQ is full
  bool push(const struct io_uring_sqe &sqe) {
    unsigned head = sq_head->load(std::memory_order_acquire);
    unsigned tail = sq_tail->load(std::memory_order_relaxed);
    if (tail - head >= entries) return false;
    unsigned idx = tail & sq_mask;
    sqes[idx] = sqe;
    sq_array[idx] = idx;
    sq_tail->store(tail + 1, std::memory_order_release);
    return true;
  }

  // caller serializes; returns number of CQEs popped into out[]
  int pop(struct io_uring_cqe *out, int max) {
    unsigned head = cq_head->load(std::memory_order_relaxed);
    unsigned tail = cq_tail->load(std::memory_order_acquire);
    int n = 0;
    while (head != tail && n < max) {
      out[n++] = cqes[head & cq_mask];
      ++head;
    }
    cq_head->store(head, std::memory_order_release);
    return n;
  }

  ~Ring() {
    if (sqe_ptr && sqe_ptr != MAP_FAILED) munmap(sqe_ptr, sqe_sz);
    if (cq_ptr && cq_ptr != MAP_FAILED && cq_ptr != sq_ptr)
      munmap(cq_ptr, cq_sz);
    if (sq_ptr && sq_ptr != MAP_FAILED) munmap(sq_ptr, sq_sz);
    if (fd >= 0) close(fd);
  }
};

}  // namespace uring
