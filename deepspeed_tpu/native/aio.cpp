// Async file I/O thread pool — the DeepNVMe/aio analog.
//
// TPU-native counterpart of the reference's csrc/aio library
// (deepspeed_aio_thread.cpp thread pool, py_ds_aio.cpp bindings,
// deepspeed_pin_tensor.cpp pinned buffers): a C++ worker pool doing
// chunked pread/pwrite against NVMe-backed files, exposed through a
// plain C ABI consumed via ctypes (no pybind11 in this image).
//
// Requests are split into block_size chunks fanned across the pool, so a
// single large tensor read/write saturates multiple NVMe queues exactly
// like the reference's parallel pread/pwrite (csrc/aio/py_lib
// deepspeed_py_aio_handle.cpp).  Each request opens its file once; the
// fds are shared by all of its chunks and closed when the last chunk
// retires.
//
// The reference handle's knobs are consumed with these semantics
// (csrc/aio/py_lib/deepspeed_py_aio_handle.cpp):
//   block_size    — chunk granularity (parallelism unit).
//   queue_depth   — max chunks in flight; submission applies
//                   backpressure beyond it (libaio iodepth analog).
//   single_submit — one op per request instead of chunking (the
//                   reference's non-batched submit mode).
//   overlap_events— when false, each submit drains before returning
//                   (no submit/complete overlap).
//   use_odirect   — page-cache bypass: 4096-aligned spans go through an
//                   O_DIRECT fd via pooled aligned bounce buffers
//                   (numpy callers guarantee no alignment); unaligned
//                   head/tail spans use a buffered fd.  Filesystems
//                   without O_DIRECT (tmpfs) fall back silently;
//                   aio_odirect_ops reports what actually happened.

#ifndef _GNU_SOURCE
#define _GNU_SOURCE  // O_DIRECT
#endif

#include <atomic>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <functional>
#include <memory>
#include <mutex>
#include <poll.h>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <vector>

#include "uring.h"

namespace {

constexpr long kAlign = 4096;

// backend-agnostic surface the C ABI dispatches through
class Engine {
public:
  virtual ~Engine() = default;
  virtual void submit(const char *path, char *buf, long nbytes, long offset,
                      bool write, bool trunc = false) = 0;
  virtual int wait() = 0;
  virtual int pending() const = 0;
  virtual long odirect_ops() const = 0;
  virtual long tasks_total() const = 0;
  virtual int backend() const = 0;  // 0 = thread pool, 1 = io_uring
};

struct Chunk {
  long off;
  long len;
  bool direct;
};

// split the file span [offset, offset+nbytes) into an unaligned head,
// an aligned O_DIRECT-eligible body (chunked by block_size) and an
// unaligned tail — shared by both engines
inline std::vector<Chunk> plan_chunks(long offset, long nbytes,
                                      long block_size, bool single_submit,
                                      bool have_direct) {
  std::vector<Chunk> out;
  long end = offset + nbytes;
  if (single_submit) {
    if (nbytes > 0) out.push_back({offset, nbytes, false});
    return out;
  }
  long body_lo = offset, body_hi = end;
  if (have_direct) {
    body_lo = (offset + kAlign - 1) / kAlign * kAlign;
    body_hi = end / kAlign * kAlign;
    if (body_hi <= body_lo) { body_lo = body_hi = offset; }
  } else {
    for (long done = 0; done < nbytes; done += block_size)
      out.push_back({offset + done, std::min(block_size, nbytes - done),
                     false});
    return out;
  }
  if (body_lo > offset) out.push_back({offset, body_lo - offset, false});
  for (long off = body_lo; off < body_hi; off += block_size)
    out.push_back({off, std::min(block_size, body_hi - off), true});
  if (end > body_hi) out.push_back({body_hi, end - body_hi, false});
  return out;
}

// One submitted read/write; owns the fds for all its chunks.
struct Request {
  int fd = -1;         // buffered
  int fd_direct = -1;  // O_DIRECT (or -1: unsupported / disabled)
  Request() = default;
  Request(const Request &) = delete;
  Request &operator=(const Request &) = delete;
  ~Request() {
    if (fd >= 0) close(fd);
    if (fd_direct >= 0) close(fd_direct);
  }
};

struct Task {
  std::shared_ptr<Request> req;
  char *buf;
  long nbytes;
  long offset;
  bool write;
  bool direct;  // aligned span eligible for the O_DIRECT fd
};

// open the buffered (and optionally O_DIRECT) fds for one request and
// apply the trunc-for-full-rewrite policy — shared by both engines
inline std::shared_ptr<Request> make_request(
    const char *path, long nbytes, long offset, bool write, bool trunc,
    bool want_direct, std::atomic<int> &errors) {
  int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
  auto req = std::make_shared<Request>();
  req->fd = open(path, flags, 0644);
  if (req->fd < 0) {
    errors.fetch_add(1);
    return nullptr;
  }
  if (want_direct)
    req->fd_direct = open(path, flags | O_DIRECT, 0644);  // may fail: ok
  // opt-in for full-file rewrites: a smaller rewrite must not leave a
  // stale tail from a previous, larger request
  if (write && trunc) {
    if (ftruncate(req->fd, offset + nbytes) != 0) errors.fetch_add(1);
  }
  return req;
}


class AioPool : public Engine {
public:
  AioPool(int num_threads, long block_size, int queue_depth,
          int single_submit, int overlap_events, int use_odirect)
      : block_size_(block_size), queue_depth_(queue_depth),
        single_submit_(single_submit != 0),
        overlap_events_(overlap_events != 0),
        use_odirect_(use_odirect != 0), stop_(false), pending_(0),
        errors_(0), odirect_ops_(0), tasks_total_(0) {
    if (num_threads < 1) num_threads = 1;
    if (block_size_ < 1) block_size_ = 1 << 20;
    // O_DIRECT chunks must stay 4096-multiples
    if (use_odirect_ && block_size_ % kAlign)
      block_size_ = ((block_size_ / kAlign) + 1) * kAlign;
    if (queue_depth_ < 1) queue_depth_ = 1 << 20;  // effectively unbounded
    for (int i = 0; i < num_threads; ++i)
      workers_.emplace_back([this] { worker(); });
  }

  ~AioPool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto &w : workers_) w.join();
  }

  void submit(const char *path, char *buf, long nbytes, long offset,
              bool write, bool trunc = false) override {
    // single_submit runs each request as ONE buffered op (no chunking);
    // opening a direct fd it can never use would waste a syscall pair
    auto req = make_request(path, nbytes, offset, write, trunc,
                            use_odirect_ && !single_submit_, errors_);
    if (!req) return;
    auto chunks = plan_chunks(offset, nbytes, block_size_, single_submit_,
                              req->fd_direct >= 0);
    std::unique_lock<std::mutex> lk(mu_);
    for (const auto &c : chunks) {
      // queue_depth backpressure (libaio iodepth analog)
      space_cv_.wait(lk, [this] {
        return (long)queue_.size() < queue_depth_;
      });
      queue_.push_back(
          Task{req, buf + (c.off - offset), c.len, c.off, write, c.direct});
      pending_.fetch_add(1);
      tasks_total_.fetch_add(1);
      cv_.notify_one();
    }
    lk.unlock();
    if (!overlap_events_) {
      std::unique_lock<std::mutex> dlk(done_mu_);
      done_cv_.wait(dlk, [this] { return pending_.load() == 0; });
    }
  }

  int wait() override {
    std::unique_lock<std::mutex> lk(done_mu_);
    done_cv_.wait(lk, [this] { return pending_.load() == 0; });
    return errors_.exchange(0);
  }

  int pending() const override { return pending_.load(); }
  long odirect_ops() const override { return odirect_ops_.load(); }
  long tasks_total() const override { return tasks_total_.load(); }
  int backend() const override { return 0; }

private:
  void worker() {
    AlignedBuf bounce;
    for (;;) {
      Task t;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        t = std::move(queue_.front());
        queue_.pop_front();
        space_cv_.notify_one();
      }
      if (!run_one(t, bounce)) errors_.fetch_add(1);
      t.req.reset();  // close fds as soon as the last chunk retires
      if (pending_.fetch_sub(1) == 1) {
        std::unique_lock<std::mutex> lk(done_mu_);
        done_cv_.notify_all();
      }
    }
  }

  // per-worker reusable aligned bounce buffer for O_DIRECT chunks
  struct AlignedBuf {
    char *p = nullptr;
    long cap = 0;
    ~AlignedBuf() { free(p); }
    char *get(long n) {
      if (n > cap) {
        free(p);
        if (posix_memalign(reinterpret_cast<void **>(&p), kAlign, n))
          p = nullptr;
        cap = p ? n : 0;
      }
      return p;
    }
  };

  bool run_one(const Task &t, AlignedBuf &bounce) {
    int fd = t.req->fd;
    char *src = t.buf;
    if (t.direct && t.req->fd_direct >= 0) {
      // aligned file span; the USER buffer may still be unaligned, so
      // stage through the worker's aligned bounce buffer
      char *b = bounce.get(t.nbytes);
      if (b != nullptr) {
        fd = t.req->fd_direct;
        src = b;
        if (t.write) memcpy(b, t.buf, t.nbytes);
        odirect_ops_.fetch_add(1);
      }
    }
    long done = 0;
    while (done < t.nbytes) {
      ssize_t n = t.write
          ? pwrite(fd, src + done, t.nbytes - done, t.offset + done)
          : pread(fd, src + done, t.nbytes - done, t.offset + done);
      if (n <= 0) {
        if (fd == t.req->fd_direct) {
          // e.g. EINVAL from a filesystem that accepted the open but
          // rejects direct I/O — retry the whole chunk buffered
          fd = t.req->fd;
          src = t.buf;
          odirect_ops_.fetch_sub(1);
          done = 0;
          continue;
        }
        return false;
      }
      done += n;
    }
    if (!t.write && src != t.buf) memcpy(t.buf, src, t.nbytes);
    return true;
  }

  long block_size_;
  long queue_depth_;
  bool single_submit_;
  bool overlap_events_;
  bool use_odirect_;
  bool stop_;
  std::vector<std::thread> workers_;
  std::deque<Task> queue_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable space_cv_;
  std::mutex done_mu_;
  std::condition_variable done_cv_;
  std::atomic<int> pending_;
  std::atomic<int> errors_;
  std::atomic<long> odirect_ops_;
  std::atomic<long> tasks_total_;
};



// ---------------------------------------------------------------------
// io_uring engine: real kernel queue depth, registered bounce buffers
// (see uring.h for the design notes)
// ---------------------------------------------------------------------
class UringEngine : public Engine {
public:
  UringEngine(long block_size, int queue_depth, int single_submit,
              int overlap_events, int use_odirect, bool *ok)
      : block_size_(block_size), single_submit_(single_submit != 0),
        overlap_events_(overlap_events != 0), use_odirect_(use_odirect != 0),
        pending_(0), errors_(0), odirect_ops_(0), tasks_total_(0) {
    if (block_size_ < 1) block_size_ = 1 << 20;
    if (use_odirect_ && block_size_ % kAlign)
      block_size_ = ((block_size_ / kAlign) + 1) * kAlign;
    if (queue_depth < 2) queue_depth = 2;
    if (queue_depth > 1024) queue_depth = 1024;
    *ok = ring_.init((unsigned)queue_depth);
    if (!*ok) return;
    depth_ = ring_.entries;
    ops_.resize(depth_);
    // descending: free_slots_.back() hands out LOW slots first, which
    // is where the capped pinned bounce pool lives
    for (unsigned i = depth_; i > 0; --i)
      free_slots_.push_back((int)(i - 1));
    if (use_odirect_) {
      // pinned aligned buffers registered once — the fixed-buffer pool
      // O_DIRECT chunks do zero-copy kernel DMA into.  Capped: pinning
      // queue_depth x block_size (up to 1 GB) eagerly would waste pages
      // whenever the filesystem rejects O_DIRECT; chunks landing in
      // slots past the pool simply run buffered
      npinned_ = depth_ < 64 ? depth_ : 64;
      bounce_.resize(npinned_, nullptr);
      std::vector<struct iovec> iov(npinned_);
      bool all = true;
      for (unsigned i = 0; i < npinned_; ++i) {
        if (posix_memalign(reinterpret_cast<void **>(&bounce_[i]), kAlign,
                           block_size_))
          bounce_[i] = nullptr;
        all = all && bounce_[i];
        iov[i].iov_base = bounce_[i];
        iov[i].iov_len = (size_t)block_size_;
      }
      registered_ =
          all && uring::sys_register(ring_.fd, IORING_REGISTER_BUFFERS,
                                     iov.data(), npinned_) == 0;
      if (!registered_) use_odirect_ = false;
    }
    reaper_ = std::thread([this] { reap(); });
  }

  ~UringEngine() override {
    // drain in-flight I/O first: the kernel may still be DMA-ing into
    // the registered bounce buffers and the caller's memory (the thread
    // pool likewise completes its queue before destruction)
    if (reaper_.joinable() && !dead_.load()) wait();
    if (reaper_.joinable()) {
      // the reaper's primary wake channel is stop_ + its bounded poll
      // (it re-checks stop_ at least every poll timeout), so shutdown
      // can never be stranded by a submission failure.  The NOP
      // sentinel below is a best-effort INSTANT wake: if pushing or
      // submitting it fails in any way we just fall through to the
      // bounded-poll path instead of looping on errno forever (the old
      // sentinel-MUST-land loop hung the destructor on any errno
      // outside EINTR/EAGAIN/EBUSY).
      stop_.store(true);
      {
        std::lock_guard<std::mutex> lk(mu_);
        struct io_uring_sqe sqe;
        std::memset(&sqe, 0, sizeof(sqe));
        sqe.opcode = IORING_OP_NOP;
        sqe.user_data = ~0ull;           // stop sentinel
        if (ring_.push(sqe)) {
          for (int tries = 0; tries < 64; ++tries) {
            if (uring::sys_enter(ring_.fd, 1, 0, 0) >= 0) break;
            if (errno != EINTR && errno != EAGAIN && errno != EBUSY)
              break;
          }
        }
      }
      reaper_.join();
    }
    for (char *b : bounce_) free(b);
  }

  void submit(const char *path, char *buf, long nbytes, long offset,
              bool write, bool trunc = false) override {
    if (dead_.load()) {        // ring failed fatally: fail fast, no hang
      errors_.fetch_add(1);
      return;
    }
    auto req = make_request(path, nbytes, offset, write, trunc,
                            use_odirect_ && !single_submit_, errors_);
    if (!req) return;
    auto chunks = plan_chunks(offset, nbytes, block_size_, single_submit_,
                              req->fd_direct >= 0);
    for (const auto &c : chunks) {
      std::unique_lock<std::mutex> lk(mu_);
      slot_cv_.wait(lk, [this] {
        return dead_.load() || !free_slots_.empty();
      });
      if (dead_.load()) {        // ring died mid-request: fail the rest
        errors_.fetch_add(1);
        break;
      }
      int slot = free_slots_.back();
      free_slots_.pop_back();
      UOp &op = ops_[slot];
      op.req = req;
      op.user = buf + (c.off - offset);
      op.len = c.len;
      op.off = c.off;
      op.done = 0;
      op.write = write;
      op.direct = c.direct && registered_ && c.len <= block_size_ &&
                  (unsigned)slot < npinned_;
      pending_.fetch_add(1);
      tasks_total_.fetch_add(1);
      if (op.direct && write) {
        // the slot is exclusively ours: stage the bounce copy OUTSIDE
        // the lock so concurrent submitters/reaper aren't serialized
        // behind a memcpy (op.staging keeps the fatal sweep off it)
        op.staging = true;
        lk.unlock();
        std::memcpy(bounce_[slot], op.user, op.len);
        lk.lock();
        op.staging = false;
      }
      push_locked(slot);
    }
    if (!overlap_events_) {
      std::unique_lock<std::mutex> dlk(done_mu_);
      done_cv_.wait(dlk, [this] { return pending_.load() == 0; });
    }
  }

  int wait() override {
    std::unique_lock<std::mutex> lk(done_mu_);
    done_cv_.wait(lk, [this] { return pending_.load() == 0; });
    return errors_.exchange(0);
  }

  int pending() const override { return pending_.load(); }
  long odirect_ops() const override { return odirect_ops_.load(); }
  long tasks_total() const override { return tasks_total_.load(); }
  int backend() const override { return 1; }

private:
  struct UOp {
    std::shared_ptr<Request> req;
    char *user = nullptr;
    long len = 0, off = 0, done = 0;
    bool write = false;
    bool direct = false;
    bool staging = false;   // claimed, memcpy in progress OUTSIDE mu_ —
  };                        // the fatal sweep must not touch it

  // fill + submit the SQE for ops_[slot]'s remaining span (mu_ held)
  void push_locked(int slot) {
    if (dead_.load()) {
      // nothing was pushed for this span yet, so the slot can't see a
      // ghost completion — retire it normally with an error
      retire_locked(slot, true);
      return;
    }
    UOp &op = ops_[slot];
    struct io_uring_sqe sqe;
    std::memset(&sqe, 0, sizeof(sqe));
    if (op.direct) {
      sqe.opcode = op.write ? IORING_OP_WRITE_FIXED : IORING_OP_READ_FIXED;
      sqe.fd = op.req->fd_direct;
      sqe.addr = (unsigned long long)(bounce_[slot] + op.done);
      sqe.buf_index = (unsigned short)slot;
    } else {
      sqe.opcode = op.write ? IORING_OP_WRITE : IORING_OP_READ;
      sqe.fd = op.req->fd;
      sqe.addr = (unsigned long long)(op.user + op.done);
    }
    long remaining = op.len - op.done;
    if (remaining > (1L << 30)) remaining = 1L << 30;  // sqe.len is u32
    sqe.len = (unsigned)remaining;
    sqe.off = (unsigned long long)(op.off + op.done);
    sqe.user_data = (unsigned long long)slot;
    while (!ring_.push(sqe))   // SQ can lag CQ reaping under bursts
      uring::sys_enter(ring_.fd, 0, 1, IORING_ENTER_GETEVENTS);
    for (int tries = 0;; ++tries) {
      int r = uring::sys_enter(ring_.fd, 1, 0, 0);
      if (r >= 0) return;
      if ((errno == EINTR || errno == EAGAIN || errno == EBUSY) &&
          tries < 1000) {
        // transient submit failure.  Only an op already in flight can
        // post the completion whose reaping frees resources, so a
        // min_complete=1 GETEVENTS here (holding mu_!) would block
        // forever whenever nothing else is pending.  Instead: a BOUNDED
        // poll — woken early by CQ readiness when ops are outstanding
        // (pending_ counts this op too, hence > 1), a pure short
        // backoff when they are not; tries*timeout bounds the total
        // wait before the poison path.
        struct pollfd pfd = {ring_.fd, POLLIN, 0};
        poll(&pfd, 1, pending_.load() > 1 ? 50 : 2);
        continue;
      }
      // fatal: the SQE may or may not ever be consumed later — poison
      // the engine and LEAK the slot (never back on the free list), so
      // a ghost completion can't race a reused slot; account the op as
      // finished so wait() returns with the error, and wake slot
      // waiters so multi-chunk submits observe dead_ instead of
      // blocking forever
      dead_.store(true);
      account_done_locked(slot, true);
      slot_cv_.notify_all();
      return;
    }
  }

  // completion accounting shared by every finish path (mu_ held)
  void account_done_locked(int slot, bool error) {
    UOp &op = ops_[slot];
    if (error) errors_.fetch_add(1);
    op.req.reset();            // close fds when the last chunk retires
    if (pending_.fetch_sub(1) == 1) {
      std::lock_guard<std::mutex> dlk(done_mu_);
      done_cv_.notify_all();
    }
  }

  void retire_locked(int slot, bool error) {
    UOp &op = ops_[slot];
    if (!error && op.direct) {
      if (!op.write) std::memcpy(op.user, bounce_[slot], op.len);
      odirect_ops_.fetch_add(1);
    }
    account_done_locked(slot, error);
    free_slots_.push_back(slot);
    slot_cv_.notify_one();
  }

  void reap() {
    struct io_uring_cqe cqe[64];
    for (;;) {
      int n;
      {
        std::lock_guard<std::mutex> lk(mu_);
        n = ring_.pop(cqe, 64);
      }
      if (n == 0) {
        if (stop_.load()) return;   // shutdown: second wake channel —
        // never depends on a sentinel SQE reaching the kernel
        // bounded CQ wait: the ring fd polls readable when completions
        // are pending, and the timeout re-checks stop_ so a lost
        // wakeup can strand this thread for at most one interval
        struct pollfd pfd = {ring_.fd, POLLIN, 0};
        int r = poll(&pfd, 1, 100);
        if (r < 0 && errno != EINTR) {
          // ring unusable: poison the engine (submits fail fast) and
          // fail everything still pending so wait() returns
          dead_.store(true);
          std::lock_guard<std::mutex> lk(mu_);
          for (unsigned i = 0; i < depth_; ++i)
            if (ops_[i].req && !ops_[i].staging)
              retire_locked((int)i, true);   // staging slots belong to
          slot_cv_.notify_all();             // their submitter, which
          return;                            // sees dead_ in push_locked
        }
        continue;
      }
      std::vector<int> drained;
      std::unique_lock<std::mutex> lk(mu_);
      for (int i = 0; i < n; ++i) {
        if (cqe[i].user_data == ~0ull) return;          // stop sentinel
        int slot = (int)cqe[i].user_data;
        if (slot < 0 || (unsigned)slot >= depth_) continue;
        UOp &op = ops_[slot];
        if (!op.req) continue;     // ghost CQE for a leaked/fatal slot
        long res = (long)cqe[i].res;
        if (res < 0) {
          if (op.direct) {
            // e.g. -EINVAL: fs accepted the open but rejects direct
            // I/O — retry the whole chunk buffered
            op.direct = false;
            op.done = 0;
            push_locked(slot);
          } else {
            retire_locked(slot, true);
          }
          continue;
        }
        if (res == 0) {             // EOF: no progress is possible —
          retire_locked(slot, true);  // error, like the thread pool
          continue;
        }
        op.done += res;
        if (op.done < op.len) {
          if (op.direct && (op.done % kAlign)) {  // unaligned remainder
            op.direct = false;
            op.done = 0;
          }
          push_locked(slot);                      // short op: resubmit
        } else if (op.direct && !op.write) {
          drained.push_back(slot);   // bounce->user copy happens below,
        } else {                     // outside the lock
          retire_locked(slot, false);
        }
      }
      if (!drained.empty()) {
        lk.unlock();
        for (int slot : drained) {
          UOp &op = ops_[slot];      // slot still owned: safe unlocked
          std::memcpy(op.user, bounce_[slot], op.len);
        }
        lk.lock();
        for (int slot : drained) {
          ops_[slot].direct = false;     // copy already done
          retire_locked(slot, false);
          odirect_ops_.fetch_add(1);     // it DID go through O_DIRECT
        }
      }
    }
  }

  long block_size_;
  bool single_submit_, overlap_events_, use_odirect_;
  bool registered_ = false;
  unsigned depth_ = 0;
  unsigned npinned_ = 0;
  uring::Ring ring_;
  std::vector<UOp> ops_;
  std::vector<char *> bounce_;
  std::vector<int> free_slots_;
  std::thread reaper_;
  std::mutex mu_;
  std::condition_variable slot_cv_;
  std::mutex done_mu_;
  std::condition_variable done_cv_;
  std::atomic<int> pending_;
  std::atomic<int> errors_;
  std::atomic<long> odirect_ops_;
  std::atomic<long> tasks_total_;
  std::atomic<bool> dead_{false};
  std::atomic<bool> stop_{false};   // reaper shutdown flag (dtor sets it;
};                                  // the bounded poll observes it)

}  // namespace

extern "C" {

void *aio_create(int num_threads, long block_size) {
  return new AioPool(num_threads, block_size, 0, 0, 1, 0);
}

// full-knob constructor (reference: aio_handle ctor py_ds_aio.cpp:15)
void *aio_create2(int num_threads, long block_size, int queue_depth,
                  int single_submit, int overlap_events, int use_odirect) {
  return new AioPool(num_threads, block_size, queue_depth, single_submit,
                     overlap_events, use_odirect);
}

// backend-selecting constructor: use_uring 1 = io_uring (falls back to
// the thread pool when the kernel/sandbox refuses io_uring_setup),
// 0 = thread pool, -1 = auto (io_uring when available)
void *aio_create3(int num_threads, long block_size, int queue_depth,
                  int single_submit, int overlap_events, int use_odirect,
                  int use_uring) {
  bool want = use_uring == 1 || (use_uring == -1 && uring::available());
  if (want) {
    bool ok = false;
    auto *e = new UringEngine(block_size, queue_depth, single_submit,
                              overlap_events, use_odirect, &ok);
    if (ok) return e;
    delete e;
  }
  return new AioPool(num_threads, block_size, queue_depth, single_submit,
                     overlap_events, use_odirect);
}

int aio_backend(void *h) { return static_cast<Engine *>(h)->backend(); }

int aio_uring_available(void) { return uring::available() ? 1 : 0; }

void aio_destroy(void *h) { delete static_cast<Engine *>(h); }

// async chunked read/write; call aio_wait to drain
void aio_pread(void *h, const char *path, void *buf, long nbytes,
               long offset) {
  static_cast<Engine *>(h)->submit(path, static_cast<char *>(buf), nbytes,
                                   offset, false);
}

void aio_pwrite(void *h, const char *path, const void *buf, long nbytes,
                long offset) {
  static_cast<Engine *>(h)->submit(
      path, const_cast<char *>(static_cast<const char *>(buf)), nbytes,
      offset, true);
}

// full-file rewrite: truncates to offset+nbytes before queueing the chunks
void aio_pwrite_trunc(void *h, const char *path, const void *buf, long nbytes,
                      long offset) {
  static_cast<Engine *>(h)->submit(
      path, const_cast<char *>(static_cast<const char *>(buf)), nbytes,
      offset, true, true);
}

int aio_wait(void *h) { return static_cast<Engine *>(h)->wait(); }

int aio_pending(void *h) { return static_cast<Engine *>(h)->pending(); }

// observability: chunks that actually went through O_DIRECT / total chunks
long aio_odirect_ops(void *h) {
  return static_cast<Engine *>(h)->odirect_ops();
}
long aio_tasks_total(void *h) {
  return static_cast<Engine *>(h)->tasks_total();
}

// synchronous helpers (reference: aio_read/aio_write free functions)
int aio_sync_pread(void *h, const char *path, void *buf, long nbytes,
                   long offset) {
  aio_pread(h, path, buf, nbytes, offset);
  return aio_wait(h);
}

int aio_sync_pwrite(void *h, const char *path, const void *buf, long nbytes,
                    long offset) {
  aio_pwrite(h, path, buf, nbytes, offset);
  return aio_wait(h);
}

}  // extern "C"
