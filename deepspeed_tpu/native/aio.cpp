// Async file I/O thread pool — the DeepNVMe/aio analog.
//
// TPU-native counterpart of the reference's csrc/aio library
// (deepspeed_aio_thread.cpp thread pool, py_ds_aio.cpp bindings,
// deepspeed_pin_tensor.cpp pinned buffers): a C++ worker pool doing
// chunked pread/pwrite against NVMe-backed files, exposed through a
// plain C ABI consumed via ctypes (no pybind11 in this image).
//
// Requests are split into block_size chunks fanned across the pool, so a
// single large tensor read/write saturates multiple NVMe queues exactly
// like the reference's parallel pread/pwrite (csrc/aio/py_lib
// deepspeed_py_aio_handle.cpp).  Each request opens its file once; the
// fds are shared by all of its chunks and closed when the last chunk
// retires.
//
// The reference handle's knobs are consumed with these semantics
// (csrc/aio/py_lib/deepspeed_py_aio_handle.cpp):
//   block_size    — chunk granularity (parallelism unit).
//   queue_depth   — max chunks in flight; submission applies
//                   backpressure beyond it (libaio iodepth analog).
//   single_submit — one op per request instead of chunking (the
//                   reference's non-batched submit mode).
//   overlap_events— when false, each submit drains before returning
//                   (no submit/complete overlap).
//   use_odirect   — page-cache bypass: 4096-aligned spans go through an
//                   O_DIRECT fd via pooled aligned bounce buffers
//                   (numpy callers guarantee no alignment); unaligned
//                   head/tail spans use a buffered fd.  Filesystems
//                   without O_DIRECT (tmpfs) fall back silently;
//                   aio_odirect_ops reports what actually happened.

#ifndef _GNU_SOURCE
#define _GNU_SOURCE  // O_DIRECT
#endif

#include <atomic>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr long kAlign = 4096;

// One submitted read/write; owns the fds for all its chunks.
struct Request {
  int fd = -1;         // buffered
  int fd_direct = -1;  // O_DIRECT (or -1: unsupported / disabled)
  Request() = default;
  Request(const Request &) = delete;
  Request &operator=(const Request &) = delete;
  ~Request() {
    if (fd >= 0) close(fd);
    if (fd_direct >= 0) close(fd_direct);
  }
};

struct Task {
  std::shared_ptr<Request> req;
  char *buf;
  long nbytes;
  long offset;
  bool write;
  bool direct;  // aligned span eligible for the O_DIRECT fd
};

class AioPool {
public:
  AioPool(int num_threads, long block_size, int queue_depth,
          int single_submit, int overlap_events, int use_odirect)
      : block_size_(block_size), queue_depth_(queue_depth),
        single_submit_(single_submit != 0),
        overlap_events_(overlap_events != 0),
        use_odirect_(use_odirect != 0), stop_(false), pending_(0),
        errors_(0), odirect_ops_(0), tasks_total_(0) {
    if (num_threads < 1) num_threads = 1;
    if (block_size_ < 1) block_size_ = 1 << 20;
    // O_DIRECT chunks must stay 4096-multiples
    if (use_odirect_ && block_size_ % kAlign)
      block_size_ = ((block_size_ / kAlign) + 1) * kAlign;
    if (queue_depth_ < 1) queue_depth_ = 1 << 20;  // effectively unbounded
    for (int i = 0; i < num_threads; ++i)
      workers_.emplace_back([this] { worker(); });
  }

  ~AioPool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto &w : workers_) w.join();
  }

  void submit(const char *path, char *buf, long nbytes, long offset,
              bool write, bool trunc = false) {
    int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    auto req = std::make_shared<Request>();
    req->fd = open(path, flags, 0644);
    if (req->fd < 0) {
      errors_.fetch_add(1);
      return;
    }
    // single_submit runs each request as ONE buffered op (no chunking);
    // opening a direct fd it can never use would waste a syscall pair
    if (use_odirect_ && !single_submit_)
      req->fd_direct = open(path, flags | O_DIRECT, 0644);  // may fail: ok
    // opt-in for full-file rewrites: a smaller rewrite must not leave a
    // stale tail from a previous, larger request (a reader trusting file
    // size would see old data).  Never implicit — partial-write users of
    // the public handle rely on surrounding bytes surviving.
    if (write && trunc) {
      if (ftruncate(req->fd, offset + nbytes) != 0) errors_.fetch_add(1);
    }
    long end = offset + nbytes;
    // the file span [offset, end) splits into an unaligned head, an
    // aligned body (O_DIRECT-eligible, chunked), and an unaligned tail
    long body_lo = offset, body_hi = end;
    if (req->fd_direct >= 0) {
      body_lo = (offset + kAlign - 1) / kAlign * kAlign;
      body_hi = end / kAlign * kAlign;
      if (body_hi <= body_lo) { body_lo = body_hi = offset; }
    }
    std::unique_lock<std::mutex> lk(mu_);
    auto push = [&](long off, long len, bool direct) {
      if (len <= 0) return;
      // queue_depth backpressure (libaio iodepth analog)
      space_cv_.wait(lk, [this] {
        return (long)queue_.size() < queue_depth_;
      });
      queue_.push_back(
          Task{req, buf + (off - offset), len, off, write, direct});
      pending_.fetch_add(1);
      tasks_total_.fetch_add(1);
      cv_.notify_one();
    };
    if (single_submit_ || req->fd_direct < 0) {
      // one op per request (single_submit) / plain chunking (no direct)
      if (single_submit_) {
        push(offset, nbytes, false);
      } else {
        for (long done = 0; done < nbytes; done += block_size_)
          push(offset + done, std::min(block_size_, nbytes - done), false);
      }
    } else {
      push(offset, body_lo - offset, false);            // head
      for (long off = body_lo; off < body_hi; off += block_size_)
        push(off, std::min(block_size_, body_hi - off), true);
      push(body_hi, end - body_hi, false);              // tail
    }
    lk.unlock();
    if (!overlap_events_) {
      std::unique_lock<std::mutex> dlk(done_mu_);
      done_cv_.wait(dlk, [this] { return pending_.load() == 0; });
    }
  }

  int wait() {
    std::unique_lock<std::mutex> lk(done_mu_);
    done_cv_.wait(lk, [this] { return pending_.load() == 0; });
    return errors_.exchange(0);
  }

  int pending() const { return pending_.load(); }
  long odirect_ops() const { return odirect_ops_.load(); }
  long tasks_total() const { return tasks_total_.load(); }

private:
  void worker() {
    AlignedBuf bounce;
    for (;;) {
      Task t;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        t = std::move(queue_.front());
        queue_.pop_front();
        space_cv_.notify_one();
      }
      if (!run_one(t, bounce)) errors_.fetch_add(1);
      t.req.reset();  // close fds as soon as the last chunk retires
      if (pending_.fetch_sub(1) == 1) {
        std::unique_lock<std::mutex> lk(done_mu_);
        done_cv_.notify_all();
      }
    }
  }

  // per-worker reusable aligned bounce buffer for O_DIRECT chunks
  struct AlignedBuf {
    char *p = nullptr;
    long cap = 0;
    ~AlignedBuf() { free(p); }
    char *get(long n) {
      if (n > cap) {
        free(p);
        if (posix_memalign(reinterpret_cast<void **>(&p), kAlign, n))
          p = nullptr;
        cap = p ? n : 0;
      }
      return p;
    }
  };

  bool run_one(const Task &t, AlignedBuf &bounce) {
    int fd = t.req->fd;
    char *src = t.buf;
    if (t.direct && t.req->fd_direct >= 0) {
      // aligned file span; the USER buffer may still be unaligned, so
      // stage through the worker's aligned bounce buffer
      char *b = bounce.get(t.nbytes);
      if (b != nullptr) {
        fd = t.req->fd_direct;
        src = b;
        if (t.write) memcpy(b, t.buf, t.nbytes);
        odirect_ops_.fetch_add(1);
      }
    }
    long done = 0;
    while (done < t.nbytes) {
      ssize_t n = t.write
          ? pwrite(fd, src + done, t.nbytes - done, t.offset + done)
          : pread(fd, src + done, t.nbytes - done, t.offset + done);
      if (n <= 0) {
        if (fd == t.req->fd_direct) {
          // e.g. EINVAL from a filesystem that accepted the open but
          // rejects direct I/O — retry the whole chunk buffered
          fd = t.req->fd;
          src = t.buf;
          odirect_ops_.fetch_sub(1);
          done = 0;
          continue;
        }
        return false;
      }
      done += n;
    }
    if (!t.write && src != t.buf) memcpy(t.buf, src, t.nbytes);
    return true;
  }

  long block_size_;
  long queue_depth_;
  bool single_submit_;
  bool overlap_events_;
  bool use_odirect_;
  bool stop_;
  std::vector<std::thread> workers_;
  std::deque<Task> queue_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable space_cv_;
  std::mutex done_mu_;
  std::condition_variable done_cv_;
  std::atomic<int> pending_;
  std::atomic<int> errors_;
  std::atomic<long> odirect_ops_;
  std::atomic<long> tasks_total_;
};

}  // namespace

extern "C" {

void *aio_create(int num_threads, long block_size) {
  return new AioPool(num_threads, block_size, 0, 0, 1, 0);
}

// full-knob constructor (reference: aio_handle ctor py_ds_aio.cpp:15)
void *aio_create2(int num_threads, long block_size, int queue_depth,
                  int single_submit, int overlap_events, int use_odirect) {
  return new AioPool(num_threads, block_size, queue_depth, single_submit,
                     overlap_events, use_odirect);
}

void aio_destroy(void *h) { delete static_cast<AioPool *>(h); }

// async chunked read/write; call aio_wait to drain
void aio_pread(void *h, const char *path, void *buf, long nbytes,
               long offset) {
  static_cast<AioPool *>(h)->submit(path, static_cast<char *>(buf), nbytes,
                                    offset, false);
}

void aio_pwrite(void *h, const char *path, const void *buf, long nbytes,
                long offset) {
  static_cast<AioPool *>(h)->submit(
      path, const_cast<char *>(static_cast<const char *>(buf)), nbytes,
      offset, true);
}

// full-file rewrite: truncates to offset+nbytes before queueing the chunks
void aio_pwrite_trunc(void *h, const char *path, const void *buf, long nbytes,
                      long offset) {
  static_cast<AioPool *>(h)->submit(
      path, const_cast<char *>(static_cast<const char *>(buf)), nbytes,
      offset, true, true);
}

int aio_wait(void *h) { return static_cast<AioPool *>(h)->wait(); }

int aio_pending(void *h) { return static_cast<AioPool *>(h)->pending(); }

// observability: chunks that actually went through O_DIRECT / total chunks
long aio_odirect_ops(void *h) {
  return static_cast<AioPool *>(h)->odirect_ops();
}
long aio_tasks_total(void *h) {
  return static_cast<AioPool *>(h)->tasks_total();
}

// synchronous helpers (reference: aio_read/aio_write free functions)
int aio_sync_pread(void *h, const char *path, void *buf, long nbytes,
                   long offset) {
  aio_pread(h, path, buf, nbytes, offset);
  return aio_wait(h);
}

int aio_sync_pwrite(void *h, const char *path, const void *buf, long nbytes,
                    long offset) {
  aio_pwrite(h, path, buf, nbytes, offset);
  return aio_wait(h);
}

}  // extern "C"
