// Async file I/O thread pool — the DeepNVMe/aio analog.
//
// TPU-native counterpart of the reference's csrc/aio library
// (deepspeed_aio_thread.cpp thread pool, py_ds_aio.cpp bindings,
// deepspeed_pin_tensor.cpp pinned buffers): a C++ worker pool doing
// chunked pread/pwrite against NVMe-backed files, exposed through a
// plain C ABI consumed via ctypes (no pybind11 in this image).
//
// Requests are split into block_size chunks fanned across the pool, so a
// single large tensor read/write saturates multiple NVMe queues exactly
// like the reference's parallel pread/pwrite (csrc/aio/py_lib
// deepspeed_py_aio_handle.cpp).  Each request opens its file once; the fd
// is shared by all of its chunks and closed when the last chunk retires.
// I/O goes through the page cache (no O_DIRECT: numpy source buffers
// carry no alignment guarantee).

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// One submitted read/write; owns the fd for all its chunks.
struct Request {
  int fd = -1;
  Request() = default;
  Request(const Request &) = delete;
  Request &operator=(const Request &) = delete;
  ~Request() {
    if (fd >= 0) close(fd);
  }
};

struct Task {
  std::shared_ptr<Request> req;
  char *buf;
  long nbytes;
  long offset;
  bool write;
};

class AioPool {
public:
  AioPool(int num_threads, long block_size)
      : block_size_(block_size), stop_(false), pending_(0), errors_(0) {
    if (num_threads < 1) num_threads = 1;
    if (block_size_ < 1) block_size_ = 1 << 20;
    for (int i = 0; i < num_threads; ++i)
      workers_.emplace_back([this] { worker(); });
  }

  ~AioPool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto &w : workers_) w.join();
  }

  void submit(const char *path, char *buf, long nbytes, long offset,
              bool write, bool trunc = false) {
    int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = open(path, flags, 0644);
    if (fd < 0) {
      errors_.fetch_add(1);
      return;
    }
    // opt-in for full-file rewrites: a smaller rewrite must not leave a
    // stale tail from a previous, larger request (a reader trusting file
    // size would see old data).  Never implicit — partial-write users of
    // the public handle rely on surrounding bytes surviving.
    if (write && trunc) {
      if (ftruncate(fd, offset + nbytes) != 0) errors_.fetch_add(1);
    }
    auto req = std::make_shared<Request>();
    req->fd = fd;
    // split into block-sized chunks for parallelism
    long done = 0;
    std::unique_lock<std::mutex> lk(mu_);
    while (done < nbytes) {
      long n = std::min(block_size_, nbytes - done);
      queue_.push_back(Task{req, buf + done, n, offset + done, write});
      pending_.fetch_add(1);
      done += n;
    }
    cv_.notify_all();
  }

  int wait() {
    std::unique_lock<std::mutex> lk(done_mu_);
    done_cv_.wait(lk, [this] { return pending_.load() == 0; });
    return errors_.exchange(0);
  }

  int pending() const { return pending_.load(); }

private:
  void worker() {
    for (;;) {
      Task t;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        t = std::move(queue_.front());
        queue_.pop_front();
      }
      if (!run_one(t)) errors_.fetch_add(1);
      t.req.reset();  // close fd as soon as the last chunk retires
      if (pending_.fetch_sub(1) == 1) {
        std::unique_lock<std::mutex> lk(done_mu_);
        done_cv_.notify_all();
      }
    }
  }

  bool run_one(const Task &t) {
    long done = 0;
    while (done < t.nbytes) {
      ssize_t n = t.write
          ? pwrite(t.req->fd, t.buf + done, t.nbytes - done, t.offset + done)
          : pread(t.req->fd, t.buf + done, t.nbytes - done, t.offset + done);
      if (n <= 0) return false;
      done += n;
    }
    return true;
  }

  long block_size_;
  bool stop_;
  std::vector<std::thread> workers_;
  std::deque<Task> queue_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::mutex done_mu_;
  std::condition_variable done_cv_;
  std::atomic<int> pending_;
  std::atomic<int> errors_;
};

}  // namespace

extern "C" {

void *aio_create(int num_threads, long block_size) {
  return new AioPool(num_threads, block_size);
}

void aio_destroy(void *h) { delete static_cast<AioPool *>(h); }

// async chunked read/write; call aio_wait to drain
void aio_pread(void *h, const char *path, void *buf, long nbytes,
               long offset) {
  static_cast<AioPool *>(h)->submit(path, static_cast<char *>(buf), nbytes,
                                    offset, false);
}

void aio_pwrite(void *h, const char *path, const void *buf, long nbytes,
                long offset) {
  static_cast<AioPool *>(h)->submit(
      path, const_cast<char *>(static_cast<const char *>(buf)), nbytes,
      offset, true);
}

// full-file rewrite: truncates to offset+nbytes before queueing the chunks
void aio_pwrite_trunc(void *h, const char *path, const void *buf, long nbytes,
                      long offset) {
  static_cast<AioPool *>(h)->submit(
      path, const_cast<char *>(static_cast<const char *>(buf)), nbytes,
      offset, true, true);
}

int aio_wait(void *h) { return static_cast<AioPool *>(h)->wait(); }

int aio_pending(void *h) { return static_cast<AioPool *>(h)->pending(); }

// synchronous helpers (reference: aio_read/aio_write free functions)
int aio_sync_pread(void *h, const char *path, void *buf, long nbytes,
                   long offset) {
  aio_pread(h, path, buf, nbytes, offset);
  return aio_wait(h);
}

int aio_sync_pwrite(void *h, const char *path, const void *buf, long nbytes,
                    long offset) {
  aio_pwrite(h, path, buf, nbytes, offset);
  return aio_wait(h);
}

}  // extern "C"
