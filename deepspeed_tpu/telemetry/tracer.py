"""Host-side span tracing for the serving and training loops.

A :class:`SpanTracer` is a preallocated ring buffer of (name, track,
begin, duration) records on the monotonic ``time.perf_counter_ns``
clock.  It exists to make the pipelined serving loop's overlap structure
*visible*: each pipeline stage (schedule / stage / dispatch / wait /
readback) records onto its own track, so the exported Chrome trace shows
dispatch-ahead steps overlapping device compute exactly as they ran.

Design constraints (docs/OBSERVABILITY.md):

* **Near-zero cost when disabled** — every entry point checks
  ``self.enabled`` first and returns a shared no-op; a disabled tracer
  never reads the clock and never allocates.
* **Bounded memory** — the ring holds ``capacity`` records; older spans
  are overwritten (``dropped`` counts them), so a long-lived serving
  engine can leave tracing on without growing.
* **No device work** — the tracer only ever touches host integers.
  Recording a span must never force a device sync (enforced tree-wide
  by tpulint's ``telemetry-hotpath`` rule: telemetry calls are banned
  inside jit-traced functions).

Two export formats:

* :meth:`export_chrome_trace` — Chrome trace-event JSON (load in
  Perfetto / ``chrome://tracing``), one thread-track per stage.
* :meth:`export_jsonl` — one JSON object per span, for ad-hoc tooling.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional


class _NoopSpan:
    """Shared do-nothing context manager returned while disabled."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    """Context manager that records one span on exit."""
    __slots__ = ("_tracer", "_name", "_track", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str,
                 track: Optional[str], args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._track = track
        self._args = args

    def __enter__(self):
        tr = self._tracer
        tr._depth += 1
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        tr = self._tracer
        tr._depth -= 1
        tr._push(self._name, self._track or self._name, self._t0,
                 t1 - self._t0, tr._depth, self._args)
        return False


class SpanTracer:
    """Preallocated-ring span recorder on ``perf_counter_ns``.

    Spans can be recorded two ways:

    * ``with tracer.span("prefix_match", track="schedule"):`` — the
      context manager reads the clock at enter/exit; nesting is tracked
      (``depth``) so tooling can reconstruct the stack without relying
      on time containment alone.
    * ``tracer.record("schedule", t0, t1, track="schedule")`` — explicit
      ``time.perf_counter()`` (float seconds) endpoints.  The serving
      loop uses this form to reuse the timestamps it already takes for
      ``engine.timings``, so tracing adds no extra clock reads on the
      hot path.
    """

    def __init__(self, capacity: int = 1 << 16, enabled: bool = False):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.enabled = bool(enabled)
        # the ring is allocated lazily on the first recorded span, so a
        # never-enabled tracer (every engine constructs one) costs one
        # None attribute, not a capacity-sized list
        self._buf: Optional[List[Optional[tuple]]] = None
        self._cursor = 0
        self._total = 0            # spans ever recorded (dropped included)
        self._depth = 0            # live context-manager nesting depth

    # ------------------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self._buf = None
        self._cursor = 0
        self._total = 0
        self._depth = 0

    @property
    def dropped(self) -> int:
        """Spans overwritten by ring wraparound."""
        return max(0, self._total - self.capacity)

    def __len__(self) -> int:
        return min(self._total, self.capacity)

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------
    def _push(self, name: str, track: str, ts_ns: int, dur_ns: int,
              depth: int, args: Optional[Dict[str, Any]]) -> None:
        buf = self._buf
        if buf is None:
            buf = self._buf = [None] * self.capacity
        i = self._cursor
        buf[i] = (name, track, ts_ns, dur_ns, depth, args)
        self._cursor = (i + 1) % self.capacity
        self._total += 1

    def span(self, name: str, track: Optional[str] = None, **args):
        """Context manager timing its body; no-op while disabled."""
        if not self.enabled:
            return _NOOP_SPAN
        return _Span(self, name, track, args or None)

    def record(self, name: str, t0: float, t1: float,
               track: Optional[str] = None, depth: int = 0,
               **args) -> None:
        """Record a span from explicit ``time.perf_counter()`` endpoints
        (float seconds — the same clock as ``perf_counter_ns``)."""
        if not self.enabled:
            return
        ts = int(t0 * 1e9)
        self._push(name, track or name, ts, max(0, int(t1 * 1e9) - ts),
                   depth, args or None)

    def instant(self, name: str, track: Optional[str] = None,
                **args) -> None:
        """Zero-duration marker (request arrivals, evictions, ...)."""
        if not self.enabled:
            return
        self._push(name, track or name, time.perf_counter_ns(), -1,
                   self._depth, args or None)

    # ------------------------------------------------------------------
    # export
    # ------------------------------------------------------------------
    def events(self, since_ns: Optional[int] = None
               ) -> List[Dict[str, Any]]:
        """Recorded spans, oldest first (wraparound-corrected).
        ``since_ns`` keeps only spans beginning at/after that
        ``perf_counter_ns`` instant — the capture-window export
        (telemetry/profiler.py) uses it to emit just the window."""
        if self._buf is None:
            return []
        n = len(self)
        start = (self._cursor - n) % self.capacity
        out = []
        for k in range(n):
            name, track, ts_ns, dur_ns, depth, args = \
                self._buf[(start + k) % self.capacity]
            if since_ns is not None and ts_ns < since_ns:
                continue
            ev: Dict[str, Any] = {"name": name, "track": track,
                                  "ts_ns": ts_ns, "depth": depth}
            if dur_ns >= 0:
                ev["dur_ns"] = dur_ns
            else:
                ev["instant"] = True
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def chrome_trace(self, process_name: str = "deepspeed_tpu",
                     since_ns: Optional[int] = None) -> Dict:
        """Chrome trace-event JSON object (the ``traceEvents`` array
        format Perfetto and chrome://tracing load).  One tid per track,
        named via thread_name metadata, so each pipeline stage renders
        as its own horizontal track and the dispatch-ahead overlap is
        visually inspectable.  ``since_ns`` restricts the export to
        spans beginning at/after that instant (capture windows)."""
        tids: Dict[str, int] = {}
        trace_events: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": 1, "tid": 0,
            "args": {"name": process_name}}]
        body: List[Dict[str, Any]] = []
        for ev in self.events(since_ns=since_ns):
            track = ev["track"]
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = len(tids) + 1
                trace_events.append({
                    "name": "thread_name", "ph": "M", "pid": 1,
                    "tid": tid, "args": {"name": track}})
                # stable top-to-bottom track order in the viewer
                trace_events.append({
                    "name": "thread_sort_index", "ph": "M", "pid": 1,
                    "tid": tid, "args": {"sort_index": tid}})
            rec: Dict[str, Any] = {
                "name": ev["name"], "pid": 1, "tid": tid,
                "ts": ev["ts_ns"] / 1e3,              # microseconds
                "ph": "i" if ev.get("instant") else "X"}
            if not ev.get("instant"):
                rec["dur"] = ev["dur_ns"] / 1e3
            else:
                rec["s"] = "t"                        # thread-scoped
            if ev.get("args"):
                rec["args"] = ev["args"]
            body.append(rec)
        return {"traceEvents": trace_events + body,
                "displayTimeUnit": "ms",
                "otherData": {"dropped_spans": self.dropped}}

    def export_chrome_trace(self, path: str,
                            process_name: str = "deepspeed_tpu") -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(process_name), f)
        return path

    def export_jsonl(self, path: str) -> str:
        with open(path, "w") as f:
            for ev in self.events():
                f.write(json.dumps(ev) + "\n")
        return path
