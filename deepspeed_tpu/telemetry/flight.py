"""Post-mortem flight recorder (docs/OBSERVABILITY.md "Device &
compiler telemetry").

PR 8's failure layer can declare an engine dead, quarantine a poison
request, or abandon a hung dispatch — and until now left NO artifact to
debug from: the spans, counters, and request records died with the
process.  The flight recorder is the bounded black box: a ring of
failure/health events the engine notes as they happen, plus a
``snapshot`` assembled on demand from the live telemetry objects —
last-N spans, the full metrics snapshot, recent request statuses, the
config fingerprint (so the artifact says WHICH engine defaults
produced it), and the engine's health/failure state.

Dump triggers (wired in ``inference/engine.py``):

* automatically, when ``FailureConfig.flight_dir`` is set — on watchdog
  expiry, on the fatal transition to engine-dead, and on the first
  healthy->degraded transition of a failure window;
* on demand, via ``engine.debug_dump(path)`` (always available, no
  config needed).

Everything here is host-side dict/list work on the failure path — the
happy path never touches the recorder beyond its construction, and the
event ring is bounded, so a long-lived engine cannot grow it.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from ..utils.logging import logger

FLIGHT_SCHEMA_VERSION = 1

# the snapshot's required top-level keys — validated by the chaos
# harness on every auto-dump and by tests/test_device_telemetry.py
FLIGHT_REQUIRED_KEYS = ("version", "reason", "time", "fingerprint",
                        "health", "steps", "metrics", "spans",
                        "requests", "events")


def config_fingerprint() -> Dict[str, str]:
    """Engine version + a short digest over the serving/overload/
    failure config DEFAULTS — the knobs whose defaults PRs keep
    evolving.  Two artifacts (BENCH JSONs, flight dumps) with different
    hashes came from different default engines; compare only within a
    hash.  Shared by ``bench.py`` (the BENCH JSON fingerprint) and the
    flight recorder, so the bench trajectory and the post-mortems are
    joinable on the same key."""
    import dataclasses
    import hashlib

    from .. import __version__
    from ..inference import (FailureConfig, InferenceConfig,
                             OverloadConfig)

    blob = json.dumps(
        {cls.__name__: {f.name: repr(getattr(cls(), f.name))
                        for f in dataclasses.fields(cls)
                        if f.name not in ("overload", "failure")}
         for cls in (InferenceConfig, OverloadConfig, FailureConfig)},
        sort_keys=True)
    return {"engine_version": __version__,
            "config_hash": hashlib.blake2b(
                blob.encode(), digest_size=8).hexdigest()}


class FlightRecorder:
    """Bounded black box for one engine.

    ``note(kind, **info)`` appends one event to the ring (failure
    verdicts, health transitions, dump records — the failure path's
    breadcrumbs); ``snapshot(...)`` assembles the full artifact;
    ``dump(path, ...)`` writes it as JSON and returns the path."""

    def __init__(self, capacity: int = 128, span_tail: int = 256):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.span_tail = span_tail
        self._events: Deque[Dict[str, Any]] = deque(maxlen=capacity)
        self.dumps = 0             # artifacts written by this recorder

    def note(self, kind: str, **info) -> None:
        """Record one breadcrumb (failure-path only — never per-step).
        The wall-clock stamp is deliberate: post-mortems are read next
        to logs and other hosts' artifacts, where monotonic clocks mean
        nothing."""
        self._events.append({"kind": kind, "time": time.time(), **info})

    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()

    # ------------------------------------------------------------------
    def snapshot(self, reason: str, metrics=None, tracer=None,
                 requests=None, health: Optional[Dict] = None,
                 steps: int = 0,
                 extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Assemble the black-box artifact from the live telemetry
        objects (each optional — a partial engine still dumps what it
        has): the last ``span_tail`` spans, the full registry snapshot,
        the most recent request records (ring-bounded by the tracker
        already), and the event breadcrumbs."""
        spans: List[Dict[str, Any]] = []
        if tracer is not None:
            spans = tracer.events()[-self.span_tail:]
        reqs: List[Dict[str, Any]] = []
        if requests is not None:
            reqs = [r.as_dict() for r in requests.records()]
        snap: Dict[str, Any] = {
            "version": FLIGHT_SCHEMA_VERSION,
            "reason": reason,
            "time": time.time(),
            "fingerprint": config_fingerprint(),
            "health": health if health is not None else {},
            "steps": int(steps),
            "metrics": metrics.snapshot() if metrics is not None else {},
            "spans": spans,
            "requests": reqs,
            "events": self.events(),
        }
        if extra:
            snap.update(extra)
        return snap

    def dump(self, path: str, reason: str,
             snap: Optional[Dict[str, Any]] = None, **kw) -> str:
        """Write :meth:`snapshot` (or a prebuilt ``snap``) to ``path``
        as JSON.  Best-effort by design: a post-mortem writer must
        never turn a degraded engine into a crashed one — I/O failures
        log and return the path unwritten."""
        if snap is None:
            snap = self.snapshot(reason, **kw)
        try:
            with open(path, "w") as f:
                json.dump(snap, f)
            self.dumps += 1
        except OSError as e:
            logger.warning("flight recorder: cannot write %s (%s)",
                           path, e)
        return path


def validate_flight_dump(snap: Dict[str, Any]) -> List[str]:
    """Schema check for one flight artifact (loaded JSON): returns the
    list of violations, empty when valid — the chaos harness asserts
    emptiness on every auto-dump it finds."""
    problems = []
    for k in FLIGHT_REQUIRED_KEYS:
        if k not in snap:
            problems.append(f"missing key {k!r}")
    if snap.get("version") != FLIGHT_SCHEMA_VERSION:
        problems.append(f"version {snap.get('version')!r} != "
                        f"{FLIGHT_SCHEMA_VERSION}")
    fp = snap.get("fingerprint")
    if not (isinstance(fp, dict) and "engine_version" in fp
            and "config_hash" in fp):
        problems.append("fingerprint missing engine_version/config_hash")
    if not isinstance(snap.get("metrics"), dict):
        problems.append("metrics is not a dict")
    for k in ("spans", "requests", "events"):
        if not isinstance(snap.get(k), list):
            problems.append(f"{k} is not a list")
    return problems
