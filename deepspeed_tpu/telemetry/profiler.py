"""Deep-capture windows: bounded ``jax.profiler`` device traces armed
around the next N engine steps (docs/OBSERVABILITY.md "Anomaly
detection & deep capture").

This module is THE gated seam for profiler session control on serving
paths (tpulint's ``profiler-capture`` rule bans direct
``jax.profiler.start_trace``/``stop_trace`` calls inside
serving-loop-marked methods): the engines hold one
:class:`ProfilerCapture` and call ``begin()`` / ``end_step()`` at their
existing step boundaries, and everything session-shaped — the device
trace, the host span window, the clock anchor that lets
``tools/tracemerge.py`` put both on one Perfetto timeline — happens
here, once, bounded.

A capture window produces one directory::

    <out_dir>/capture_<seq>_<reason>/
        meta.json          clock anchor (perf_ns <-> epoch_ns at start),
                           step/sid range, reason, profiler presence
        host_trace.json    Chrome trace of the window's host spans
                           (SpanTracer, force-enabled for the window)
        device/            jax.profiler log dir (plugins/profile/...,
                           xplane.pb + trace.json.gz) — ABSENT when the
                           backend/build has no profiler support
        flight.json        the engine's flight-recorder dump (written
                           by the engine when the window completes)

Degradation is loud but absent: a missing/busy profiler logs a warning
and the window still completes with host spans + meta (tracemerge then
emits a host-only timeline and says so).  Only one jax profiler session
can exist per process — a module-level owner flag keeps two engines
from racing ``start_trace``.

No JAX at import time (the telemetry/ contract); ``jax.profiler`` is
imported inside the capture calls only, and only while a window is
actually starting — a disabled engine never touches this module.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import logger

# process-wide session owner: jax.profiler supports ONE active trace
_TRACE_OWNER: List[object] = []


def profiler_available() -> bool:
    """Whether this build exposes ``jax.profiler.start_trace`` (pure
    presence probe — no session is started)."""
    try:
        import jax.profiler
        return hasattr(jax.profiler, "start_trace") \
            and hasattr(jax.profiler, "stop_trace")
    except Exception as e:
        logger.warning("jax.profiler unavailable: %r", e)
        return False


class ProfilerCapture:
    """One engine's capture-window manager.

    States: idle -> ``armed`` (``arm()``) -> ``active`` (``begin()``,
    called by the engine right before its next dispatch) -> idle again
    when ``end_step()`` counts the window down (or ``finish_now()``
    aborts it early on a step failure).  One window at a time; anomaly-
    armed windows (``budgeted=True``) draw from ``max_captures`` until
    ``reset_budget()`` rearms it, explicit ``engine.capture()`` windows
    do not."""

    def __init__(self, out_dir: str, tracer=None,
                 max_captures: Optional[int] = 2):
        self.out_dir = out_dir
        self.tracer = tracer
        self.max_captures = max_captures
        self.captures: List[str] = []     # finished capture dirs
        self._seq = 0
        self._budget_used = 0
        self._armed: Optional[Dict[str, Any]] = None
        self._active: Optional[Dict[str, Any]] = None
        self._warned_unavailable = False

    # ------------------------------------------------------------------
    @property
    def armed(self) -> bool:
        return self._armed is not None

    @property
    def active(self) -> bool:
        return self._active is not None

    def budget_left(self) -> Optional[int]:
        if self.max_captures is None:
            return None
        return max(0, self.max_captures - self._budget_used)

    def reset_budget(self) -> None:
        """Rearm the anomaly-capture budget (``engine.reset_metrics``)."""
        self._budget_used = 0

    # ------------------------------------------------------------------
    def arm(self, steps: int, reason: str = "manual",
            budgeted: bool = False) -> Optional[str]:
        """Schedule a capture of the next ``steps`` engine steps;
        returns the capture directory path, or None when refused (a
        window is already armed/active, or the anomaly budget is
        spent).  Nothing starts until the engine's next step boundary
        calls :meth:`begin`."""
        if self._armed is not None or self._active is not None:
            logger.debug("capture %r refused: a window is already %s",
                         reason, "active" if self._active else "armed")
            return None
        if budgeted:
            left = self.budget_left()
            if left is not None and left <= 0:
                logger.debug("capture %r refused: budget exhausted "
                             "(max_captures=%s)", reason,
                             self.max_captures)
                return None
            self._budget_used += 1
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:48]
        cdir = os.path.join(self.out_dir,
                            f"capture_{self._seq:03d}_{safe}")
        self._seq += 1
        self._armed = {"steps": max(1, int(steps)), "reason": reason,
                       "dir": cdir, "budgeted": budgeted}
        return cdir

    def begin(self, sid: Optional[int] = None,
              step: Optional[int] = None) -> None:
        """Start the armed window: create the capture dir, try to start
        the jax profiler session (loudly absent on failure), force the
        span tracer on, and record the clock anchor tracemerge aligns
        with.  Called by the engine at the step boundary BEFORE its
        schedule/stage work, so the window covers whole steps."""
        a, self._armed = self._armed, None
        if a is None:
            return
        cdir = a["dir"]
        try:
            os.makedirs(cdir, exist_ok=True)
        except OSError as e:
            logger.warning("capture dir %r unusable (%s); window "
                           "dropped", cdir, e)
            if a.get("budgeted"):
                # a window that produced NOTHING must not burn the
                # anomaly-capture budget — once the directory is
                # fixed, later anomalies can still capture
                self._budget_used = max(0, self._budget_used - 1)
            return
        profiling = False
        device_dir = os.path.join(cdir, "device")
        if _TRACE_OWNER:
            if not self._warned_unavailable:
                self._warned_unavailable = True
                logger.warning(
                    "capture %r: another jax profiler session is "
                    "active — this window records host spans only",
                    a["reason"])
        elif not profiler_available():
            if not self._warned_unavailable:
                self._warned_unavailable = True
                logger.warning(
                    "capture %r: this build exposes no jax profiler — "
                    "recording host spans only", a["reason"])
        else:
            try:
                import jax.profiler
                jax.profiler.start_trace(device_dir)
                _TRACE_OWNER.append(self)
                profiling = True
            except Exception as e:
                # loud-but-absent: the window still completes with host
                # spans + meta; tracemerge reports the device gap
                logger.warning(
                    "capture %r: jax profiler unavailable on this "
                    "backend/build (%s: %s) — recording host spans "
                    "only", a["reason"], type(e).__name__,
                    (str(e).splitlines() or [""])[0][:120])
        tracer_was = None
        if self.tracer is not None:
            tracer_was = self.tracer.enabled
            self.tracer.enable()
        self._active = {
            **a,
            "steps_left": a["steps"],
            "profiling": profiling,
            "device_dir": device_dir if profiling else None,
            "tracer_was_enabled": tracer_was,
            "t_start_perf_ns": time.perf_counter_ns(),
            "t_start_epoch_ns": time.time_ns(),
            "sid_start": sid,
            "step_start": step,
        }

    def end_step(self, sid: Optional[int] = None,
                 step: Optional[int] = None) -> Optional[str]:
        """Count one completed engine step against the active window;
        finalizes and returns the capture dir when the window is done,
        else None."""
        a = self._active
        if a is None:
            return None
        a["steps_left"] -= 1
        a["sid_end"] = sid
        a["step_end"] = step
        if a["steps_left"] > 0:
            return None
        return self._finish()

    def finish_now(self) -> Optional[str]:
        """Close an active window immediately (the engine calls this on
        a step failure — a capture that witnessed the failure is worth
        more finished than abandoned)."""
        if self._active is None:
            return None
        return self._finish()

    def _finish(self) -> str:
        a, self._active = self._active, None
        t_stop = time.perf_counter_ns()
        if a["profiling"]:
            try:
                import jax.profiler
                jax.profiler.stop_trace()
            except Exception as e:
                logger.warning("capture %r: stop_trace failed (%s)",
                               a["reason"], e)
                a["profiling"] = False
            finally:
                if _TRACE_OWNER and _TRACE_OWNER[-1] is self:
                    _TRACE_OWNER.pop()
        host_trace = None
        if self.tracer is not None:
            try:
                host_trace = os.path.join(a["dir"], "host_trace.json")
                with open(host_trace, "w") as f:
                    json.dump(self.tracer.chrome_trace(
                        since_ns=a["t_start_perf_ns"]), f)
            except OSError as e:
                logger.warning("capture %r: cannot write host trace "
                               "(%s)", a["reason"], e)
                host_trace = None
            if a["tracer_was_enabled"] is False:
                self.tracer.disable()
        meta = {
            "version": 1,
            "reason": a["reason"],
            "steps": a["steps"],
            "t_start_perf_ns": a["t_start_perf_ns"],
            "t_start_epoch_ns": a["t_start_epoch_ns"],
            "t_stop_perf_ns": t_stop,
            "profiler": a["profiling"],
            "device_dir": "device" if a["profiling"] else None,
            "host_trace": "host_trace.json" if host_trace else None,
            "sid_start": a["sid_start"], "sid_end": a.get("sid_end"),
            "step_start": a["step_start"], "step_end": a.get("step_end"),
        }
        try:
            with open(os.path.join(a["dir"], "meta.json"), "w") as f:
                json.dump(meta, f)
        except OSError as e:
            logger.warning("capture %r: cannot write meta (%s)",
                           a["reason"], e)
        self.captures.append(a["dir"])
        logger.info("capture %r complete: %s (device trace: %s)",
                    a["reason"], a["dir"],
                    "yes" if a["profiling"] else "ABSENT")
        return a["dir"]
