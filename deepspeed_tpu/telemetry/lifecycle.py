"""Request-lifecycle records for the serving engine.

Every request the engine sees walks one state machine
(docs/OBSERVABILITY.md):

    arrival --> admitted --> prefill_start --> first_token --> finish
    (put)       (scheduler    (first dispatch   (first emitted  (flush)
                 takes its     carrying its      token)
                 prompt)       tokens launches)

and its :class:`RequestRecord` yields the per-request latency story:

* **queue wait** — arrival -> admitted (scheduler backlog / pool
  pressure);
* **TTFT** — arrival -> first emitted token (what the user feels);
* **TPOT** — mean inter-token time over the decode tail
  (``(t_last - t_first) / (generated - 1)``).

Token accounting mirrors the engine counters *by construction*: the
tracker is bumped at the same statements that bump
``engine.timings["prompt_tokens"/"cached_tokens"/"generated_tokens"]``,
so ``sum(per-request) == engine counter`` is an invariant the tests
enforce (a drift means someone added an accounting site and forgot one
side).

All timestamps are monotonic ``time.perf_counter()`` seconds; the
tracker performs dict lookups and float stores only — never device
work.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from .metrics import MetricsRegistry

# fixed histogram bucket edges (ms) — powers-of-ten-ish ladders wide
# enough for CPU-fallback tests and tunneled-TPU serving alike
TTFT_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
                   1000.0, 2000.0, 5000.0, 10000.0, 30000.0)
TPOT_BUCKETS_MS = (0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                   500.0, 1000.0, 5000.0)
QUEUE_WAIT_BUCKETS_MS = TTFT_BUCKETS_MS


# terminal statuses a record may close with (docs/OBSERVABILITY.md):
#   finished          — ran to completion (stop token / max_new / flush)
#   shed              — rejected or evicted by backpressure before ever
#                       holding KV (overload.OverloadConfig.shed_policy),
#                       or left unfinished by engine.drain()
#   deadline_exceeded — its deadline_ms elapsed before completion
#   context_exhausted — hit the engine's max context; nothing more can
#                       be scheduled for it
#   cancelled         — engine.cancel() (client abort)
#   released          — its KV was released out-of-band (direct
#                       StateManager.release while the record was open)
#   failed            — quarantined by the failure classifier: the
#                       request repeatedly sat in failing step batches
#                       (poison — docs/SERVING.md "Failure domains &
#                       recovery"), or its device-side tokens were lost
#                       to a failure the host could not replay
#   migrated          — its open work was extracted
#                       (engine.migrate_out) and re-placed on another
#                       replica by the fleet router: terminal on THIS
#                       engine, while the request lives on at the
#                       fleet level (docs/SERVING.md "Fleet: routing,
#                       failover, migration")
#   handed_off        — prefill finished on a prefill-pool replica and
#                       the request was shipped to a decode replica
#                       (engine.handoff_out): like ``migrated``,
#                       terminal on THIS engine while the stream lives
#                       on at the fleet level (docs/SERVING.md
#                       "Disaggregated pools & elasticity")
TERMINAL_STATUSES = ("finished", "shed", "deadline_exceeded",
                     "context_exhausted", "cancelled", "released",
                     "failed", "migrated", "handed_off")


@dataclasses.dataclass
class RequestRecord:
    """One request's lifecycle timestamps + token accounting."""
    uid: int
    t_arrival: float
    # "open" until a terminal event closes the record; then one of
    # TERMINAL_STATUSES.  Preemption is NOT terminal: a preempted
    # request is re-queued (its KV re-prefills, from the prefix cache
    # when possible) and the record stays open with ``preemptions``
    # counting the evictions it survived.
    status: str = "open"
    preemptions: int = 0
    # step-failure recoveries this request rode through (non-terminal:
    # the failed batch was re-queued and the request resumed — the
    # failure-domain sibling of ``preemptions``)
    retries: int = 0
    t_admitted: Optional[float] = None
    t_prefill_start: Optional[float] = None
    t_first_token: Optional[float] = None
    t_last_token: Optional[float] = None
    t_finish: Optional[float] = None
    # decode-tail anchor for TPOT.  Stepwise emission: == t_first_token.
    # When the record's FIRST emission is a multi-token burst (all n
    # tokens materialize at one readback instant), this anchors at the
    # burst's dispatch time instead, so the tail isn't zero-width and
    # TPOT doesn't collapse to 0 (see RequestTracker.on_tokens).
    t_tail_start: Optional[float] = None
    prompt_tokens: int = 0
    cached_tokens: int = 0
    generated_tokens: int = 0
    # --- speculative decoding (docs/SERVING.md "Speculative decoding"):
    # drafts this request's verify windows scored / committed.  Bumped
    # at the same engine statements as the serving_spec_* counters, so
    # sum(per-request) == engine counter by construction — and the
    # per-request acceptance_rate is the measured signal the autotuner
    # (ROADMAP item 4) needs to drive spec_decode="auto" from data.
    drafted_tokens: int = 0
    accepted_tokens: int = 0
    # SLO class the request was admitted under (gateway header / fleet
    # routing) — the key the scorecard evaluates it by; None = the
    # tracker's default class (telemetry/slo.py)
    slo_class: Optional[str] = None

    @property
    def queue_wait_ms(self) -> Optional[float]:
        if self.t_admitted is None:
            return None
        return (self.t_admitted - self.t_arrival) * 1e3

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return (self.t_first_token - self.t_arrival) * 1e3

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean time per output token over the decode tail; needs at
        least two emitted tokens to have a tail."""
        if self.t_first_token is None or self.t_last_token is None \
                or self.generated_tokens < 2:
            return None
        tail0 = self.t_tail_start if self.t_tail_start is not None \
            else self.t_first_token
        return (self.t_last_token - tail0) * 1e3 \
            / (self.generated_tokens - 1)

    @property
    def e2e_ms(self) -> Optional[float]:
        if self.t_finish is None:
            return None
        return (self.t_finish - self.t_arrival) * 1e3

    @property
    def acceptance_rate(self) -> Optional[float]:
        """Accepted / drafted over this request's verify windows; None
        when no window was ever scored (spec off, or the proposer never
        matched)."""
        if not self.drafted_tokens:
            return None
        return self.accepted_tokens / self.drafted_tokens

    def as_dict(self) -> Dict[str, Any]:
        ms = {k: (None if v is None else round(v, 4))
              for k, v in (("queue_wait_ms", self.queue_wait_ms),
                           ("ttft_ms", self.ttft_ms),
                           ("tpot_ms", self.tpot_ms),
                           ("e2e_ms", self.e2e_ms))}
        ar = self.acceptance_rate
        return {"uid": self.uid,
                "slo_class": self.slo_class,
                "prompt_tokens": self.prompt_tokens,
                "cached_tokens": self.cached_tokens,
                "generated_tokens": self.generated_tokens,
                "drafted_tokens": self.drafted_tokens,
                "accepted_tokens": self.accepted_tokens,
                "acceptance_rate": None if ar is None else round(ar, 4),
                "finished": self.t_finish is not None,
                "status": self.status,
                "preemptions": self.preemptions,
                "retries": self.retries,
                **ms}


class RequestTracker:
    """Open-record table + bounded finished ring, feeding the latency
    histograms and request counters of a :class:`MetricsRegistry`."""

    def __init__(self, registry: MetricsRegistry,
                 max_finished: int = 4096):
        self.registry = registry
        # optional SloTracker sink (telemetry/slo.py), attached by the
        # engine when InferenceConfig.slo resolves ON.  None = SLO
        # tracking off: the two hook sites below are a single attribute
        # test — the zero-cost-off bar.  When attached, both hooks
        # evaluate from timestamps ALREADY stamped on the record (zero
        # new clock reads on the hot path).
        self.slo = None
        self.open: Dict[int, RequestRecord] = {}  # tpulint: live-set
        self.finished: Deque[RequestRecord] = deque(maxlen=max_finished)
        self._h_ttft = registry.histogram(
            "serving_ttft_ms", TTFT_BUCKETS_MS,
            "arrival to first emitted token")
        self._h_tpot = registry.histogram(
            "serving_tpot_ms", TPOT_BUCKETS_MS,
            "mean inter-token latency over the decode tail")
        self._h_queue = registry.histogram(
            "serving_queue_wait_ms", QUEUE_WAIT_BUCKETS_MS,
            "arrival to first scheduler admission")
        self._c_arrived = registry.counter(
            "serving_requests_total", "requests ever opened",
            int_valued=True)
        # tpulint: pair=_c_finished/_c_terminal
        self._c_finished = registry.counter(
            "serving_requests_finished_total",
            "requests closed with any terminal status", int_valued=True)
        self._c_terminal = registry.counter(
            "serving_requests_terminal_total",
            "terminal lifecycle closures by status", int_valued=True)
        self._c_preempted = registry.counter(
            "serving_preemptions_total",
            "preemption-by-eviction events (non-terminal: the request "
            "is re-queued)", int_valued=True)
        self._c_retried = registry.counter(
            "serving_request_retries_total",
            "step-failure recoveries ridden through (non-terminal: the "
            "failed batch was re-queued)", int_valued=True)
        # uid -> last terminal status, bounded alongside the finished
        # ring (``_status_refs`` counts ring records per uid so the
        # entry dies with its last evicted record)
        self._last_status: Dict[int, str] = {}
        self._status_refs: Dict[int, int] = {}
        # uids whose terminal status aged OUT of the ring — so
        # ``status_of`` can answer "forgotten" (the uid existed; its
        # story is gone) instead of the never-seen "unknown".  Bounded
        # at 8x the ring: beyond that, truly ancient uids fall back to
        # "unknown" (insertion-ordered dict = O(1) FIFO eviction)
        self._forgotten: Dict[int, None] = {}
        self._forgotten_cap = 8 * max_finished
        # cumulative speculative-decode tallies (plain ints, NOT registry
        # counters — the engine's serving_spec_* counters are the
        # exported metric; these survive finished-ring eviction so the
        # aggregate acceptance_rate stays exact over long traffic)
        self._drafted = 0
        self._accepted = 0

    def clear(self) -> None:
        self.open.clear()
        self.finished.clear()
        self._last_status.clear()
        self._status_refs.clear()
        self._forgotten.clear()
        self._drafted = 0
        self._accepted = 0

    # ------------------------------------------------------------------
    # lifecycle events (all O(1) dict/float work)
    # ------------------------------------------------------------------
    def on_arrival(self, uid: int, now: Optional[float] = None,
                   slo_class: Optional[str] = None) -> RequestRecord:
        rec = self.open.get(uid)
        if rec is not None:
            # continuation put: a late class tag fills the blank, but
            # never overwrites the class the request arrived under
            if slo_class is not None and rec.slo_class is None:
                rec.slo_class = slo_class
            return rec
        rec = RequestRecord(uid, now if now is not None
                            else time.perf_counter(),
                            slo_class=slo_class)
        self.open[uid] = rec
        self._forgotten.pop(uid, None)       # the uid lives again
        self._c_arrived.inc()
        return rec

    def on_admitted(self, uid: int, prompt_tokens: int,
                    cached_tokens: int, now: float) -> None:
        rec = self.open.get(uid)
        if rec is None:                      # direct-API putless entry
            rec = self.on_arrival(uid, now)
        if rec.t_admitted is None:
            rec.t_admitted = now
            self._h_queue.observe((now - rec.t_arrival) * 1e3)
        rec.prompt_tokens += prompt_tokens
        rec.cached_tokens += cached_tokens

    def on_prefill_start(self, uid: int, now: float) -> None:
        rec = self.open.get(uid)
        if rec is not None and rec.t_prefill_start is None:
            rec.t_prefill_start = now

    def on_tokens(self, uid: int, n: int, now: float,
                  t_dispatch: Optional[float] = None) -> None:
        """``t_dispatch``: for an ``n > 1`` burst emission (all tokens
        land at one readback), the burst's dispatch time — used as the
        decode-tail anchor when these are the record's first tokens.
        TTFT stays at ``now``: the tokens are not visible to the host
        before readback."""
        rec = self.open.get(uid)
        if rec is None or n <= 0:
            return
        if rec.t_first_token is None:
            rec.t_first_token = now
            rec.t_tail_start = t_dispatch \
                if (t_dispatch is not None and n > 1) else now
            self._h_ttft.observe((now - rec.t_arrival) * 1e3)
            if self.slo is not None:
                # same statement the TTFT histogram observes at —
                # the scorecard reads the stamps just stored
                self.slo.on_first_token(rec)
        rec.t_last_token = now
        rec.generated_tokens += n

    def on_draft(self, uid: int, drafted: int, accepted: int) -> None:
        """One resolved verify window: ``drafted`` tokens scored,
        ``accepted`` of them committed (emission also flows through
        :meth:`on_tokens` — these counters are the speculative overlay,
        not a second token count)."""
        rec = self.open.get(uid)
        if rec is None:
            return
        rec.drafted_tokens += drafted
        rec.accepted_tokens += accepted
        self._drafted += drafted
        self._accepted += accepted

    def on_preempted(self, uid: int, now: Optional[float] = None) -> None:
        """A running request was evicted and re-queued — NOT terminal:
        the record stays open accumulating tokens/latency across the
        re-prefill; only the eviction count and counter move."""
        rec = self.open.get(uid)
        if rec is None:
            return
        rec.preemptions += 1
        self._c_preempted.inc()

    def on_retried(self, uid: int) -> None:
        """The request sat in a step batch the failure classifier
        recovered (re-queue + re-prefill) — NOT terminal; the
        failure-domain sibling of :meth:`on_preempted`."""
        rec = self.open.get(uid)
        if rec is None:
            return
        rec.retries += 1
        self._c_retried.inc()

    def on_finish(self, uid: int, now: Optional[float] = None,
                  status: str = "finished") -> None:
        """Close the record with a terminal ``status`` (idempotent: a
        second terminal event for the same uid is a no-op, so e.g. a
        context-exhausted close followed by the driver's flush never
        double-counts)."""
        rec = self.open.pop(uid, None)
        if rec is None:
            return
        rec.t_finish = now if now is not None else time.perf_counter()
        rec.status = status
        tpot = rec.tpot_ms
        if tpot is not None:
            self._h_tpot.observe(tpot)
        self._c_finished.inc()
        self._c_terminal.inc(status=status)
        if self.slo is not None:
            # terminal close-out: the record carries every timestamp
            # the scorecard needs — no clock is read here
            self.slo.on_close(rec)
        if len(self.finished) == self.finished.maxlen:
            old = self.finished[0]          # about to be ring-evicted
            self._status_refs[old.uid] -= 1
            if not self._status_refs[old.uid]:
                del self._status_refs[old.uid]
                if self._last_status.pop(old.uid, None) is not None:
                    # the uid's whole story just aged out: remember
                    # THAT it existed (bounded), so status_of answers
                    # "forgotten" instead of the never-seen "unknown"
                    self._forgotten[old.uid] = None
                    while len(self._forgotten) > self._forgotten_cap:
                        self._forgotten.pop(next(iter(self._forgotten)))
        self.finished.append(rec)
        self._last_status[uid] = status
        self._forgotten.pop(uid, None)
        self._status_refs[uid] = self._status_refs.get(uid, 0) + 1

    def status_of(self, uid: int) -> Optional[str]:
        """``"open"`` while the request is live, its terminal status
        after closure (as far back as the finished ring remembers),
        ``"forgotten"`` for a uid whose terminal record aged out of the
        ring (sized by ``OverloadConfig.status_retention``), or None
        for a uid this tracker never saw."""
        if uid in self.open:
            return "open"
        s = self._last_status.get(uid)
        if s is None and uid in self._forgotten:
            return "forgotten"
        return s

    # ------------------------------------------------------------------
    def records(self) -> List[RequestRecord]:
        """Finished (oldest first) then still-open records."""
        return list(self.finished) + list(self.open.values())

    def aggregate(self) -> Dict[str, Any]:
        """Compact summary for bench JSON / dashboards."""
        return {
            "requests": int(self._c_arrived.value()),
            "finished": int(self._c_finished.value()),
            "open": len(self.open),
            "preemptions": int(self._c_preempted.value()),
            "retries": int(self._c_retried.value()),
            # terminal closures by status (only statuses that occurred)
            "statuses": {k[0][1]: int(v)
                         for k, v in self._c_terminal.series() if k},
            "ttft_ms": self._h_ttft.summary(),
            "tpot_ms": self._h_tpot.summary(),
            "queue_wait_ms": self._h_queue.summary(),
            # speculative decoding (docs/SERVING.md "Speculative
            # decoding"): fleet-wide draft tallies + acceptance_rate —
            # the measured signal ROADMAP item 4's autotuner reads
            "drafted_tokens": self._drafted,
            "accepted_tokens": self._accepted,
            "acceptance_rate": (round(self._accepted / self._drafted, 4)
                                if self._drafted else None),
        }
