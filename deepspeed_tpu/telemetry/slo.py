"""Per-class SLO objectives, error budgets, and burn-rate signals
(docs/OBSERVABILITY.md "SLOs & error budgets").

The serving stack routes by SLO class (``x-slo-class`` -> priority /
deadline / pool) and even scales pools by class, but routing a class is
not *meeting* it.  This module is the measurement half: per-class
:class:`SloObjective` targets (TTFT / TPOT / e2e latency, deadline-met,
availability = the non-shed fraction), rolling compliance windows, an
error budget per class, and deterministic multi-window burn-rate
detectors that plug into the existing :class:`~.anomaly.AnomalyMonitor`
catalog as the ``slo_burn_rate_<class>`` signal family — so a burning
budget breadcrumbs the flight recorder, arms a budgeted profiler
capture, and reaches the autoscaler's signal->pool map exactly like
every other anomaly signal.

Design rules (the telemetry-layer discipline):

* **zero new clock reads** — the tracker is fed at the same two
  statements :class:`~.lifecycle.RequestTracker` already stamps (the
  first-token branch of ``on_tokens`` and the terminal close-out of
  ``on_finish``) and evaluates entirely from timestamps already on the
  :class:`~.lifecycle.RequestRecord`.  SLO tracking ON adds zero
  ``perf_counter`` calls per warm step; OFF constructs nothing
  (``InferenceConfig.slo`` is the usual ``"auto"|"on"|"off"`` gate,
  auto resolving OFF today).
* **attainment == counter quotient by construction** — every
  evaluation bumps the paired labeled counters
  ``serving_slo_good_total`` / ``serving_slo_evaluated_total``
  (``class=`` / ``objective=`` labels) at ONE site, declared to
  tpulint's counter-pairing pass, so the scorecard's attainment is
  exactly the quotient of two exported monotonic counters — a
  dashboard recomputes it from a scrape and gets the same number.
* **request-counted, deterministic burn windows** — the fast/slow
  windows count *requests*, not seconds (Google-SRE multi-window
  burn-rate shape, made replayable): burn rate is
  ``bad_fraction / (1 - target)`` over each window, and the detector
  fires when BOTH windows exceed their thresholds — the fast window
  catches the current burn, the slow window confirms it is sustained
  rather than one unlucky request.

Hop closures (``migrated`` / ``handed_off``) are *not* evaluated: the
request lives on at the fleet level and will be judged once, by the
replica that actually finishes it (otherwise a disaggregated fleet
double-counts every request's availability).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, Optional, Tuple

# the class a record evaluates under when it was never tagged — the
# same default the gateway's class map applies to header-less requests
DEFAULT_SLO_CLASS = "standard"

# statuses that are a hop, not an end: skip evaluation entirely
HOP_STATUSES = ("migrated", "handed_off")

# statuses charged against availability (the engine failed the client)
UNAVAILABLE_STATUSES = ("shed", "failed")


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One class's service-level objective.  Latency targets are
    opt-in (None = that dimension is not part of this class's
    contract); ``target`` is the attainment goal the error budget and
    burn rates are normalised against.  Window sizes count REQUESTS —
    the whole scorecard replays deterministically."""
    ttft_ms: Optional[float] = None       # first-token latency bound
    tpot_ms: Optional[float] = None       # decode-tail per-token bound
    e2e_ms: Optional[float] = None        # arrival->finish bound
    target: float = 0.95                  # latency/deadline attainment
    availability: float = 0.999           # non-shed fraction target
    window: int = 512                     # rolling compliance window
    fast_window: int = 32                 # burn-rate windows (requests)
    slow_window: int = 256
    fast_burn: float = 14.0               # fire thresholds (x budget)
    slow_burn: float = 6.0

    def __post_init__(self):
        if not (0.0 < self.target < 1.0):
            raise ValueError("target must be in (0, 1)")
        if not (0.0 < self.availability <= 1.0):
            raise ValueError("availability must be in (0, 1]")
        if self.window < 1 or self.fast_window < 1 or self.slow_window < 1:
            raise ValueError("window sizes must be >= 1")
        if self.fast_window > self.slow_window:
            raise ValueError("fast_window must be <= slow_window (the "
                             "slow window is the sustained confirmation)")
        for name in ("ttft_ms", "tpot_ms", "e2e_ms"):
            v = getattr(self, name)
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be positive when set")
        if self.fast_burn <= 0 or self.slow_burn <= 0:
            raise ValueError("burn thresholds must be positive")


def default_slo_objectives() -> Dict[str, SloObjective]:
    """Objectives for the gateway's default class map
    (``sloclass.default_slo_classes``): interactive carries the tight
    latency contract, standard a loose one, batch only a throughput-ish
    TPOT bound and availability."""
    return {
        "interactive": SloObjective(ttft_ms=1000.0, tpot_ms=200.0,
                                    e2e_ms=30_000.0, target=0.95),
        "standard": SloObjective(ttft_ms=5000.0, e2e_ms=120_000.0,
                                 target=0.9),
        "batch": SloObjective(tpot_ms=500.0, target=0.9),
    }


class BurnRateDetector:
    """Deterministic multi-window error-budget burn detector, protocol-
    compatible with the :class:`~.anomaly.AnomalyMonitor` catalog
    (``kind`` / ``direction`` / ``reset`` / ``observe``).

    ``observe(bit)`` takes one request's composite violation bit
    (1.0 = the request violated its class objective).  Burn rate over a
    window is ``bad_fraction / (1 - target)`` — 1.0 means the budget is
    consumed exactly at the rate the objective allows for; the detector
    fires when the fast window burns >= ``fast_burn`` AND the slow
    window burns >= ``slow_burn``.  The fast window must be FULL before
    the first fire (warm-up); the slow window evaluates over however
    many of its samples exist so far (early in life it equals the fast
    window — the sustained confirmation strengthens as traffic
    accumulates).  No clocks anywhere: replay-identical."""

    kind = "burn_rate"
    direction = "high"

    def __init__(self, target: float = 0.95, fast_window: int = 32,
                 slow_window: int = 256, fast_burn: float = 14.0,
                 slow_burn: float = 6.0):
        if fast_window < 1 or slow_window < fast_window:
            raise ValueError("need 1 <= fast_window <= slow_window")
        self.target = float(target)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self._budget = max(1.0 - self.target, 1e-9)
        self._fast: Deque[float] = deque(maxlen=fast_window)
        self._slow: Deque[float] = deque(maxlen=slow_window)

    @classmethod
    def for_objective(cls, obj: SloObjective) -> "BurnRateDetector":
        return cls(target=obj.target, fast_window=obj.fast_window,
                   slow_window=obj.slow_window, fast_burn=obj.fast_burn,
                   slow_burn=obj.slow_burn)

    def reset(self) -> None:
        self._fast.clear()
        self._slow.clear()

    def _burn(self, win: Deque[float]) -> float:
        if not win:
            return 0.0
        return (sum(win) / len(win)) / self._budget

    @property
    def fast_rate(self) -> float:
        return self._burn(self._fast)

    @property
    def slow_rate(self) -> float:
        return self._burn(self._slow)

    def observe(self, value: float) -> Optional[Tuple[float, float]]:
        bit = 1.0 if value else 0.0
        self._fast.append(bit)
        self._slow.append(bit)
        if len(self._fast) < self._fast.maxlen:
            return None                      # warm-up: fast window full
        fast, slow = self.fast_rate, self.slow_rate
        if fast >= self.fast_burn and slow >= self.slow_burn:
            # baseline = the allowed bad fraction, score = how many
            # times over budget the fast window is burning
            return self._budget, fast
        return None


class SloTracker:
    """Per-class scorecard state over one :class:`MetricsRegistry`.

    Fed by :class:`~.lifecycle.RequestTracker` at its existing stamp
    sites (:meth:`on_first_token`, :meth:`on_close`); every evaluation
    flows through ONE paired-counter site (:meth:`_observe`) so
    attainment is the exported counter quotient by construction.
    ``bind`` attaches the per-class burn detectors to an
    :class:`AnomalyMonitor` so fires ride the monitor's cooldown /
    event ring / counters and reach the engine's capture+breadcrumb
    path like any other anomaly."""

    # the composite per-request objective every class evaluates: "this
    # request met everything its class asked of it"
    COMPOSITE = "requests"

    def __init__(self, objectives: Dict[str, SloObjective], registry,
                 default_class: str = DEFAULT_SLO_CLASS):
        if not objectives:
            raise ValueError("need at least one SloObjective")
        self.objectives = dict(objectives)
        self.default_class = default_class
        # tpulint: pair=_c_good/_c_eval
        self._c_good = registry.counter(
            "serving_slo_good_total",
            "SLO evaluations that met their objective "
            "(class/objective labels)", int_valued=True)
        self._c_eval = registry.counter(
            "serving_slo_evaluated_total",
            "SLO evaluations performed (class/objective labels)",
            int_valued=True)
        # rolling compliance windows, (class, objective) -> 0/1 ring
        self._windows: Dict[Tuple[str, str], Deque[int]] = {}
        self._burn: Dict[str, BurnRateDetector] = {
            cls: BurnRateDetector.for_objective(obj)
            for cls, obj in self.objectives.items()}
        self._monitor = None
        self._step_fn = None
        self._on_fire = None

    # ------------------------------------------------------------------
    # anomaly-catalog attachment
    # ------------------------------------------------------------------
    def bind(self, monitor, step_fn, on_fire=None) -> None:
        """Register the per-class burn detectors as the
        ``slo_burn_rate_<class>`` signal family of ``monitor``;
        ``step_fn`` supplies the step a fire is stamped with and
        ``on_fire(event)`` receives fired events (the engine routes
        them into its breadcrumb + budgeted-capture path)."""
        for cls in self._burn:
            monitor.watch(f"slo_burn_rate_{cls}", self._burn[cls])
        self._monitor = monitor
        self._step_fn = step_fn
        self._on_fire = on_fire

    # ------------------------------------------------------------------
    # the one paired-counter site (attainment == quotient by construction)
    # ------------------------------------------------------------------
    def _observe(self, cls: str, objective: str, good: bool) -> None:
        labels = {"class": cls, "objective": objective}
        self._c_eval.inc(**labels)
        if good:
            self._c_good.inc(**labels)
        win = self._windows.get((cls, objective))
        if win is None:
            obj = self.objectives.get(cls)
            size = obj.window if obj is not None else 512
            win = self._windows[(cls, objective)] = deque(maxlen=size)
        win.append(1 if good else 0)

    def _class_of(self, rec) -> str:
        return getattr(rec, "slo_class", None) or self.default_class

    # ------------------------------------------------------------------
    # feed points (RequestTracker's existing stamp statements)
    # ------------------------------------------------------------------
    def on_first_token(self, rec) -> None:
        """Fed from the first-token branch of ``on_tokens`` —
        ``rec.ttft_ms`` is already computed from stamps the tracker
        just stored; no clock is read here."""
        cls = self._class_of(rec)
        obj = self.objectives.get(cls)
        if obj is None or obj.ttft_ms is None:
            return
        ttft = rec.ttft_ms
        if ttft is None:
            return
        self._observe(cls, "ttft", ttft <= obj.ttft_ms)

    def on_close(self, rec) -> None:
        """Fed from ``on_finish`` after the record's terminal stamp —
        evaluates availability, deadline-met, the latency targets, and
        the composite per-request bit that drives the burn detector.
        Hop closures are skipped (module docstring)."""
        status = rec.status
        if status in HOP_STATUSES:
            return
        cls = self._class_of(rec)
        obj = self.objectives.get(cls)
        if obj is None:
            return
        avail_ok = status not in UNAVAILABLE_STATUSES
        self._observe(cls, "availability", avail_ok)
        deadline_ok = status != "deadline_exceeded"
        self._observe(cls, "deadline", deadline_ok)
        good = avail_ok and deadline_ok
        if obj.ttft_ms is not None and rec.ttft_ms is not None:
            # already counted under "ttft" at first token; folded into
            # the composite here without re-counting
            good = good and rec.ttft_ms <= obj.ttft_ms
        if obj.tpot_ms is not None and rec.tpot_ms is not None:
            tpot_ok = rec.tpot_ms <= obj.tpot_ms
            self._observe(cls, "tpot", tpot_ok)
            good = good and tpot_ok
        if obj.e2e_ms is not None and status == "finished" \
                and rec.e2e_ms is not None:
            e2e_ok = rec.e2e_ms <= obj.e2e_ms
            self._observe(cls, "e2e", e2e_ok)
            good = good and e2e_ok
        self._observe(cls, self.COMPOSITE, good)
        self._feed_burn(cls, good)

    def _feed_burn(self, cls: str, good: bool) -> None:
        bit = 0.0 if good else 1.0
        if self._monitor is not None:
            ev = self._monitor.observe(f"slo_burn_rate_{cls}", bit,
                                       self._step_fn())
            if ev is not None and self._on_fire is not None:
                self._on_fire(ev)
        else:
            # unbound (anomaly plane off): the detector still tracks
            # burn rates so the scorecard reports them
            self._burn[cls].observe(bit)

    # ------------------------------------------------------------------
    # reporting
    # ------------------------------------------------------------------
    def _pair(self, cls: str, objective: str) -> Tuple[int, int]:
        labels = {"class": cls, "objective": objective}
        good = int(self._c_good.value(**labels))
        total = int(self._c_eval.value(**labels))
        return good, total

    def scorecard(self) -> Dict:
        """The per-class scorecard (JSON-able): per-objective counter
        pairs + attainment quotient + rolling-window attainment, the
        class error budget on the composite objective, and the burn
        detector's fast/slow rates."""
        classes: Dict[str, Dict] = {}
        for cls in sorted(self.objectives):
            obj = self.objectives[cls]
            objectives: Dict[str, Dict] = {}
            for name, tgt in (("ttft", obj.ttft_ms),
                              ("tpot", obj.tpot_ms),
                              ("e2e", obj.e2e_ms)):
                if tgt is None:
                    continue
                objectives[name] = self._objective_entry(
                    cls, name, obj.target, threshold_ms=tgt)
            objectives["deadline"] = self._objective_entry(
                cls, "deadline", obj.target)
            objectives["availability"] = self._objective_entry(
                cls, "availability", obj.availability)
            objectives[self.COMPOSITE] = self._objective_entry(
                cls, self.COMPOSITE, obj.target)
            good, total = self._pair(cls, self.COMPOSITE)
            bad = total - good
            budget = (1.0 - obj.target) * total
            det = self._burn[cls]
            classes[cls] = {
                "objectives": objectives,
                "error_budget": {
                    "target": obj.target,
                    "evaluated": total,
                    "allowed_bad": round(budget, 4),
                    "consumed_bad": bad,
                    "remaining": round(budget - bad, 4),
                    "burn_total": (round(bad / budget, 4)
                                   if budget > 0 else None),
                },
                "burn_rate": {
                    "fast": round(det.fast_rate, 4),
                    "slow": round(det.slow_rate, 4),
                    "fast_window": det._fast.maxlen,
                    "slow_window": det._slow.maxlen,
                    "fast_threshold": det.fast_burn,
                    "slow_threshold": det.slow_burn,
                },
            }
        return {"enabled": True, "default_class": self.default_class,
                "classes": classes}

    def _objective_entry(self, cls: str, name: str, target: float,
                         threshold_ms: Optional[float] = None) -> Dict:
        good, total = self._pair(cls, name)
        win = self._windows.get((cls, name))
        entry = {
            "good": good,
            "evaluated": total,
            "attainment": (round(good / total, 4) if total else None),
            "target": target,
            "window_attainment": (round(sum(win) / len(win), 4)
                                  if win else None),
        }
        if threshold_ms is not None:
            entry["threshold_ms"] = threshold_ms
        return entry

    def reset(self) -> None:
        """Rearm windows and burn detectors (counters are the
        registry's to reset — ``engine.reset_metrics`` clears both)."""
        self._windows.clear()
        for det in self._burn.values():
            det.reset()


def merge_scorecards(cards: Dict[str, Dict]) -> Dict:
    """Fleet rollup of per-replica scorecards: counter pairs SUM (the
    quotient stays exact — the fleet attainment is the quotient of the
    summed exported counters), budgets sum, and burn rates take the
    per-replica MAX (the fleet number for a peak signal is its worst
    replica, the FleetRegistry rollup convention).  Disabled replicas
    contribute nothing; all-disabled merges to ``{"enabled": False}``."""
    live = {n: c for n, c in cards.items() if c and c.get("enabled")}
    if not live:
        return {"enabled": False, "replicas": sorted(cards)}
    classes: Dict[str, Dict] = {}
    for name in sorted(live):
        for cls, entry in live[name]["classes"].items():
            agg = classes.setdefault(cls, {
                "objectives": {}, "error_budget": None,
                "burn_rate": {"fast": 0.0, "slow": 0.0},
            })
            for oname, o in entry["objectives"].items():
                tgt = agg["objectives"].setdefault(oname, {
                    "good": 0, "evaluated": 0, "target": o["target"]})
                tgt["good"] += o["good"]
                tgt["evaluated"] += o["evaluated"]
                if "threshold_ms" in o:
                    tgt["threshold_ms"] = o["threshold_ms"]
            eb = entry["error_budget"]
            acc = agg["error_budget"]
            if acc is None:
                agg["error_budget"] = acc = {
                    "target": eb["target"], "evaluated": 0,
                    "allowed_bad": 0.0, "consumed_bad": 0}
            acc["evaluated"] += eb["evaluated"]
            acc["allowed_bad"] += eb["allowed_bad"]
            acc["consumed_bad"] += eb["consumed_bad"]
            br = entry["burn_rate"]
            agg["burn_rate"]["fast"] = max(agg["burn_rate"]["fast"],
                                           br["fast"])
            agg["burn_rate"]["slow"] = max(agg["burn_rate"]["slow"],
                                           br["slow"])
    for cls, agg in classes.items():
        for o in agg["objectives"].values():
            o["attainment"] = (round(o["good"] / o["evaluated"], 4)
                               if o["evaluated"] else None)
        eb = agg["error_budget"]
        eb["allowed_bad"] = round(eb["allowed_bad"], 4)
        eb["remaining"] = round(eb["allowed_bad"] - eb["consumed_bad"], 4)
        eb["burn_total"] = (round(eb["consumed_bad"] / eb["allowed_bad"], 4)
                            if eb["allowed_bad"] > 0 else None)
    return {"enabled": True, "classes": classes,
            "replicas": {n: c for n, c in cards.items()}}
