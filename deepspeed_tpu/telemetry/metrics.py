"""Metrics registry: labeled counters / gauges / fixed-bucket histograms.

One registry instance per engine holds every serving (or training)
metric as a first-class object — the flat ``engine.timings``
ms-accumulator dict is now a :class:`CounterDictView` façade over these
counters, so old callers keep their dict while new code reads the
registry.

Exports:

* :meth:`MetricsRegistry.prometheus_text` — Prometheus text exposition
  (scrape-ready; :func:`parse_prometheus_text` is the matching parser,
  used by the round-trip tests).
* :meth:`MetricsRegistry.write_jsonl` — one JSON snapshot line appended
  per call (bench captures, offline analysis).
* :meth:`MetricsRegistry.publish` — fan out through the existing
  ``monitor/`` writer interface (:class:`deepspeed_tpu.monitor.Monitor`
  — CSV/TensorBoard/WandB/Comet), so serving metrics and training
  scalars share one pipeline.

Everything here is plain host-side arithmetic — no JAX imports, no
device arrays (a metric update must never trigger a sync; tpulint's
``telemetry-hotpath`` rule keeps these calls out of jit-traced code).
Single-writer by design: the serving loop and the training step are
single-threaded, so there are no locks on the update path.
"""

from __future__ import annotations

import bisect
import json
import re
import time
from typing import (Any, Dict, Iterator, List, MutableMapping, Optional,
                    Sequence, Tuple)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Prometheus-legal metric name (``Train/loss`` -> ``Train_loss``)."""
    safe = _NAME_SANITIZE.sub("_", name)
    return "_" + safe if safe[:1].isdigit() else safe


def _prom_label_str(key: LabelKey) -> str:
    if not key:
        return ""
    parts = []
    for k, v in key:
        v = v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        parts.append(f'{_prom_name(k)}="{v}"')
    return "{" + ",".join(parts) + "}"


def _fmt(v: float) -> str:
    """Float formatting matching Prometheus conventions (ints bare)."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Metric:
    """Base: one named metric holding one series per label set."""
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._values: Dict[LabelKey, float] = {}

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def series(self) -> Iterator[Tuple[LabelKey, float]]:
        if not self._values:
            yield (), 0.0
            return
        for k in sorted(self._values):
            yield k, self._values[k]

    def reset(self) -> None:
        self._values = {}


class Counter(Metric):
    """Monotonic accumulator.  ``int_valued`` marks token/step counts so
    the :class:`CounterDictView` façade hands back true ints."""
    kind = "counter"

    def __init__(self, name: str, help: str = "", int_valued: bool = False):
        super().__init__(name, help)
        self.int_valued = int_valued

    def inc(self, amount: float = 1, **labels) -> None:
        k = _label_key(labels)
        self._values[k] = self._values.get(k, 0.0) + amount

    def _set(self, value: float, **labels) -> None:
        """Back-compat escape hatch for the dict view (``tm[k] = 0``);
        counters are otherwise inc-only."""
        self._values[_label_key(labels)] = value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1, **labels) -> None:
        k = _label_key(labels)
        self._values[k] = self._values.get(k, 0.0) + amount


class FnGauge(Gauge):
    """Pull-based gauge: its value is computed by a zero-arg callable at
    *read/export* time (snapshot / Prometheus exposition / monitor
    fan-out), so the measured subsystem never pays an update on its hot
    path and a scrape always sees the current truth.  The callable
    returns a number, or ``None`` for "no sample right now" — the gauge
    is then ABSENT from the exposition (the contract device telemetry
    uses for probes a backend does not support: absent, never fake).
    Exceptions from the callable also read as absent (a gauge must
    never take the exporter down), ``set()`` raises (there is nothing
    to set), and ``reset()`` is a no-op (the source owns the state)."""
    kind = "gauge"

    def __init__(self, name: str, fn, help: str = ""):
        super().__init__(name, help)
        self._fn = fn

    def value(self, **labels) -> float:
        v = self._read()
        return 0.0 if v is None else v

    def _read(self) -> Optional[float]:
        try:
            v = self._fn()
        except Exception:  # tpulint: disable=silent-except — a broken probe reads as an absent sample, never an export crash
            return None
        return None if v is None else float(v)

    def series(self) -> Iterator[Tuple[LabelKey, float]]:
        v = self._read()
        if v is not None:
            yield (), v

    def set(self, value: float, **labels) -> None:
        raise TypeError(f"{self.name} is a pull-based FnGauge; "
                        "its source computes the value")

    def inc(self, amount: float = 1, **labels) -> None:
        raise TypeError(f"{self.name} is a pull-based FnGauge; "
                        "its source computes the value")

    def reset(self) -> None:
        pass                    # the source owns the state


class Histogram(Metric):
    """Fixed-bucket histogram (cumulative ``le`` buckets, Prometheus
    semantics).  Bucket bounds are chosen at registration — observation
    is one bisect + three adds, no allocation."""
    kind = "histogram"

    def __init__(self, name: str, buckets: Sequence[float],
                 help: str = ""):
        super().__init__(name, help)
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"{name}: buckets must be sorted and "
                             f"non-empty, got {buckets!r}")
        self.buckets: Tuple[float, ...] = tuple(float(b) for b in buckets)
        # per label set: [count per bucket + overflow], sum, count
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sums: Dict[LabelKey, float] = {}
        self._totals: Dict[LabelKey, int] = {}

    def observe(self, value: float, **labels) -> None:
        k = _label_key(labels)
        counts = self._counts.get(k)
        if counts is None:
            counts = self._counts[k] = [0] * (len(self.buckets) + 1)
            self._sums[k] = 0.0
            self._totals[k] = 0
        counts[bisect.bisect_left(self.buckets, value)] += 1
        self._sums[k] += value
        self._totals[k] += 1

    def count(self, **labels) -> int:
        return self._totals.get(_label_key(labels), 0)

    def sum(self, **labels) -> float:
        return self._sums.get(_label_key(labels), 0.0)

    def mean(self, **labels) -> float:
        n = self.count(**labels)
        return self.sum(**labels) / n if n else 0.0

    def percentile(self, q: float, **labels) -> float:
        """Bucket-resolution quantile estimate (linear interpolation
        inside the winning bucket; the overflow bucket reports its lower
        bound — the histogram cannot see past its last edge)."""
        k = _label_key(labels)
        counts = self._counts.get(k)
        total = self._totals.get(k, 0)
        if not counts or not total:
            return 0.0
        target = q * total
        cum = 0
        lo = 0.0
        for i, upper in enumerate(self.buckets):
            prev = cum
            cum += counts[i]
            if cum >= target:
                frac = (target - prev) / max(counts[i], 1)
                return lo + (upper - lo) * min(1.0, frac)
            lo = upper
        return self.buckets[-1]

    def bucket_counts(self, **labels) -> Dict[str, int]:
        """Cumulative counts keyed by ``le`` edge (``+Inf`` last)."""
        k = _label_key(labels)
        counts = self._counts.get(k, [0] * (len(self.buckets) + 1))
        out: Dict[str, int] = {}
        cum = 0
        for i, upper in enumerate(self.buckets):
            cum += counts[i]
            out[_fmt(upper)] = cum
        out["+Inf"] = cum + counts[-1]
        return out

    def series(self) -> Iterator[Tuple[LabelKey, float]]:
        for k in sorted(self._counts) or [()]:
            yield k, float(self._totals.get(k, 0))

    def summary(self, **labels) -> Dict[str, Any]:
        return {"count": self.count(**labels),
                "sum": round(self.sum(**labels), 6),
                "mean": round(self.mean(**labels), 6),
                "p50": round(self.percentile(0.50, **labels), 6),
                "p90": round(self.percentile(0.90, **labels), 6),
                "p99": round(self.percentile(0.99, **labels), 6),
                "buckets": self.bucket_counts(**labels)}

    def reset(self) -> None:
        self._counts = {}
        self._sums = {}
        self._totals = {}


class MetricsRegistry:
    """Ordered name -> metric table with get-or-create registration."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}

    # ------------------------------------------------------------------
    def _register(self, name: str, factory, kind: str) -> Metric:
        m = self._metrics.get(name)
        if m is not None:
            if m.kind != kind:
                raise ValueError(f"metric {name!r} already registered "
                                 f"as {m.kind}, requested {kind}")
            return m
        m = factory()
        self._metrics[name] = m
        return m

    def counter(self, name: str, help: str = "",
                int_valued: bool = False) -> Counter:
        return self._register(
            name, lambda: Counter(name, help, int_valued), "counter")

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(name, lambda: Gauge(name, help), "gauge")

    def gauge_fn(self, name: str, fn, help: str = "") -> FnGauge:
        """Register a pull-based gauge computed by ``fn()`` at read
        time (:class:`FnGauge`); re-registering rebinds the callable
        (an engine rebuilt over the same registry must not read a dead
        object's state).  A name already held by a PLAIN gauge raises —
        silently dropping the callable would freeze the metric."""
        g = self._register(name, lambda: FnGauge(name, fn, help), "gauge")
        if not isinstance(g, FnGauge):
            raise ValueError(
                f"metric {name!r} already registered as a set-based "
                "gauge; gauge_fn cannot rebind it to a callable")
        g._fn = fn
        return g

    def histogram(self, name: str, buckets: Sequence[float],
                  help: str = "") -> Histogram:
        return self._register(
            name, lambda: Histogram(name, buckets, help), "histogram")

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def series_sum(self, name: str) -> float:
        """Sum of a metric's value across ALL its label sets — the
        scalar a dashboard wants from a labeled counter (e.g. total
        scale-ups regardless of ``pool=``).  0.0 for an unknown name or
        an empty metric; histograms sum their observation totals (the
        ``_count`` a Prometheus ``sum()`` over buckets would yield)."""
        m = self._metrics.get(name)
        if m is None:
            return 0.0
        if isinstance(m, Histogram):
            return float(sum(m._totals.values()))
        if not m._values and not isinstance(m, FnGauge):
            # Metric.series() yields a synthetic ((), 0.0) placeholder
            # for empty metrics; the SUM of nothing is a plain 0.0
            return 0.0
        return float(sum(v for _, v in m.series()))

    def __iter__(self) -> Iterator[Metric]:
        return iter(self._metrics.values())

    def __contains__(self, name: str) -> bool:
        return name in self._metrics

    def reset(self) -> None:
        """Zero every metric (registrations and bucket layouts stay)."""
        for m in self._metrics.values():
            m.reset()

    # ------------------------------------------------------------------
    # snapshots / export
    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view: scalar metrics map to their value (or a
        ``{label_str: value}`` dict when labeled), histograms to a
        summary with cumulative bucket counts."""
        out: Dict[str, Any] = {}
        for name, m in self._metrics.items():
            if isinstance(m, Histogram):
                if not m._counts:
                    out[name] = m.summary()
                elif list(m._counts) == [()]:
                    out[name] = m.summary()
                else:
                    out[name] = {
                        _prom_label_str(k) or "{}": {
                            "count": m._totals[k],
                            "sum": round(m._sums[k], 6)}
                        for k in sorted(m._counts)}
                continue
            vals = dict(m.series())
            if not vals:
                # a pull-based gauge with no current sample (FnGauge
                # returning None — e.g. memory_stats on a backend
                # without them) is ABSENT, not zero
                continue
            if list(vals) == [()]:
                v = vals[()]
                out[name] = int(v) if getattr(m, "int_valued", False) \
                    else round(v, 6)
            else:
                out[name] = {_prom_label_str(k) or "{}": round(v, 6)
                             for k, v in vals.items()}
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format, version 0.0.4."""
        lines: List[str] = []
        for name, m in self._metrics.items():
            pname = _prom_name(name)
            if m.help:
                lines.append(f"# HELP {pname} {m.help}")
            lines.append(f"# TYPE {pname} {m.kind}")
            if isinstance(m, Histogram):
                keys = sorted(m._counts) or [()]
                for k in keys:
                    for le, cum in m.bucket_counts(
                            **dict(k)).items():
                        lk = _prom_label_str(k + (("le", le),))
                        lines.append(f"{pname}_bucket{lk} {cum}")
                    ls = _prom_label_str(k)
                    lines.append(f"{pname}_sum{ls} "
                                 f"{_fmt(m._sums.get(k, 0.0))}")
                    lines.append(f"{pname}_count{ls} "
                                 f"{m._totals.get(k, 0)}")
            else:
                for k, v in m.series():
                    lines.append(f"{pname}{_prom_label_str(k)} {_fmt(v)}")
        return "\n".join(lines) + "\n"

    def write_jsonl(self, path: str, step: Optional[int] = None) -> None:
        """Append one snapshot line (``{"time", "step"?, "metrics"}``)."""
        rec: Dict[str, Any] = {"time": time.time(),
                               "metrics": self.snapshot()}
        if step is not None:
            rec["step"] = step
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    # ------------------------------------------------------------------
    # monitor fan-out
    # ------------------------------------------------------------------
    def scalar_events(self, step: int) -> List[Tuple[str, float, int]]:
        """(name, value, step) scalar triples in the ``monitor/`` event
        shape: counters/gauges as-is (labels suffixed into the name),
        histograms as ``_count`` / ``_sum`` / ``_p50``."""
        events: List[Tuple[str, float, int]] = []
        for name, m in self._metrics.items():
            if isinstance(m, Histogram):
                for k in sorted(m._counts) or [()]:
                    suffix = _prom_label_str(k)
                    lb = dict(k)
                    events.append((f"{name}{suffix}_count",
                                   float(m.count(**lb)), step))
                    events.append((f"{name}{suffix}_sum",
                                   m.sum(**lb), step))
                    events.append((f"{name}{suffix}_p50",
                                   m.percentile(0.5, **lb), step))
            else:
                for k, v in m.series():
                    events.append((f"{name}{_prom_label_str(k)}",
                                   float(v), step))
        return events

    def publish(self, monitor, step: int) -> None:
        """Fan the current values out through a ``monitor/`` writer
        (Monitor/MonitorMaster ``write_events`` interface) — serving
        metrics and training scalars ride the same CSV/TensorBoard/
        WandB pipeline."""
        if monitor is None:
            return
        monitor.write_events(self.scalar_events(step))


class CounterDictView(MutableMapping):
    """Dict-shaped façade over registry counters.

    ``engine.timings`` was a plain accumulator dict; it is now this view
    over first-class registry counters, so ``tm["stage_ms"] += dt`` and
    ``dict(engine.timings)`` keep working while ``engine.metrics`` holds
    the same numbers for Prometheus/JSONL export.  Int-valued counters
    (steps, token counts) read back as true ints."""

    def __init__(self, counters: Dict[str, Counter]):
        self._counters = dict(counters)

    def __getitem__(self, key: str):
        c = self._counters[key]
        v = c.value()
        return int(v) if c.int_valued else v

    def __setitem__(self, key: str, value) -> None:
        self._counters[key]._set(value)

    def __delitem__(self, key: str) -> None:
        raise TypeError("engine.timings keys are fixed; "
                        "register new metrics on engine.metrics instead")

    def __iter__(self) -> Iterator[str]:
        return iter(self._counters)

    def __len__(self) -> int:
        return len(self._counters)

    def __repr__(self) -> str:
        return f"CounterDictView({dict(self)!r})"

    def reset(self) -> None:
        for c in self._counters.values():
            c.reset()


# --------------------------------------------------------------------------
# exposition parser (round-trip testing / scrape tooling)
# --------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse text exposition back into
    ``{name: {"type": kind, "samples": {label_key: value}}}`` —
    histogram ``_bucket``/``_sum``/``_count`` samples fold back under
    their base metric name."""
    out: Dict[str, Dict[str, Any]] = {}
    types: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            types[name] = kind.strip()
            out.setdefault(name, {"type": kind.strip(), "samples": {}})
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name = m.group("name")
        labels: LabelKey = ()
        if m.group("labels"):
            labels = tuple(sorted(
                (k, v.replace('\\"', '"').replace("\\n", "\n")
                 .replace("\\\\", "\\"))
                for k, v in _LABEL_RE.findall(m.group("labels"))))
        value = float(m.group("value"))
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stem = name[:-len(suffix)] if name.endswith(suffix) else None
            if stem and types.get(stem) == "histogram":
                base = stem
                break
        rec = out.setdefault(base, {"type": types.get(base, "untyped"),
                                    "samples": {}})
        rec["samples"][(name, labels)] = value
    return out
