"""Streaming anomaly detection over per-step telemetry signals
(docs/OBSERVABILITY.md "Anomaly detection & deep capture").

PR 5 made the serving loop *observable* and PR 9 made the device and
compiler observable — but nothing watched those streams live: a latency
regression, retrace storm, or KV-pool leak was only discovered after
the fact by benchdiff or a crash dump.  This module is the watcher:
cheap streaming detectors the engines feed once per step with values
they already computed (no added clock reads), each firing a structured
:class:`AnomalyEvent` that the engine notes into the flight recorder,
counts (``serving_anomalies_total{signal=...}``), surfaces through
``engine.health()``, and — rate-limited — uses to arm a deep-capture
window (telemetry/profiler.py).

Three detector shapes, all **deterministic**: a detector consumes the
values it is fed and the integer step index, never a clock, so unit
tests drive them with a fake step counter and fixed value streams.

* :class:`EwmaMadDetector` — rolling median/MAD firing + EWMA trend;
  fires on ``|z| > z_threshold`` in the configured direction, where z
  is measured against the window MEDIAN (Hampel-style).  The robust
  default for latency-shaped signals (step interval, device ms, wait
  ms, TTFT/TPOT): the median ignores the compile-gap outliers that
  would drag a mean, the MAD ignores the spike it is about to flag,
  and the scale floor keeps a near-constant stream (MAD 0) from
  firing on noise.
* :class:`RollingPercentileDetector` — fires when a value leaves the
  rolling window's [q_low, q_high] band by a margin ratio.  The right
  shape for bounded rates (prefix hit rate, spec acceptance) where a
  *collapse* is the anomaly and absolute z-scores mean little.
* :class:`ThresholdDetector` — fires when a value crosses a fixed
  limit.  For signals where ANY occurrence is the anomaly (a runtime
  retrace after warmup).

:class:`AnomalyMonitor` owns the per-signal detector table, the
cooldown ledger (a fired signal is suppressed for ``cooldown``
subsequent samples — a pathological workload must not fire per step),
the bounded event ring, and the sustained-anomaly window
``engine.health()`` consults.  Everything here is host-side floats and
deques — no JAX imports, no device work (the telemetry/ contract).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Deque, Dict, Iterator, List, Optional, Tuple

# MAD -> sigma for a normal distribution; the usual robust-scale factor
_MAD_SIGMA = 1.4826


@dataclasses.dataclass
class AnomalyConfig:
    """Knobs shared by the default detector catalog and the monitor.

    ``warmup``: samples a detector must see before it may fire (the
    baseline is meaningless earlier).  ``window``: rolling-window length
    for MAD / percentile scale estimates.  ``ewma_alpha``: baseline
    smoothing.  ``z_threshold``: robust z-score a sample must exceed.
    ``cooldown``: per-signal samples suppressed after a fire.
    ``sustained_count`` within ``sustained_window`` steps flips
    ``engine.health()`` to degraded.  ``max_captures``: anomaly-armed
    deep-capture budget per engine (``reset_metrics`` rearms it);
    ``capture_steps``: length of each anomaly-armed capture window."""
    warmup: int = 16
    window: int = 64
    ewma_alpha: float = 0.05
    z_threshold: float = 8.0
    # relative + absolute floors under the MAD scale estimate: a
    # near-constant stream (MAD ~ 0) must not turn float jitter into
    # infinite z-scores
    min_scale_frac: float = 0.05
    min_scale: float = 1e-3
    cooldown: int = 32
    sustained_count: int = 3
    sustained_window: int = 128
    max_captures: int = 2
    capture_steps: int = 4


@dataclasses.dataclass
class AnomalyEvent:
    """One fired detector: what was observed vs. what the baseline
    promised, and how far out it was (robust z-score, or the band ratio
    for percentile detectors)."""
    signal: str
    step: int
    observed: float
    baseline: float
    score: float
    detector: str
    direction: str

    def as_dict(self) -> Dict[str, Any]:
        return {"signal": self.signal, "step": self.step,
                "observed": round(self.observed, 6),
                "baseline": round(self.baseline, 6),
                "score": round(self.score, 3),
                "detector": self.detector,
                "direction": self.direction}


class EwmaMadDetector:
    """EWMA trend + rolling median/MAD firing; fires on robust z-score.

    The *firing* reference is the rolling-window MEDIAN with a MAD
    scale (the Hampel shape): a few huge outliers — the compile gaps
    every serving engine's first steps contain — cannot poison it the
    way they drag a mean/EWMA, so a genuine 250 ms stall still reads
    as a spike against a 3 ms median even when the window remembers a
    15 s compile.  The EWMA is maintained as the smoothed trend
    (:attr:`baseline` — what dashboards want to plot), not the firing
    reference.  The score is computed against the window *before* the
    sample enters it, so one spike cannot hide itself; it does enter
    afterwards, which (with the cooldown upstream) naturally de-arms
    the detector while a shifted regime establishes a new normal."""

    kind = "ewma_mad"

    def __init__(self, warmup: int = 16, alpha: float = 0.05,
                 window: int = 64, z_threshold: float = 8.0,
                 direction: str = "high", min_scale_frac: float = 0.05,
                 min_scale: float = 1e-3):
        if direction not in ("high", "low", "both"):
            raise ValueError(f"direction={direction!r}")
        self.warmup = max(2, int(warmup))
        self.alpha = float(alpha)
        self.z_threshold = float(z_threshold)
        self.direction = direction
        self.min_scale_frac = float(min_scale_frac)
        self.min_scale = float(min_scale)
        self._win: Deque[float] = deque(maxlen=max(4, int(window)))
        self.reset()

    def reset(self) -> None:
        self._ewma: Optional[float] = None
        self._n = 0
        self._win.clear()

    @property
    def baseline(self) -> Optional[float]:
        """The EWMA trend (plot this; firing uses the median)."""
        return self._ewma

    def _center_scale(self) -> Tuple[float, float]:
        """Rolling median + floored MAD scale.  Both floors key off
        the MEDIAN, not the EWMA: a compile-gap-inflated trend must
        not inflate the band a real stall has to clear."""
        vals = sorted(self._win)
        n = len(vals)
        med = vals[n // 2] if n % 2 else 0.5 * (vals[n // 2 - 1]
                                                + vals[n // 2])
        dev = sorted(abs(v - med) for v in vals)
        mad = dev[n // 2] if n % 2 else 0.5 * (dev[n // 2 - 1]
                                               + dev[n // 2])
        return med, max(mad * _MAD_SIGMA, abs(med) * self.min_scale_frac,
                        self.min_scale)

    def observe(self, value: float) -> Optional[Tuple[float, float]]:
        """Feed one sample; returns ``(baseline, score)`` when the
        detector fires (baseline = the rolling median compared
        against), else None.  Always updates state — a cooldown
        upstream must not freeze the reference."""
        value = float(value)
        fired = None
        if self._n >= self.warmup and self._win:
            med, scale = self._center_scale()
            z = (value - med) / scale
            out = (z if self.direction == "high"
                   else -z if self.direction == "low" else abs(z))
            if out > self.z_threshold:
                fired = (med, z)
        self._n += 1
        self._ewma = value if self._ewma is None else \
            self._ewma + self.alpha * (value - self._ewma)
        self._win.append(value)
        return fired


class RollingPercentileDetector:
    """Fires when a sample leaves the rolling window's percentile band
    by ``ratio``: ``value > ratio * pct(q_high)`` (direction high) or
    ``value < pct(q_low) / ratio`` (direction low).  The score is the
    band-exceedance ratio."""

    kind = "rolling_pct"

    def __init__(self, warmup: int = 16, window: int = 64,
                 q: float = 0.95, ratio: float = 2.0,
                 direction: str = "low"):
        if direction not in ("high", "low"):
            raise ValueError(f"direction={direction!r}")
        self.warmup = max(2, int(warmup))
        self.q = float(q)
        self.ratio = float(ratio)
        self.direction = direction
        self._win: Deque[float] = deque(maxlen=max(4, int(window)))
        self.reset()

    def reset(self) -> None:
        self._n = 0
        self._win.clear()

    def _pct(self, q: float) -> float:
        vals = sorted(self._win)
        i = min(len(vals) - 1, max(0, int(q * (len(vals) - 1))))
        return vals[i]

    def observe(self, value: float) -> Optional[Tuple[float, float]]:
        value = float(value)
        fired = None
        if self._n >= self.warmup and self._win:
            if self.direction == "high":
                edge = self._pct(self.q)
                if value > self.ratio * edge and value > 0:
                    fired = (edge, value / max(edge, 1e-12))
            else:
                edge = self._pct(1.0 - self.q)
                if value * self.ratio < edge:
                    fired = (edge, edge / max(value, 1e-12))
        self._n += 1
        self._win.append(value)
        return fired


class ThresholdDetector:
    """Fires whenever a sample crosses a fixed ``limit`` (after
    ``warmup`` samples); the degenerate detector for signals where any
    occurrence IS the anomaly — e.g. the per-step runtime-retrace
    delta, whose healthy value is exactly zero."""

    kind = "threshold"

    def __init__(self, limit: float = 0.0, warmup: int = 0,
                 direction: str = "high"):
        if direction not in ("high", "low"):
            raise ValueError(f"direction={direction!r}")
        self.limit = float(limit)
        self.warmup = int(warmup)
        self.direction = direction
        self.reset()

    def reset(self) -> None:
        self._n = 0

    def observe(self, value: float) -> Optional[Tuple[float, float]]:
        value = float(value)
        fired = None
        if self._n >= self.warmup:
            if (value > self.limit if self.direction == "high"
                    else value < self.limit):
                fired = (self.limit, value - self.limit)
        self._n += 1
        return fired


def default_serving_detectors(cfg: AnomalyConfig) -> Dict[str, object]:
    """The serving-engine signal catalog (docs/OBSERVABILITY.md lists
    what each watches for).  All values are fed from timestamps and
    counters the loop already takes — enabling detection adds no clock
    reads to a warm step."""
    def lat(**kw):
        return EwmaMadDetector(
            warmup=cfg.warmup, alpha=cfg.ewma_alpha, window=cfg.window,
            z_threshold=cfg.z_threshold,
            min_scale_frac=cfg.min_scale_frac, min_scale=cfg.min_scale,
            **kw)

    return {
        # host stall / GC pause / injected latency spike: the gap
        # between consecutive dispatches
        "step_interval_ms": lat(direction="high"),
        # the device step itself got slower (shape drift, thermal
        # throttle, a losing autotune config)
        "step_device_ms": lat(direction="high"),
        # the host blocked longer on the collected step's readiness
        "step_wait_ms": lat(direction="high"),
        # schedule+stage host work per step (the depth-2 pipeline's
        # whole point is keeping this off the critical path)
        "step_host_ms": lat(direction="high"),
        "ttft_ms": lat(direction="high"),
        "tpot_ms": lat(direction="high"),
        # any runtime retrace after warmup is a storm signal (the
        # dynamic complement of tpulint's static retrace rule)
        "retrace": ThresholdDetector(limit=0.0, warmup=1),
        # KV-pool growth burst: referenced-block delta far above the
        # workload's baseline.  The scale floor is 8 whole blocks —
        # block counts are small integers and ordinary prefill
        # admissions grow the pool by a few per step, which must not
        # read as z=inf against a near-zero MAD
        "kv_referenced_delta": EwmaMadDetector(
            warmup=2 * cfg.warmup, alpha=cfg.ewma_alpha,
            window=cfg.window, z_threshold=cfg.z_threshold,
            min_scale_frac=cfg.min_scale_frac, min_scale=8.0,
            direction="high"),
        # prefix-cache hit-rate collapse (an eviction storm, a routing
        # change upstream): per-admission hit rate leaves the band
        "prefix_hit_rate": RollingPercentileDetector(
            warmup=cfg.warmup, window=cfg.window, q=0.95, ratio=2.0,
            direction="low"),
        # speculative acceptance collapse: drafts stopped matching
        "spec_acceptance": RollingPercentileDetector(
            warmup=cfg.warmup, window=cfg.window, q=0.95, ratio=2.0,
            direction="low"),
    }


def default_training_detectors(cfg: AnomalyConfig) -> Dict[str, object]:
    """Training-engine catalog: the step's host phases and the retrace
    storm signal (the fused train step leaves little else visible
    host-side; device captures answer the *why*)."""
    def lat(**kw):
        return EwmaMadDetector(
            warmup=cfg.warmup, alpha=cfg.ewma_alpha, window=cfg.window,
            z_threshold=cfg.z_threshold,
            min_scale_frac=cfg.min_scale_frac, min_scale=cfg.min_scale,
            **kw)

    return {
        "step_interval_ms": lat(direction="high"),
        "step_host_ms": lat(direction="high"),
        "retrace": ThresholdDetector(limit=0.0, warmup=1),
    }


class AnomalyMonitor:
    """Per-engine detector table + cooldown + event ring + sustained
    window.

    ``observe(signal, value, step)`` feeds one sample and returns the
    fired :class:`AnomalyEvent` (already counted and ring-recorded) or
    None.  A fired signal is suppressed — but its detector keeps
    learning — for ``cfg.cooldown`` subsequent samples.  ``sustained()``
    answers whether enough events fired within the recent window to
    call the engine degraded.  ``registry``: when given, fires bump a
    labeled ``<prefix>_anomalies_total`` counter so the events are
    scrape-visible next to every other serving metric."""

    def __init__(self, cfg: Optional[AnomalyConfig] = None,
                 registry=None, prefix: str = "serving",
                 event_capacity: int = 256):
        self.cfg = cfg or AnomalyConfig()
        self.prefix = prefix
        self._detectors: Dict[str, object] = {}
        self._cooldown_until: Dict[str, int] = {}
        self.counts: Dict[str, int] = {}
        self.events: Deque[AnomalyEvent] = deque(maxlen=event_capacity)
        self._fire_steps: Deque[int] = deque(maxlen=256)
        self._counter = None
        if registry is not None:
            self._counter = registry.counter(
                f"{prefix}_anomalies_total",
                "anomaly-detector fires by signal", int_valued=True)

    def watch(self, signal: str, detector) -> None:
        self._detectors[signal] = detector

    def watch_all(self, detectors: Dict[str, object]) -> None:
        for s, d in detectors.items():
            self.watch(s, d)

    @property
    def signals(self) -> List[str]:
        return list(self._detectors)

    def observe(self, signal: str, value: float,
                step: int) -> Optional[AnomalyEvent]:
        det = self._detectors.get(signal)
        if det is None:
            return None
        fired = det.observe(value)
        if fired is None:
            return None
        if step < self._cooldown_until.get(signal, -1):
            return None                      # suppressed, still learned
        baseline, score = fired
        self._cooldown_until[signal] = step + self.cfg.cooldown
        ev = AnomalyEvent(signal=signal, step=int(step),
                          observed=float(value),
                          baseline=float(baseline) if baseline is not None
                          else 0.0,
                          score=float(score), detector=det.kind,
                          direction=getattr(det, "direction", "high"))
        self.counts[signal] = self.counts.get(signal, 0) + 1
        self.events.append(ev)
        self._fire_steps.append(int(step))
        if self._counter is not None:
            self._counter.inc(signal=signal)
        return ev

    def total(self) -> int:
        return sum(self.counts.values())

    def sustained(self, step: int) -> bool:
        """True when ``sustained_count`` events fired within the last
        ``sustained_window`` steps — the health() degradation bar."""
        recent = sum(1 for s in self._fire_steps
                     if step - s <= self.cfg.sustained_window)
        return recent >= self.cfg.sustained_count

    def summary(self) -> Dict[str, Any]:
        """JSON-able tally for bench legs / SLO sweeps / health."""
        return {"total": self.total(),
                "by_signal": dict(self.counts),
                "recent": [e.as_dict() for e in list(self.events)[-8:]]}

    def reset(self) -> None:
        """Full rearm (``engine.reset_metrics``): detector baselines,
        cooldowns, counts, and the event ring all restart — a bench
        leg's timed region watches with fresh eyes.  The registry
        counter resets with the registry itself."""
        for det in self._detectors.values():
            det.reset()
        self._cooldown_until.clear()
        self.counts.clear()
        self.events.clear()
        self._fire_steps.clear()

    def __iter__(self) -> Iterator[AnomalyEvent]:
        return iter(self.events)
