"""Device & compiler observability (docs/OBSERVABILITY.md "Device &
compiler telemetry").

PR 5's telemetry sees *when* the host waits; this module sees *what the
device and compiler are doing*: per-program ``compiled.cost_analysis()``
(flops / bytes accessed / HLO size), derived achieved-utilization gauges
(``serving_mfu`` / ``serving_hbm_bw_util`` — computed at *read* time
from the existing step-timing counters, never on the hot path), and
``device.memory_stats()`` polled at phase boundaries (the probe pattern
of ``runtime/runtime_utils.py:see_memory_usage`` — one host call, no
device sync).  These are exactly the profiling-derived signals
DeepCompile (arxiv 2504.09983) argues an autotuner must consume, and
the live complement of the bench's one-shot MFU number.

Design constraints, same priority order as the rest of telemetry/:

* **Zero cost when off.**  An engine with device telemetry disabled
  constructs NO :class:`DeviceTelemetry` — no ``cost_analysis`` calls,
  no memory polls, no clock reads added anywhere
  (tests/test_device_telemetry.py holds the bar).
* **Loud-but-graceful degradation.**  Every probe is best-effort per
  backend: CPU has ``cost_analysis`` but no ``memory_stats`` (returns
  None) and no published peak — missing inputs make the derived gauges
  ABSENT from the exposition (FnGauge's ``None`` contract), never zero
  and never a crash.  One warning per engine per missing capability.
* **Probe at boundaries, read at export.**  ``cost_analysis`` runs once
  per compiled program (an explicit AOT lower+compile of an
  already-warm program — host/compiler work only); memory polls run at
  engine phase boundaries (health checks, dumps, bench captures) —
  never inside a serving-loop-marked method.

The compile/retrace *counters* deliberately do NOT live here: they are
plain host counter bumps on the engines' existing executable-cache fill
paths, cheap enough to stay always-on like the rest of the registry.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ..utils.logging import logger
from .metrics import MetricsRegistry

# bf16 peak FLOP/s and HBM bandwidth (bytes/s) per chip generation —
# the same table bench.py uses for its one-shot MFU, here feeding the
# live gauges.  Matched by substring against device_kind (lowercased);
# unknown kinds (CPU fallback included) yield None -> absent gauges.
PEAK_FLOPS = {"v4": 275e12, "v5 lite": 197e12, "v5e": 197e12,
              "v5p": 459e12, "v5": 459e12, "v6e": 918e12, "v6": 918e12}
PEAK_HBM_BW = {"v4": 1.2e12, "v5 lite": 0.82e12, "v5e": 0.82e12,
               "v5p": 2.77e12, "v5": 2.77e12, "v6e": 1.64e12,
               "v6": 1.64e12}


def _match_peak(table: Dict[str, float], kind: str) -> Optional[float]:
    kind = (kind or "").lower()
    for k, v in table.items():
        if k in kind:
            return v
    return None


def peak_flops(device=None) -> Optional[float]:
    """Published bf16 peak FLOP/s for ``device`` (default: the default
    backend's first device); None when unknown — CPU and virtualized
    kinds have no honest peak, and a made-up one would make the MFU
    gauge a lie."""
    d = device if device is not None else _default_device()
    if d is None:
        return None
    return _match_peak(PEAK_FLOPS, getattr(d, "device_kind", ""))


def peak_hbm_bw(device=None) -> Optional[float]:
    """Published HBM bandwidth (bytes/s); None when unknown."""
    d = device if device is not None else _default_device()
    if d is None:
        return None
    return _match_peak(PEAK_HBM_BW, getattr(d, "device_kind", ""))


def _default_device():
    try:
        import jax
        return jax.devices()[0]
    except Exception as e:
        logger.warning("device telemetry: no default device (%s)",
                       type(e).__name__)
        return None


def cost_analysis_of(compiled) -> Dict[str, float]:
    """Robust extraction from a ``jax.stages.Compiled``: whatever of
    ``flops`` / ``bytes_accessed`` / ``peak_bytes`` / ``hlo_bytes`` the
    backend reports — missing fields are ABSENT from the dict, never
    zero-filled (an absent field keeps its derived gauge absent)."""
    out: Dict[str, float] = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if "flops" in cost:
            out["flops"] = float(cost["flops"])
        if "bytes accessed" in cost:
            out["bytes_accessed"] = float(cost["bytes accessed"])
    except Exception as e:
        logger.warning("cost_analysis unavailable on this backend: %r", e)
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out["peak_bytes"] = float(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0))
    except Exception as e:
        logger.debug("memory_analysis unavailable: %r", e)
    try:
        out["hlo_bytes"] = float(len(compiled.as_text()))
    except Exception as e:
        logger.debug("compiled.as_text unavailable: %r", e)
    return out


def poll_memory_stats() -> Dict[str, Dict[str, int]]:
    """``device.memory_stats()`` for every local device, keyed by device
    id — the ``see_memory_usage`` probe shape (one host call per device,
    never a device sync).  Devices that report None (CPU) are simply
    absent from the result."""
    import jax

    out: Dict[str, Dict[str, int]] = {}
    for d in jax.local_devices():
        try:
            s = d.memory_stats()
        except Exception as e:
            logger.debug("memory_stats unavailable on %s: %r", d, e)
            s = None
        if s:
            out[str(d.id)] = {
                "bytes_in_use": int(s.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(s.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(s.get("bytes_limit", 0)),
            }
    return out


class DeviceTelemetry:
    """The gated half of device observability for ONE engine: program
    cost table, per-step flop/byte accumulation, derived utilization
    gauges, and memory-stat polling.  Constructed ONLY when device
    telemetry is enabled — a disabled engine holds ``None`` and pays
    nothing.

    ``prefix``: ``"serving"`` or ``"training"`` — the metric-name
    family (tpulint's ``metric-name`` rule).  ``step_ms_fn``: zero-arg
    callable returning the cumulative device-busy milliseconds the
    utilization gauges divide by (the engines pass their existing
    ``device_ms + wait_ms`` counters — read at export time, so the hot
    path takes no new clock reads).  ``peak_flops``/``peak_hbm_bw``:
    explicit overrides (tests; rigs whose kind string lies), default
    resolved from the default device — None leaves the corresponding
    gauge absent."""

    def __init__(self, registry: MetricsRegistry, prefix: str,
                 step_ms_fn, peak_flops: Optional[float] = None,
                 peak_hbm_bw: Optional[float] = None,
                 device=None):
        self.registry = registry
        self.prefix = prefix
        self._step_ms_fn = step_ms_fn
        dev = device if device is not None else _default_device()
        kind = getattr(dev, "device_kind", "")
        self.peak_flops = peak_flops if peak_flops is not None \
            else _match_peak(PEAK_FLOPS, kind)
        self.peak_hbm_bw = peak_hbm_bw if peak_hbm_bw is not None \
            else _match_peak(PEAK_HBM_BW, kind)
        if self.peak_flops is None:
            logger.warning(
                "device telemetry: no published peak for device kind "
                "%r — %s_mfu/%s_hbm_bw_util gauges stay absent",
                getattr(dev, "device_kind", "?"), prefix, prefix)
        # program-key -> cost dict (flops/bytes_accessed/peak_bytes/...)
        self.program_costs: Dict[Any, Dict[str, float]] = {}
        # dispatched work attributed from the cost table (counters so
        # snapshots/JSONL see them; bumped once per dispatch — a dict
        # lookup + two adds, only when telemetry is ON)
        self._c_flops = registry.counter(
            f"{prefix}_model_flops_total",
            "model FLOPs dispatched, attributed from per-program "
            "cost_analysis")
        self._c_bytes = registry.counter(
            f"{prefix}_hbm_bytes_total",
            "HBM bytes accessed by dispatched programs, attributed "
            "from per-program cost_analysis")
        registry.gauge_fn(
            f"{prefix}_mfu", self._mfu,
            "achieved model-FLOPs utilization over the measured steps "
            "(cost-analysis flops / device-busy time / published peak; "
            "absent when the backend reports no flops or has no "
            "published peak)")
        registry.gauge_fn(
            f"{prefix}_hbm_bw_util", self._bw_util,
            "achieved HBM bandwidth utilization (cost-analysis bytes "
            "accessed / device-busy time / published peak bandwidth; "
            "absent when unavailable)")
        # memory gauges are registered lazily on the first poll that
        # actually returns data, so a backend without memory_stats
        # (CPU) exports NO fake zero series
        self._mem_registered = False
        self._warned_mem = False

    # ---- compile observatory ------------------------------------------
    def probe_program(self, key, jitted, args) -> Dict[str, float]:
        """Record one compiled program's cost analysis (memoized by
        ``key``).  Runs an explicit AOT ``lower(*args).compile()`` on
        the already-warm jit function — the ONE deliberately-paid
        duplicate compile per program, bought only when device
        telemetry is on, outside any timed/hot region (see the
        cost-analysis caveats in docs/OBSERVABILITY.md)."""
        cached = self.program_costs.get(key)
        if cached is not None:
            return cached
        import time
        cost: Dict[str, float] = {}
        try:
            t0 = time.perf_counter()
            compiled = jitted.lower(*args).compile()
            cost = cost_analysis_of(compiled)
            cost["compile_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 3)
        except Exception as e:
            logger.warning("device telemetry: cost probe failed for "
                           "%r (%s: %s)", key, type(e).__name__,
                           str(e).splitlines()[0][:120] if str(e) else "")
        self.program_costs[key] = cost
        return cost

    def on_dispatch(self, key, n: int = 1) -> None:
        """Attribute one dispatched execution of program ``key`` (``n``
        model invocations for burst scans) to the flop/byte counters."""
        cost = self.program_costs.get(key)
        if not cost:
            return
        f = cost.get("flops")
        b = cost.get("bytes_accessed")
        if f:
            self._c_flops.inc(f * n)
        if b:
            self._c_bytes.inc(b * n)

    # ---- derived utilization gauges (read-time, FnGauge) --------------
    def _busy_s(self) -> Optional[float]:
        try:
            ms = float(self._step_ms_fn())
        except Exception:  # tpulint: disable=silent-except — a dead engine's counters read as no sample
            return None
        return ms / 1e3 if ms > 0 else None

    def _mfu(self) -> Optional[float]:
        busy = self._busy_s()
        flops = self._c_flops.value()
        if busy is None or not flops or not self.peak_flops:
            return None
        return flops / busy / self.peak_flops

    def _bw_util(self) -> Optional[float]:
        busy = self._busy_s()
        nbytes = self._c_bytes.value()
        if busy is None or not nbytes or not self.peak_hbm_bw:
            return None
        return nbytes / busy / self.peak_hbm_bw

    # ---- memory accounting --------------------------------------------
    def poll_memory(self) -> Dict[str, Dict[str, int]]:
        """Poll ``memory_stats`` for every local device and publish the
        per-device gauges (labeled by device id).  Called at phase
        boundaries only — engine health checks, drains, dumps, bench
        captures — never per step.  On backends without memory stats
        this warns ONCE and the gauges stay absent."""
        stats = poll_memory_stats()
        if not stats:
            if not self._warned_mem:
                self._warned_mem = True
                logger.warning(
                    "device telemetry: memory_stats unavailable on "
                    "this backend — %s_hbm_* gauges stay absent",
                    self.prefix)
            return stats
        if not self._mem_registered:
            self._mem_registered = True
            p = self.prefix
            self._g_in_use = self.registry.gauge(
                f"{p}_hbm_bytes_in_use", "device bytes in use at the "
                "last phase-boundary poll")
            self._g_peak = self.registry.gauge(
                f"{p}_hbm_peak_bytes_in_use",
                "peak device bytes in use")
            self._g_limit = self.registry.gauge(
                f"{p}_hbm_bytes_limit", "device memory capacity")
        for did, s in stats.items():
            self._g_in_use.set(s["bytes_in_use"], device=did)
            self._g_peak.set(s["peak_bytes_in_use"], device=did)
            self._g_limit.set(s["bytes_limit"], device=did)
        return stats

    # ---- export --------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able device-telemetry summary (what bench legs embed):
        per-program costs, the derived utilizations (None when absent),
        and the last memory poll."""
        mfu = self._mfu()
        bw = self._bw_util()
        return {
            "programs": {self._key_str(k): dict(v)
                         for k, v in self.program_costs.items()},
            "model_flops_total": self._c_flops.value(),
            "hbm_bytes_total": self._c_bytes.value(),
            "mfu": None if mfu is None else round(mfu, 6),
            "hbm_bw_util": None if bw is None else round(bw, 6),
            "peak_flops": self.peak_flops,
            "peak_hbm_bw": self.peak_hbm_bw,
            "memory": self.poll_memory(),
        }

    @staticmethod
    def _key_str(key) -> str:
        return key if isinstance(key, str) else repr(key)
