"""Low-overhead host-side telemetry: span tracing, metrics, request
lifecycle records (docs/OBSERVABILITY.md).

Three pieces, composable and JAX-free:

* :class:`SpanTracer` — ring-buffer span tracer on ``perf_counter_ns``
  with Chrome-trace / JSONL export (serving-loop + training-step
  phases).
* :class:`MetricsRegistry` — labeled counters / gauges / fixed-bucket
  histograms with Prometheus text exposition, JSONL snapshots, and
  fan-out through the ``monitor/`` writer interface.
* :class:`RequestTracker` — per-request lifecycle records (TTFT / TPOT /
  queue wait / token accounting) for the inference engine.
"""

from .metrics import (Counter, CounterDictView, FnGauge, Gauge, Histogram,
                      MetricsRegistry, parse_prometheus_text)
from .lifecycle import (QUEUE_WAIT_BUCKETS_MS, RequestRecord,
                        RequestTracker, TERMINAL_STATUSES,
                        TPOT_BUCKETS_MS, TTFT_BUCKETS_MS)
from .tracer import SpanTracer
from .device import (DeviceTelemetry, cost_analysis_of, peak_flops,
                     peak_hbm_bw, poll_memory_stats)
from .flight import (FlightRecorder, config_fingerprint,
                     validate_flight_dump)
from .anomaly import (AnomalyConfig, AnomalyEvent, AnomalyMonitor,
                      EwmaMadDetector, RollingPercentileDetector,
                      ThresholdDetector, default_serving_detectors,
                      default_training_detectors)
from .profiler import ProfilerCapture, profiler_available
from .slo import (BurnRateDetector, DEFAULT_SLO_CLASS, SloObjective,
                  SloTracker, default_slo_objectives, merge_scorecards)

__all__ = ["SpanTracer", "MetricsRegistry", "Counter", "Gauge", "FnGauge",
           "Histogram", "CounterDictView", "parse_prometheus_text",
           "RequestTracker", "RequestRecord", "TERMINAL_STATUSES",
           "TTFT_BUCKETS_MS", "TPOT_BUCKETS_MS", "QUEUE_WAIT_BUCKETS_MS",
           "DeviceTelemetry", "cost_analysis_of", "peak_flops",
           "peak_hbm_bw", "poll_memory_stats", "FlightRecorder",
           "config_fingerprint", "validate_flight_dump",
           "AnomalyConfig", "AnomalyEvent", "AnomalyMonitor",
           "EwmaMadDetector", "RollingPercentileDetector",
           "ThresholdDetector", "default_serving_detectors",
           "default_training_detectors", "ProfilerCapture",
           "profiler_available", "SloObjective", "SloTracker",
           "BurnRateDetector", "DEFAULT_SLO_CLASS",
           "default_slo_objectives", "merge_scorecards"]
