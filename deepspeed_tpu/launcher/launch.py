"""Node-local launcher (reference: ``launcher/launch.py:133`` main —
spawns one proc per GPU, sets RANK env, signal handling,
``terminate_process_tree`` :119).

On TPU there is exactly one process per host: this module reads the
coordinator env set by the runner, initializes ``jax.distributed``, and
execs the user script in-process.  Signal handling forwards
SIGTERM/SIGINT to the child process group when the script is run as a
subprocess (``--as_subprocess``).
"""

from __future__ import annotations

import os
import runpy
import signal
import subprocess
import sys
from typing import List, Optional

from ..utils.logging import logger


def terminate_process_tree(proc: subprocess.Popen) -> None:
    """(reference: launch.py:119) — SIGTERM the child's process group,
    SIGKILL after a grace period."""
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass


def resolve_process_id() -> int:
    """Rank resolution order: explicit DSPD_PROCESS_ID (ssh/local
    runners) > SLURM_PROCID (srun) > position of this hostname in
    DSPD_HOSTS (pdsh broadcast, which can't set per-host env)."""
    pid = os.environ.get("DSPD_PROCESS_ID")
    if pid is not None:
        return int(pid)
    slurm = os.environ.get("SLURM_PROCID")
    if slurm is not None:
        return int(slurm)
    hosts = os.environ.get("DSPD_HOSTS", "")
    if hosts:
        import socket
        names = hosts.split(",")
        me = socket.gethostname()
        for i, h in enumerate(names):
            if h == me or h == me.split(".")[0] or me.startswith(h + "."):
                return i
        raise RuntimeError(f"hostname {me!r} not in DSPD_HOSTS={hosts!r}")
    return 0


def init_distributed_from_env() -> None:
    """Wire DSPD_* env (set by the runner) into jax.distributed."""
    coord = os.environ.get("DSPD_COORDINATOR")
    if not coord:
        return
    import jax

    pid = resolve_process_id()
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ.get("DSPD_NUM_PROCESSES", "1")),
        process_id=pid)
    logger.info("jax.distributed up: process %s/%s via %s", pid,
                os.environ.get("DSPD_NUM_PROCESSES"), coord)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_subprocess = "--as_subprocess" in argv
    if as_subprocess:
        argv.remove("--as_subprocess")
    if not argv:
        print(  # tpulint: disable=print — CLI usage text
            "usage: python -m deepspeed_tpu.launcher.launch script.py ...",
            file=sys.stderr)
        return 2
    script, *script_args = argv

    if as_subprocess:
        proc = subprocess.Popen([sys.executable, script, *script_args],
                                start_new_session=True)

        def handler(signum, frame):
            terminate_process_tree(proc)
            raise SystemExit(128 + signum)

        signal.signal(signal.SIGTERM, handler)
        signal.signal(signal.SIGINT, handler)
        return proc.wait()

    init_distributed_from_env()
    sys.argv = [script, *script_args]
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
