from .runner import (build_parser, main, parse_hostfile,
                     parse_inclusion_exclusion, RUNNERS)
from .launch import init_distributed_from_env, terminate_process_tree

__all__ = ["main", "build_parser", "parse_hostfile",
           "parse_inclusion_exclusion", "RUNNERS",
           "init_distributed_from_env", "terminate_process_tree"]
