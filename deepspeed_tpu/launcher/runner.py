"""Multi-host launcher — the ``deepspeed`` CLI analog.

TPU-native re-design of the reference launcher
(``launcher/runner.py:398`` main — hostfile parse :210, --include/
--exclude filters :265, runner selection ``multinode_runner.py:51-376``;
node-local ``launcher/launch.py:133``).  The structural difference
(SURVEY §7): TPU pods run **one process per host** with
``jax.distributed.initialize`` — there is no per-device process spawn, so
the node-local launcher sets coordinator env vars and execs the script
once, and "slots" count hosts' local devices only for bookkeeping.

CLI::

    python -m deepspeed_tpu.launcher.runner \
        --hostfile hosts.txt --include "worker-[0-3]" train.py --args...
"""

from __future__ import annotations

import argparse
import os
import re
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger

DEFAULT_COORD_PORT = 29500


# --------------------------------------------------------------------------
# hostfile (reference: launcher/runner.py:210 parse_resource_filter et al.)
# --------------------------------------------------------------------------

def parse_hostfile(text: str) -> "OrderedDict[str, int]":
    """``hostname slots=N`` per line; '#' comments
    (reference: runner.py fetch_hostfile)."""
    hosts: "OrderedDict[str, int]" = OrderedDict()
    for line in text.splitlines():
        line = line.split("#")[0].strip()
        if not line:
            continue
        m = re.match(r"^(\S+)(?:\s+slots=(\d+))?$", line)
        if m is None:
            raise ValueError(f"bad hostfile line: {line!r}")
        hosts[m.group(1)] = int(m.group(2) or 1)
    if not hosts:
        raise ValueError("hostfile is empty")
    return hosts


def _expand_brackets(pat: str) -> List[str]:
    """worker-[0-3] -> worker-0..worker-3 (pdsh-style ranges)."""
    m = re.match(r"^(.*)\[(\d+)-(\d+)\](.*)$", pat)
    if not m:
        return [pat]
    pre, lo, hi, post = m.groups()
    return [f"{pre}{i}{post}" for i in range(int(lo), int(hi) + 1)]


def parse_inclusion_exclusion(hosts: "OrderedDict[str, int]",
                              include: str = "",
                              exclude: str = "") -> "OrderedDict[str, int]":
    """Filter hosts (reference: runner.py:265 parse_resource_filter).

    Syntax: ``host1@host2`` or ranges ``worker-[0-3]``; ``host:0,1``
    selects local device slots on that host.
    """
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")

    def parse(sel: str) -> Dict[str, Optional[List[int]]]:
        out: Dict[str, Optional[List[int]]] = {}
        for term in sel.split("@"):
            term = term.strip()
            if not term:
                continue
            if ":" in term:
                name, slots = term.split(":")
                idx = [int(s) for s in slots.split(",")]
            else:
                name, idx = term, None
            for h in _expand_brackets(name):
                out[h] = idx
        return out

    if include:
        sel = parse(include)
        result: "OrderedDict[str, int]" = OrderedDict()
        for h, idx in sel.items():
            if h not in hosts:
                raise ValueError(f"include host {h!r} not in hostfile")
            result[h] = len(idx) if idx is not None else hosts[h]
        return result
    if exclude:
        sel = parse(exclude)
        result = OrderedDict()
        for h, n in hosts.items():
            if h in sel:
                idx = sel[h]
                if idx is None:
                    continue                       # whole host excluded
                left = n - len(idx)
                if left > 0:
                    result[h] = left
            else:
                result[h] = n
        if not result:
            raise ValueError("--exclude removed every host")
        return result
    return hosts


# --------------------------------------------------------------------------
# runners (reference: launcher/multinode_runner.py PDSH/MPI/SLURM variants)
# --------------------------------------------------------------------------

class MultiNodeRunner:
    """Builds the per-job command; subclasses differ in transport."""

    name = "base"

    def __init__(self, args, hosts: "OrderedDict[str, int]"):
        self.args = args
        self.hosts = hosts
        self.exports: Dict[str, str] = {}

    def add_export(self, key: str, value: str) -> None:
        self.exports[key] = str(value)

    @property
    def coordinator(self) -> str:
        host = self.args.master_addr or next(iter(self.hosts))
        return f"{host}:{self.args.master_port}"

    def node_cmd(self, host: str, rank: int) -> List[str]:
        """Command run on one host (process_id = host rank; rank=-1 means
        the node derives it itself from DSPD_HOSTS/SLURM_PROCID)."""
        env = dict(self.exports)
        env["DSPD_COORDINATOR"] = self.coordinator
        env["DSPD_NUM_PROCESSES"] = str(len(self.hosts))
        if rank >= 0:
            env["DSPD_PROCESS_ID"] = str(rank)
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        script = " ".join([shlex.quote(self.args.user_script),
                           *map(shlex.quote, self.args.user_args)])
        return ["bash", "-c",
                f"cd {shlex.quote(os.getcwd())} && env {exports} "
                f"{sys.executable} -m deepspeed_tpu.launcher.launch {script}"]

    def launch_cmds(self) -> List[Tuple[str, List[str]]]:
        return [(h, self._wrap(h, self.node_cmd(h, i)))
                for i, h in enumerate(self.hosts)]

    def _wrap(self, host: str, cmd: List[str]) -> List[str]:
        raise NotImplementedError


class LocalRunner(MultiNodeRunner):
    """Single host, no ssh (reference: runner.py local fallback)."""
    name = "local"

    def _wrap(self, host, cmd):
        return cmd


class SSHRunner(MultiNodeRunner):
    """Plain ssh per host (reference: PDSHRunner's transport, pdsh-free)."""
    name = "ssh"

    def _wrap(self, host, cmd):
        return ["ssh", "-o", "StrictHostKeyChecking=no", host,
                " ".join(shlex.quote(c) for c in cmd)]


class PDSHRunner(MultiNodeRunner):
    """(reference: multinode_runner.py:51 PDSHRunner).

    pdsh broadcasts ONE command to every host, so the per-host rank
    cannot ride the env: instead DSPD_HOSTS carries the ordered host
    list and launch.py derives process_id from the local hostname."""
    name = "pdsh"

    def launch_cmds(self):
        hostlist = ",".join(self.hosts)
        self.add_export("DSPD_HOSTS", hostlist)
        cmd = self.node_cmd(hostlist, rank=-1)   # rank resolved on-node
        quoted = " ".join(shlex.quote(c) for c in cmd)
        return [(hostlist, ["pdsh", "-S", "-w", hostlist, quoted])]


class SlurmRunner(MultiNodeRunner):
    """(reference: multinode_runner.py SlurmRunner via srun).  Rank comes
    from SLURM_PROCID on each task (read by launch.py)."""
    name = "slurm"

    def launch_cmds(self):
        n = len(self.hosts)
        cmd = self.node_cmd(next(iter(self.hosts)), rank=-1)
        return [("slurm", ["srun", f"--nodes={n}", f"--ntasks={n}",
                           "--ntasks-per-node=1"] + cmd)]


RUNNERS = {c.name: c for c in (LocalRunner, SSHRunner, PDSHRunner,
                               SlurmRunner)}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="deepspeed_tpu",
        description="multi-host TPU launcher (deepspeed CLI analog)")
    p.add_argument("--hostfile", type=str, default="")
    p.add_argument("--include", type=str, default="")
    p.add_argument("--exclude", type=str, default="")
    p.add_argument("--num_nodes", type=int, default=-1)
    p.add_argument("--master_addr", type=str, default="")
    p.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    p.add_argument("--launcher", type=str, default="ssh",
                   choices=sorted(RUNNERS))
    p.add_argument("--force_multi", action="store_true")
    # elastic agent (reference: elasticity/elastic_agent.py:32
    # DSElasticAgent; runner.py:383 --elastic_training): when any node
    # process dies, the whole worker group is torn down and relaunched —
    # the training script resumes from its latest (universal) checkpoint
    p.add_argument("--elastic_training", "--elastic", action="store_true",
                   dest="elastic_training")
    p.add_argument("--max_elastic_restarts", type=int, default=100)
    p.add_argument("user_script", type=str)
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p


def _run_group(runner: MultiNodeRunner) -> int:
    """Launch one worker group and babysit it: returns 0 when every node
    process exits clean; on the FIRST failure the surviving processes are
    torn down (the reference agent's stop-workers step) and the failing
    rc is returned."""
    import time as _time

    procs = [subprocess.Popen(cmd) for _, cmd in runner.launch_cmds()]
    try:
        while True:
            rcs = [p.poll() for p in procs]
            bad = [rc for rc in rcs if rc not in (None, 0)]
            if bad:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    p.wait()
                return bad[0]
            if all(rc == 0 for rc in rcs):
                return 0
            _time.sleep(0.2)
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            p.wait()
        raise


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = parse_hostfile(f.read())
    else:
        hosts = OrderedDict([("localhost", 1)])
    hosts = parse_inclusion_exclusion(hosts, args.include, args.exclude)
    if args.num_nodes > 0:
        hosts = OrderedDict(list(hosts.items())[:args.num_nodes])

    if len(hosts) == 1 and not args.force_multi:
        runner: MultiNodeRunner = LocalRunner(args, hosts)
    else:
        runner = RUNNERS[args.launcher](args, hosts)
    logger.info("launching on %d host(s) via %s: %s",
                len(hosts), runner.name, list(hosts))

    if not args.elastic_training:
        procs = [subprocess.Popen(cmd)
                 for _, cmd in runner.launch_cmds()]
        rc = 0
        try:
            for p in procs:
                rc = p.wait() or rc
        except KeyboardInterrupt:
            for p in procs:
                p.terminate()
            rc = 1
        return rc
    try:
        # elastic: relaunch the worker group until it exits clean or the
        # restart budget runs out (reference: DSElasticAgent._invoke_run
        # monitor/restart loop); resumption happens inside the user
        # script via its latest checkpoint
        attempt = 0
        while True:
            rc = _run_group(runner)
            if rc == 0:
                return 0
            attempt += 1
            if attempt > args.max_elastic_restarts:
                logger.error("elastic: restart budget exhausted "
                             "(%d); giving up with rc=%d",
                             args.max_elastic_restarts, rc)
                return rc
            logger.warning("elastic: worker group failed (rc=%d); "
                           "restart %d/%d", rc, attempt,
                           args.max_elastic_restarts)
    except KeyboardInterrupt:
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
