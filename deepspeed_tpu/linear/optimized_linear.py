"""OptimizedLinear: LoRA adapters over quantized frozen base weights.

TPU-native equivalent of the reference ``deepspeed/linear``
(``linear/optimized_linear.py`` — LoRAOptimizedLinear with
``LoRAConfig(lora_r, lora_alpha, base_weight_sharding)``;
``linear/quantization.py`` QuantizedParameter via the fp_quantizer op
``csrc/fp_quantizer/fp_quantize.cpp``; config classes ``linear/config.py``).

Functional formulation: the base weight is stored quantized (int8 groups
or fp8) and dequantized on use — XLA fuses the dequant into the matmul
epilogue; only the LoRA factors train.  ``y = x @ W_q + (alpha/r) x A B``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.quant import QuantizedTensor, dequantize, fp_quantize, quantize


@dataclass
class LoRAConfig:
    """(reference: linear/config.py LoRAConfig)."""
    lora_r: int = 64
    lora_alpha: float = 16.0
    # reference shards the frozen base over this many ranks; here the
    # base follows normal logical-axis sharding, kept for config parity
    base_weight_sharding: int = 1


@dataclass
class QuantizationConfig:
    """(reference: linear/config.py QuantizationConfig — q_bits 6/8/12
    via fp_quantizer).  TPU formats: grouped int8/int4, or fp8."""
    q_bits: int = 8
    group_size: int = 512
    format: str = "int"            # int | fp8_e4m3 | fp8_e5m2


def quantize_base(w: jax.Array,
                  qcfg: Optional[QuantizationConfig]) -> Any:
    if qcfg is None:
        return w
    if qcfg.format.startswith("fp8"):
        return fp_quantize(w, fmt=qcfg.format)
    from ..ops.quant import default_groups
    return quantize(w, bits=qcfg.q_bits,
                    num_groups=default_groups(w.size, qcfg.group_size))


def base_matmul(x: jax.Array, base: Any) -> jax.Array:
    w = dequantize(base, x.dtype) if isinstance(
        base, QuantizedTensor) else base.astype(x.dtype)
    return x @ w


def init_optimized_linear(rng: jax.Array, in_dim: int, out_dim: int,
                          lora: Optional[LoRAConfig] = None,
                          quant: Optional[QuantizationConfig] = None,
                          dtype=jnp.float32,
                          base_weight: Optional[jax.Array] = None
                          ) -> Dict[str, Any]:
    """Build the parameter dict.  ``base`` is frozen (and quantized when
    requested); ``lora_a``/``lora_b`` are the trainable factors."""
    k_base, k_a = jax.random.split(rng)
    if base_weight is None:
        base_weight = (jax.random.normal(k_base, (in_dim, out_dim)) *
                       (1.0 / np.sqrt(in_dim))).astype(dtype)
    params: Dict[str, Any] = {"base": quantize_base(base_weight, quant)}
    if lora is not None:
        params["lora_a"] = (jax.random.normal(k_a, (in_dim, lora.lora_r)) *
                            (1.0 / np.sqrt(in_dim))).astype(dtype)
        params["lora_b"] = jnp.zeros((lora.lora_r, out_dim), dtype)
    return params


def apply_optimized_linear(params: Dict[str, Any], x: jax.Array,
                           lora: Optional[LoRAConfig] = None) -> jax.Array:
    y = base_matmul(x, params["base"])
    if lora is not None and "lora_a" in params:
        scale = lora.lora_alpha / lora.lora_r
        y = y + scale * ((x @ params["lora_a"]) @ params["lora_b"])
    return y


def trainable_filter(params: Any) -> Any:
    """True for leaves that should receive gradients (LoRA factors);
    the frozen quantized base is excluded (reference: LoRAOptimizedLinear
    freezes the base weight)."""
    def mark(path, leaf):
        keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        return not any(k == "base" for k in keys)
    return jax.tree_util.tree_map_with_path(mark, params)


def merge_lora(params: Dict[str, Any],
               lora: LoRAConfig) -> jax.Array:
    """Fuse adapters into a dense weight (reference:
    hybrid_engine.py:141 lora fuse used for inference)."""
    w = dequantize(params["base"]) if isinstance(
        params["base"], QuantizedTensor) else params["base"]
    if "lora_a" in params:
        scale = lora.lora_alpha / lora.lora_r
        w = w + scale * (params["lora_a"] @ params["lora_b"])
    return w
