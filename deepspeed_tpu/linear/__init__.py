from .optimized_linear import (LoRAConfig, QuantizationConfig,
                               apply_optimized_linear,
                               init_optimized_linear, merge_lora,
                               trainable_filter)

__all__ = ["LoRAConfig", "QuantizationConfig", "init_optimized_linear",
           "apply_optimized_linear", "merge_lora", "trainable_filter"]
