"""Token samplers (greedy / temperature / top-k / top-p).

The reference delegates sampling to MII / HF ``generate``; a serving
engine needs one in-repo, so this is a small jit-safe sampler family.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0          # 0 => greedy
    top_k: int = 0                    # 0 => disabled
    top_p: float = 1.0                # 1.0 => disabled
    max_new_tokens: int = 64
    stop_token: Optional[int] = None

    @property
    def sampler_key(self) -> tuple:
        """The fields that change the compiled sampling computation —
        ``stop_token``/``max_new_tokens`` are host-side loop concerns, so
        jitted steps that bake the sampler in (pipelined serving, decode
        bursts) cache executables on this key, not the full params."""
        return (self.temperature, self.top_k, self.top_p)

    @property
    def needs_rng(self) -> bool:
        return self.temperature > 0.0


def sample(logits: jnp.ndarray, params: SamplingParams,
           rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """logits [S, V] → token ids [S]."""
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if rng is None:
        raise ValueError("temperature sampling requires an rng key "
                         "(the engine supplies one automatically)")
    logits = logits / params.temperature
    if params.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -params.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; keep at least 1
        cutoff_idx = jnp.sum(cum < params.top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def row_keys(rng: jax.Array, uids: jnp.ndarray,
             context_lens: jnp.ndarray) -> jnp.ndarray:
    """[max_seqs] per-row sampling keys: ``fold_in(fold_in(rng, uid),
    position)`` where position is the sampled token's index in its
    sequence (= context length after the step).

    This makes a sequence's sampled-token randomness a pure function of
    (base key, uid, position) — invariant to HOW the serving loop
    scheduled the work.  That is what keeps seeded sampling
    token-for-token identical across pipeline depths, decode bursts, and
    prefix-cache hits/misses (a cache hit collapses prefill steps, so
    any per-step key stream would diverge)."""
    def one(u, c):
        return jax.random.fold_in(jax.random.fold_in(rng, u), c)
    return jax.vmap(one)(uids, context_lens)


def window_keys(rng: jax.Array, uids: jnp.ndarray,
                positions: jnp.ndarray) -> jnp.ndarray:
    """[S, W] per-(row, position) sampling keys for a speculative
    verify window: ``fold_in(fold_in(rng, uid), position)`` where
    ``positions[s, j]`` is the post-token position of window column
    ``j`` (the sampled token's index in its sequence).

    EXACTLY the fold :func:`row_keys` applies to a single sampled
    token, evaluated at every drafted position — so the token a verify
    column samples is bit-identical to what the non-speculative path
    would have sampled at the same (uid, position).  That identity is
    the whole parity argument for speculative decoding: acceptance
    compares drafts against the very stream a draft-less engine would
    emit (docs/SERVING.md "Speculative decoding")."""
    def one_row(u, ps):
        row_key = jax.random.fold_in(rng, u)
        return jax.vmap(lambda p: jax.random.fold_in(row_key, p))(ps)
    return jax.vmap(one_row)(uids, positions)


def sample_rows(logits: jnp.ndarray, params: SamplingParams,
                keys: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """logits [S, V] + per-row keys [S, key] → token ids [S].

    The per-row-keyed sibling of :func:`sample` the serving steps bake
    in; greedy ignores ``keys`` entirely (XLA dead-code-eliminates the
    key computation, so the seeded machinery costs nothing at
    temperature 0)."""
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if keys is None:
        raise ValueError("temperature sampling requires per-row keys "
                         "(the engine supplies them automatically)")
    return jax.vmap(lambda l, k: sample(l[None], params, k)[0])(logits, keys)
