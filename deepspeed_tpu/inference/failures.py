"""Failure domains for the serving engine: dispatch watchdog, failure
classification, and fault injection (docs/SERVING.md "Failure domains &
recovery").

PR 6 made the engine survive hostile *traffic*; this module makes it
survive *failures*.  The threat model is this rig's own history — a
backend that hangs 60 s at init, `jax.devices()` dying outright — plus
the classic serving poisons: a request whose batch OOMs the step, an
XLA error that aborts one dispatch, a device call that simply never
returns.  Without supervision any one of those wedges ``generate()``
forever or kills the process; with it, every failure degrades to a
*request-level terminal status* (the new ``failed``), a bounded retry,
or — worst case — a declared-dead engine whose host-side truth a
:meth:`~InferenceEngine.snapshot` carries into a warm restart.

Three pieces, all host-side:

* :class:`Watchdog` — runs a device dispatch/readback on a daemon
  worker thread under a deadline.  Expiry raises
  :class:`DispatchTimeoutError` (the stuck call is abandoned; a fresh
  worker serves the next dispatch, and repeated expiries escalate to
  engine-dead, bounding the leaked-thread count by
  ``FailureConfig.fatal_timeouts``).
* :func:`classify_failure` — THE one classifier seam.  Every broad
  ``except`` on the serving loop routes its exception here (tpulint's
  ``serving-except`` rule enforces it) and acts on the verdict:
  ``RETRY_STEP`` (transient: re-queue the batch, back off),
  ``POISON_STEP`` (deterministic for this batch: re-queue bisected to
  quarantine the poison request), or ``FATAL_ENGINE`` (the device is
  gone: mark the engine dead and raise :class:`EngineDeadError`).
  Exceptions the classifier does not recognize — host-side
  ``ValueError`` / ``KeyError`` / assertion bugs — return ``None`` and
  re-raise: a programming error is not a failure domain.
* :class:`FailurePolicy` — per-engine state: the resolved watchdog
  deadline (``dispatch_timeout_ms``, auto-scaled from the observed
  step latency in the metrics registry), and the fault-injection queue
  the load harness (tools/loadgen.py) and the chaos tests drive the
  whole layer with.

The reference analog is DeepSpeed's elastic-restart loop
(deepspeed/elasticity) at job granularity; a serving engine needs the
same supervision at *step and request* granularity, which is what the
``ROADMAP`` multi-replica router (item 5) and the autotuner's
"survive an OOMing candidate" (item 4, DeepCompile arxiv 2504.09983)
both reduce to.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.logging import logger

# classifier verdicts (docs/SERVING.md "Failure domains & recovery")
RETRY_STEP = "retry"          # transient: re-queue the batch, back off
POISON_STEP = "poison"        # deterministic for this batch: bisect it
FATAL_ENGINE = "fatal"        # the device is gone: dead + snapshot

# message fragments that mark an XLA/runtime error as a *capacity*
# failure of this batch (the DeepCompile "OOMing candidate"): the step
# is deterministic-bad for this batch shape, so bisect it
_POISON_MARKERS = ("resource_exhausted", "out of memory", "oom",
                   "allocation", "exceeds the memory")
# fragments that mark the backend itself as gone — no batch will ever
# run again on this engine
_FATAL_MARKERS = ("aborted", "data_loss", "device halted", "terminated",
                  "unavailable", "failed to connect", "socket closed",
                  "deadline exceeded for tpu")


class DispatchTimeoutError(RuntimeError):
    """A guarded device dispatch/readback outlived its deadline."""


class InjectedTimeout(DispatchTimeoutError):
    """A SYNTHETIC watchdog expiry (``inject("timeout")``): raised
    before the guarded call ran, so — unlike a real expiry — the
    dispatch never consumed its donated operands and recovery may keep
    the KV pool.  Classified exactly like the real thing otherwise."""


class EngineDeadError(RuntimeError):
    """The classifier declared the engine unrecoverable: the device (or
    its runtime) is gone.  Host-side truth is intact — callers
    ``snapshot()`` the dead engine and ``InferenceEngine.restore`` the
    work onto a fresh one (the warm-restart loop the load harness
    exercises)."""


class InjectedFault(RuntimeError):
    """A synthetic failure armed via :meth:`FailurePolicy.inject` —
    carries the fault ``kind`` the classifier maps to a verdict, so the
    chaos tests drive the real recovery machinery end-to-end without a
    real broken device."""

    def __init__(self, kind: str, uid: Optional[int] = None):
        super().__init__(f"injected fault: {kind}"
                         + (f" (uid {uid})" if uid is not None else ""))
        self.kind = kind
        self.uid = uid


@dataclasses.dataclass
class FailureConfig:
    """Knobs for the failure-domain layer (``InferenceConfig.failure``).

    The defaults keep the hot path unchanged for short-lived engines:
    the auto watchdog only engages after ``watchdog_warmup_steps``
    observed steps (compiles are unbounded and legitimate), and its
    deadline is generous — operators who want tight hang detection set
    ``dispatch_timeout_ms`` explicitly."""
    # watchdog deadline per guarded device call: a number (ms), "auto"
    # (scaled from the observed mean step latency once warmed up), or
    # None (watchdog off — direct calls, zero thread hops).  A guarded
    # call pays one worker-thread round trip (~40 us measured on a
    # 1-core CPU host) on the dispatch critical path; engines chasing
    # the last fraction of a millisecond per step can set None and
    # keep the classifier/quarantine layer (raised errors still route
    # through it) without deadline supervision
    dispatch_timeout_ms: object = "auto"
    # auto mode: unguarded for the first N steps (compile steps are
    # slow and legitimate), then max(floor, scale x mean step ms)
    watchdog_warmup_steps: int = 8
    auto_timeout_floor_ms: float = 10_000.0
    auto_timeout_scale: float = 50.0
    # consecutive watchdog expiries before the engine is declared dead
    fatal_timeouts: int = 2
    # LIFETIME cap on abandoned watchdog workers: consecutive-expiry
    # escalation resets on every successful step, so a device that
    # hangs intermittently (one expiry every N clean steps) would
    # otherwise strand threads without bound — past this many total
    # abandonments the next expiry is fatal regardless of spacing
    max_abandoned_workers: int = 16
    # consecutive RETRY_STEP failures tolerated before an unrecognized
    # transient error escalates to POISON_STEP (bisect instead of
    # spinning on retries)
    max_step_retries: int = 2
    # times a request may sit in a failing batch before it is closed
    # terminally with status "failed".  A singleton failing batch is
    # proof positive and fails immediately regardless — bisection
    # normally isolates the poison via such a probe; this cap is the
    # safety net for interleavings bisection cannot untangle.  It must
    # exceed ~log2(batch) + 1: an innocent neighbor of a poison request
    # shares its failing probe groups all the way down to the pair
    # split (strikes clear on the innocent's first clean probe)
    poison_strikes: int = 5
    # retry backoff: the scheduler admits nothing for up to this many
    # rounds after a retryable failure (doubling per consecutive
    # failure) — deterministic step-counted backoff, not wall-clock
    max_backoff_rounds: int = 8
    # health(): "degraded" while the last failure is within this many
    # steps (docs/OBSERVABILITY.md health-state table)
    health_window_steps: int = 64
    # post-mortem flight recorder (telemetry/flight.py): directory to
    # auto-dump the black-box JSON into on watchdog expiry, on the
    # fatal engine-dead transition, and on the first healthy->degraded
    # transition of a failure window.  None (default) disables the
    # automatic dumps; ``engine.debug_dump(path)`` works regardless.
    flight_dir: Optional[str] = None

    def __post_init__(self):
        t = self.dispatch_timeout_ms
        if t is not None and t != "auto" \
                and not (isinstance(t, (int, float)) and t > 0):
            raise ValueError(
                f"dispatch_timeout_ms={t!r}: expected a positive ms "
                "value, 'auto', or None")
        if self.fatal_timeouts < 1:
            raise ValueError("fatal_timeouts must be >= 1")
        if self.poison_strikes < 1:
            raise ValueError("poison_strikes must be >= 1")


def classify_failure(exc: BaseException, attempt: int = 0,
                     consecutive_timeouts: int = 0,
                     cfg: Optional[FailureConfig] = None) -> Optional[str]:
    """THE classifier seam: map an exception raised by a guarded device
    dispatch/readback to a verdict — :data:`RETRY_STEP`,
    :data:`POISON_STEP`, :data:`FATAL_ENGINE` — or ``None`` for
    exceptions that are not device failures at all (host programming
    errors re-raise untouched).

    ``attempt``: consecutive failed steps so far (an unrecognized
    transient escalates retry -> poison after ``max_step_retries``).
    ``consecutive_timeouts``: watchdog expiries in a row (escalate to
    fatal after ``fatal_timeouts`` — a device that repeatedly outlives
    a generous deadline is gone, and each expiry leaks one abandoned
    worker thread)."""
    cfg = cfg or FailureConfig()
    if isinstance(exc, InjectedFault):
        return {"crash": POISON_STEP, "oom": POISON_STEP,
                "transient": RETRY_STEP,
                "fatal": FATAL_ENGINE}.get(exc.kind, POISON_STEP)
    if isinstance(exc, DispatchTimeoutError):
        return FATAL_ENGINE if consecutive_timeouts >= cfg.fatal_timeouts \
            else RETRY_STEP
    # device/runtime errors: XlaRuntimeError and friends all derive from
    # jax's JaxRuntimeError umbrella; classify by message
    try:
        import jax
        device_error = isinstance(exc, jax.errors.JaxRuntimeError)
    except Exception:  # tpulint: disable=silent-except — jax-free probe
        device_error = False
    if not device_error:
        return None
    msg = str(exc).lower()
    if any(m in msg for m in _FATAL_MARKERS):
        return FATAL_ENGINE
    if any(m in msg for m in _POISON_MARKERS):
        return POISON_STEP
    return RETRY_STEP if attempt < cfg.max_step_retries else POISON_STEP


class Watchdog:
    """Deadline supervision for blocking device calls.

    One daemon worker thread runs the guarded callable; the caller
    waits on a result queue with a timeout.  Expiry raises
    :class:`DispatchTimeoutError` and ABANDONS the worker (a stuck XLA
    call cannot be interrupted from Python) — the next guarded call
    gets a fresh worker, a poison pill makes the abandoned one exit as
    soon as its stuck call completes, and the engine's
    ``fatal_timeouts`` / ``max_abandoned_workers`` escalations bound
    how many threads a dying device can strand.  With
    ``timeout_ms=None`` the call runs inline: zero threads, zero hops —
    the watchdog costs nothing unless a deadline is actually set."""

    def __init__(self):
        self._req: Optional[queue.Queue] = None
        self._res: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._token = 0
        self.abandoned = 0          # workers stranded by expiries
        # ONE guarded call at a time: the worker handshake is a single
        # (req, res) queue pair, so two concurrent run() calls would
        # interleave tokens on one queue, and a shared expiry could
        # tear down (_thread = _req = _res = None) the very worker the
        # other caller is still waiting on — double-counting
        # ``abandoned`` and stranding a result.  The admission lock
        # makes spawn + token bump + wait + abandon one atomic episode.
        # Reentrant: run() holds it across its call into
        # _ensure_worker(), which takes it again for callers that
        # pre-warm the worker directly.
        self._admit = threading.RLock()

    def _ensure_worker(self) -> None:
        with self._admit:
            if self._thread is not None and self._thread.is_alive():
                return
            self._req = queue.Queue()
            self._res = queue.Queue()

            def loop(req: queue.Queue, res: queue.Queue) -> None:
                while True:
                    token, fn = req.get()
                    if fn is None:    # poison pill: worker was abandoned
                        return
                    try:
                        out = (token, True, fn())
                    except BaseException as e:  # tpulint: disable=silent-except — shipped across the queue and re-raised in the caller
                        out = (token, False, e)
                    res.put(out)

            self._thread = threading.Thread(
                target=loop, args=(self._req, self._res),
                name="serving-watchdog", daemon=True)
            self._thread.start()

    def run(self, fn: Callable, timeout_ms: Optional[float]):
        """Run ``fn()`` under ``timeout_ms``; inline when None."""
        if timeout_ms is None:
            return fn()
        with self._admit:
            self._ensure_worker()
            self._token += 1
            token = self._token
            self._req.put((token, fn))
            deadline = time.perf_counter() + timeout_ms / 1e3
            while True:
                remaining = deadline - time.perf_counter()
                try:
                    tok, ok, val = self._res.get(
                        timeout=max(1e-4, remaining)
                        if remaining > 0 else 1e-4)
                except queue.Empty:
                    # abandon this worker.  A stuck XLA call cannot be
                    # interrupted from Python, but the poison pill makes
                    # the thread EXIT (instead of parking forever) the
                    # moment the call eventually completes — only calls
                    # that truly never return keep a thread, and the
                    # engine's max_abandoned_workers cap declares the
                    # device dead before that count can grow unboundedly
                    self.abandoned += 1
                    self._req.put((None, None))
                    self._thread = self._req = self._res = None
                    raise DispatchTimeoutError(
                        f"device dispatch outlived its {timeout_ms:.0f} ms "
                        "deadline") from None
                if tok != token:    # stale result from an older call
                    continue
                if ok:
                    return val
                raise val


class FailurePolicy:
    """Per-engine failure-domain state: the resolved watchdog deadline
    and the fault-injection queue.  The ENGINE owns the recovery
    bookkeeping (strikes, probe groups, backoff — it owns the state
    those mutate); this object owns what is independent of it."""

    def __init__(self, cfg: FailureConfig, timings):
        """``timings``: the engine's counter view — the auto deadline
        reads observed ``device_ms + wait_ms`` per step from it (the
        PR-5 metrics registry is the measurement substrate)."""
        self.cfg = cfg
        self._timings = timings
        self.watchdog = Watchdog()
        # armed injections, consumed in order by guarded dispatches:
        # (kind, uid filter or None, remaining fire count)
        self._inject: List[Tuple[str, Optional[int], int]] = []

    # ---- fault injection (the chaos harness seam) ---------------------
    def inject(self, kind: str, uid: Optional[int] = None,
               n: int = 1) -> None:
        """Arm ``n`` firings of a synthetic fault, consumed by guarded
        dispatches.  ``kind``: ``crash``/``oom`` (classified
        poison-for-step), ``transient`` (retryable), ``fatal``
        (engine-dead), ``timeout`` (a deterministic watchdog expiry —
        no real sleeping), or ``hang`` (a real sleep longer than the
        deadline, driving the real watchdog thread).  With ``uid``,
        the fault only fires on a batch containing that uid (a
        *poison request*: every batch it sits in fails, which is what
        the bisection quarantine isolates)."""
        self._inject.append((kind, uid, n))

    def _take_injection(self, uids) -> Optional[str]:
        for i, (kind, uid, n) in enumerate(self._inject):
            if uid is not None and uid not in uids:
                continue
            if n <= 1:
                del self._inject[i]
            else:
                self._inject[i] = (kind, uid, n - 1)
            return kind
        return None

    # ---- the guarded-call entry --------------------------------------
    def run(self, fn: Callable, uids=(), cold: bool = False):
        """Run one guarded device call: consume any armed injection,
        then execute under the current watchdog deadline.  ``cold``
        marks a call whose compiled program has never completed before
        (a compile may ride it): it runs UNGUARDED — compiles are slow
        and legitimate, and abandoning a worker mid-XLA-compile leaves
        native code running on a thread the interpreter cannot join
        (measured: segfault at process exit).  The deadline therefore
        supervises steady-state dispatches only, which is where a hang
        means a sick device rather than a working compiler."""
        kind = self._take_injection(uids)
        if kind is not None:
            if kind == "timeout":
                raise InjectedTimeout("injected watchdog expiry")
            if kind == "hang":
                # a real stall: the real watchdog must catch it
                inner = fn

                def fn():
                    time.sleep((self.deadline_ms() or 50.0) * 4 / 1e3)
                    return inner()
            else:
                raise InjectedFault(kind, uid=None)
        return self.watchdog.run(fn,
                                 None if cold else self.deadline_ms())

    def deadline_ms(self) -> Optional[float]:
        """The current watchdog deadline: the configured value, or the
        auto-scaled one — ``max(floor, scale x mean observed step
        ms)`` once ``watchdog_warmup_steps`` steps calibrated it (the
        warmup steps run unguarded: compiles are slow and legitimate,
        and short unit-test engines never pay the thread hop)."""
        t = self.cfg.dispatch_timeout_ms
        if t is None:
            return None
        if t != "auto":
            return float(t)
        tm = self._timings
        steps = int(tm["steps"])
        if steps < self.cfg.watchdog_warmup_steps:
            return None
        mean_ms = (float(tm["device_ms"]) + float(tm["wait_ms"])) \
            / max(steps, 1)
        return max(self.cfg.auto_timeout_floor_ms,
                   self.cfg.auto_timeout_scale * mean_ms)


def bisect_groups(uids: List[int]) -> List[List[int]]:
    """Split a failing batch's uids into the two probe halves the
    quarantine schedules next (docs/SERVING.md: the bisection rule).
    Singleton batches don't bisect — a singleton failure is proof."""
    if len(uids) <= 1:
        return []
    mid = len(uids) // 2
    return [uids[:mid], uids[mid:]]
