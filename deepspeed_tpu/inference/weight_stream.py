"""Per-layer NVMe weight streaming for serving.

TPU-native analog of the reference's NVMe parameter path
(``runtime/swap_tensor/partitioned_param_swapper.py:290`` — layer
parameters live on NVMe and stream through host DRAM just-in-time; the
ZeRO-Inference "20x bigger model" NVMe leg).  XLA cannot do file I/O
mid-graph, so the layer scan fetches each layer's payload with
``jax.experimental.io_callback``: the compiled forward blocks on a host
callback that reads that layer's file(s) via the C++ aio pool and
returns the arrays — HBM ever holds ONE layer's weights (plus the KV
cache), host DRAM holds none persistently.

Layout: one ``.npy`` file per (layer, leaf).  With ZeRO-Inference
quantization the QUANTIZED payloads are what's spilled, so the stream is
int8/int4-sized; dequantization happens on device after the fetch.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class NVMeWeightStore:
    """Spill a stacked per-layer pytree to per-layer files and fetch one
    layer at a time from inside a compiled scan."""

    # set by the engine at spill time when every quantized payload is a
    # layout the mixed-input GEMM family consumes (row-wise int8 or
    # packed row-wise int4)
    mixed_gemm_eligible = False
    qmeta = None
    # set by the engine for SPMD serving: the fetch callback must pin to
    # ONE device (XLA's partitioner rejects replicated side-effecting
    # HLOs and ordered tokens inside sharded loops); the fetched layer
    # is broadcast to the mesh by GSPMD at its first partitioned use
    spmd_device = None

    def __init__(self, path: str, num_layers: int):
        self.dir = path
        self.num_layers = num_layers
        os.makedirs(path, exist_ok=True)
        self._treedef = None
        self._shapes: Tuple[jax.ShapeDtypeStruct, ...] = ()
        self._offsets: Dict[Tuple[int, int], int] = {}
        from ..ops.aio import AsyncIOHandle
        self._aio = AsyncIOHandle(thread_count=2)

    # ---- spill -----------------------------------------------------------
    def spill(self, stacked_tree: Any) -> None:
        """``stacked_tree``: pytree whose array leaves have a leading
        ``num_layers`` dim.  Writes layer slices; frees nothing itself —
        the caller drops its references."""
        leaves, self._treedef = jax.tree.flatten(stacked_tree)
        shapes = []
        for j, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            assert arr.shape[0] == self.num_layers, (
                f"leaf {j} has no leading layer dim: {arr.shape}")
            shapes.append(jax.ShapeDtypeStruct(arr.shape[1:], arr.dtype))
            for li in range(self.num_layers):
                path = self._file(li, j)
                np.save(path, arr[li])
                # payload offset cached ONCE: the per-token fetch path
                # must not reopen/parse headers (or lean on numpy's
                # private header API)
                with open(path, "rb") as f:
                    f.seek(0, 2)
                    self._offsets[(li, j)] = f.tell() - arr[li].nbytes
        self._shapes = tuple(shapes)

    def _file(self, li: int, j: int) -> str:
        return os.path.join(self.dir, f"layer{li:04d}_leaf{j:03d}.npy")

    # ---- fetch -----------------------------------------------------------
    def result_shapes(self):
        """Pytree of ShapeDtypeStructs for one layer's payload."""
        return jax.tree.unflatten(self._treedef, list(self._shapes))

    def _fetch_host(self, li) -> Tuple[np.ndarray, ...]:
        li = int(li)
        out = []
        for j, sds in enumerate(self._shapes):
            buf = np.empty(sds.shape, sds.dtype)
            # the aio pool reads the payload region (offset cached at
            # spill) in parallel chunks
            self._aio.sync_pread(buf.view(np.uint8).reshape(-1),
                                 self._file(li, j),
                                 offset=self._offsets[(li, j)])
            out.append(buf)
        return tuple(out)

    def restore_stacked(self) -> Any:
        """Read every layer back through the aio pool and rebuild the
        stacked pytree RESIDENT — the scale-up cold-start path
        (docs/SERVING.md "Disaggregated pools & elasticity"): a new
        replica materializes its block weights from the store spilled
        once at deploy instead of re-tracing checkpoint load, and
        because the weights end resident (``icfg.weight_stream`` unset
        on the new engine) none of the modes streaming forces off —
        decode bursts, speculative decode — are forced on it."""
        assert self._treedef is not None, "restore before spill"
        leaves = []
        for j, sds in enumerate(self._shapes):
            arr = np.empty((self.num_layers,) + tuple(sds.shape),
                           sds.dtype)
            for li in range(self.num_layers):
                self._aio.sync_pread(
                    arr[li].view(np.uint8).reshape(-1),
                    self._file(li, j),
                    offset=self._offsets[(li, j)])
            leaves.append(jnp.asarray(arr))
        return jax.tree.unflatten(self._treedef, leaves)

    def fetch_layer(self, li):
        """In-graph: returns this layer's payload pytree (device arrays
        materialized from the host callback)."""
        if self.spmd_device is not None:
            from jax.sharding import SingleDeviceSharding
            flat = jax.experimental.io_callback(
                self._fetch_host, self._shapes, li,
                sharding=SingleDeviceSharding(self.spmd_device),
                ordered=False)   # pure idempotent reads: order-free
        else:
            flat = jax.experimental.io_callback(
                self._fetch_host, self._shapes, li, ordered=True)
        return jax.tree.unflatten(self._treedef, list(flat))
