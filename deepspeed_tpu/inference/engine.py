"""Serving engine: continuous ragged batching with Dynamic SplitFuse.

TPU-native re-design of the reference inference engines
(``InferenceEngineV2.put/query/flush`` engine_v2.py:107/158/242,
schedulability checks ``can_schedule`` :184 + ``scheduling_utils.py``;
v1 ``deepspeed.init_inference`` engine.py:41 is subsumed — there is no
kernel-injection step because models are born with fused TPU kernels).

Dynamic SplitFuse (the FastGen scheduling insight,
blogs/deepspeed-fastgen): every step runs a FIXED token budget mixing
decode tokens (1/seq) with prompt chunks.  On TPU this is doubly right:
the forward is compiled once for [budget] and never re-specializes.

API:
    eng = InferenceEngine(model, InferenceConfig(...))
    eng.put(uid, prompt_tokens)      # enqueue / continue a request
    out = eng.step()                 # one SplitFuse step -> {uid: token}
    eng.generate(prompts, sampling)  # convenience loop
    eng.flush(uid)                   # free a finished sequence
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.mesh import FSDP_AXIS, MeshTopology, TENSOR_AXIS
from ..models.transformer import Model, TransformerConfig
from ..telemetry import (AnomalyConfig, AnomalyMonitor, CounterDictView,
                         DeviceTelemetry, FlightRecorder, MetricsRegistry,
                         ProfilerCapture, RequestTracker, SloObjective,
                         SloTracker, SpanTracer, default_serving_detectors,
                         default_slo_objectives)
from ..utils.logging import logger
from .failures import (FATAL_ENGINE, POISON_STEP,
                       DispatchTimeoutError, EngineDeadError,
                       FailureConfig, FailurePolicy, InjectedFault,
                       InjectedTimeout, bisect_groups, classify_failure)
from .model import pipelined_ragged_step, ragged_forward
from .overload import (AdmissionVerdict, OverloadConfig, RequestMeta,
                       admission_decision, effective_priority,
                       select_victim)
from .ragged.state import (FEEDBACK_TOKEN, BatchStager, KVCacheConfig,
                           RaggedBatch, StateManager)
from .sampler import SamplingParams, sample_rows


@dataclasses.dataclass
class InferenceConfig:
    """(reference: RaggedInferenceEngineConfig inference/v2/config_v2.py —
    DSStateManagerConfig: max_ragged_batch_size/token budget,
    memory_config num blocks)."""
    token_budget: int = 256          # tokens per step (SplitFuse budget)
    max_seqs: int = 8                # concurrent sequences
    kv_block_size: int = 64
    num_kv_blocks: int = 256         # pool size
    max_seq_len: Optional[int] = None   # default: model max
    kv_dtype: object = jnp.bfloat16
    param_dtype: object = jnp.bfloat16
    # paged attention implementation: "auto" probes the Pallas streaming
    # kernel against the XLA gather formulation on the first step's real
    # shapes and keeps the faster one; "xla" / "pallas" force a path
    attn_impl: str = "auto"
    # "int8" | "fp8": store the paged KV cache quantized (one scale per
    # written token/head vector, per-block layout).  Halves (int8) the
    # dominant HBM stream of long-context decode; all paged-attention
    # paths and the decode burst consume it natively (reference analog:
    # ZeRO-Inference KV quantization, deepspeed/inference/quantization/)
    kv_quant: Optional[str] = None
    # --- ZeRO-Inference (reference: inference/quantization, README:35) --
    # "int8" | "int4": group-quantized weights, one layer dequantized at
    # a time inside the forward (2-4x smaller resident model)
    weight_quant: Optional[str] = None
    # mixed-input GEMM (int8 weight x bf16 act, dequant in VMEM —
    # ops/mixed_gemm.py; reference: cuda_linear fp6 GEMM): "auto" races
    # it against the fused-dequant XLA path once post-compile (like
    # attn_impl); "on"/"off" force.  Engages for the row-wise int8
    # and packed row-wise int4 layouts.
    mixed_gemm: str = "auto"
    quantize_embeddings: bool = False
    # keep the paged KV cache in host memory, streaming one layer per
    # scan step through HBM (over-HBM contexts; needs pinned_host)
    kv_offload: bool = False
    # NVMe per-layer weight streaming (reference:
    # partitioned_param_swapper.py:290 / ZeRO-Inference NVMe): directory
    # to spill the per-layer (quantized, when weight_quant is set)
    # payloads; the forward fetches one layer at a time via io_callback,
    # so HBM never holds the block weights. Disables decode bursts.
    weight_stream: Optional[str] = None
    # device-side decode bursts: run K decode iterations in ONE dispatch
    # (sampled tokens fed back on-device via lax.scan), amortizing the
    # host round trip over K tokens.  1 disables.  Sequences that hit
    # their stop token mid-burst over-generate up to K-1 tokens, which
    # generate() discards (the usual multi-step-scheduling trade).
    decode_burst: int = 1
    # serving-pipeline depth for generate(): 2 keeps one step in flight —
    # sampling happens INSIDE the jitted step, the sampled token array
    # stays on device and feeds the next step's batch directly, and the
    # host schedules/stages step N+1 (and reads step N's tokens back)
    # while step N computes.  1 is the strict-sync debug mode; both
    # depths run the same step computation, so outputs are
    # token-for-token identical.  Sequences that hit their stop token
    # over-generate one speculative token, which the driver discards
    # (as decode bursts do).
    pipeline_depth: int = 2
    # KV-cache donation across steps: "on" aliases the cache in place
    # (the right call wherever HBM is the constraint), "off" lets XLA
    # allocate a fresh result cache per step.  "auto" donates everywhere
    # EXCEPT a pipelined engine on the CPU backend: XLA:CPU blocks a
    # dispatch whose donated operand is still being produced by the
    # in-flight step (measured: chained donated calls serialize at full
    # step latency), which would silently turn the depth-2 pipeline back
    # into the synchronous loop.  Host RAM pays one transient cache copy
    # instead.
    kv_donate: str = "auto"
    # --- overlapped & quantized multi-chip collectives (comm/overlap.py;
    # T3 arxiv 2401.16677, EQuARX arxiv 2506.17615; docs/SERVING.md
    # "Overlapped & quantized collectives") ------------------------------
    # "on": the TP hot path's two heavy collectives — the MLP
    # down-projection's partial-sum all-reduce and the unembed's logits
    # all-gather — run tile-decomposed inside shard_map, so XLA can
    # schedule tile i's comm behind tile i+1's GEMM instead of the
    # serial GSPMD collective after the whole GEMM.  Bitwise-identical
    # to "off" (the default exact rung reduces each tile with the same
    # psum; the gather is pure data movement) — asserted by parity
    # tests on 1-chip and simulated 8-device meshes.  "auto": on
    # whenever the mesh has a tensor axis and the shapes divide it;
    # single-chip auto resolves off (there is nothing to overlap).
    # "on" without a tensor axis is a loud no-op, never an error — the
    # same config must run on 1 chip and on the pod.
    comm_overlap: str = "auto"
    # tiles per decomposed collective (clamped to divide the row dim)
    comm_tiles: int = 4
    # EQuARX-style quantized allreduce for the TP activation reduction:
    # "int8" | "int4" wire payloads — bits/8 of the exact bytes on the
    # wire (the telemetry reconciliation test asserts exactly that
    # ratio).  Applies to the down-projection all-reduce only; the
    # unembed GATHER always stays exact, because a perturbed logit
    # could flip a greedy argmax.  Meshes/shapes that cannot support
    # the quantized wire degrade LOUDLY to the exact reduction (the
    # PR-1 contract for every quantized collective).
    comm_quant: Optional[str] = None
    # automatic prefix caching over the paged KV cache: full KV blocks
    # are content-hashed by their token chain (rolling hash of
    # (parent, block_tokens)) and an incoming prompt's longest cached
    # block-aligned prefix is aliased — refcounted, read-only — into its
    # block table, so prefill starts at the first uncached token
    # (copy-on-write when a sequence must append into a shared block).
    # Matching is pure host-side hashing: a miss adds ZERO device work,
    # and blocks only alias within this engine's own pool, so "auto"
    # (default) simply enables it on every backend; "off" disables
    # (strict step-for-step reproduction of a cache-less engine), "on"
    # forces.  Hit counters: engine.timings cached_tokens/prefix_hits/
    # prompt_tokens, query()["cached_tokens"].
    prefix_cache: str = "auto"
    # span tracing of the serving loop (telemetry/tracer.py): host-side
    # perf_counter_ns spans for every pipeline stage (schedule / stage /
    # dispatch / wait / readback, COW drains, prefix-cache lookups) into
    # a preallocated ring buffer; export with
    # ``engine.tracer.export_chrome_trace(path)`` and open in Perfetto.
    # Off by default: the per-span cost is tiny but nonzero.  The
    # metrics registry (``engine.metrics``) and per-request lifecycle
    # records (``engine.request_metrics()``) are ALWAYS on — they are
    # host-side counter bumps that never touch device arrays.
    trace: bool = False
    trace_capacity: int = 1 << 16   # spans retained (ring wraps beyond)
    # device & compiler telemetry (telemetry/device.py,
    # docs/OBSERVABILITY.md "Device & compiler telemetry"): per-program
    # ``compiled.cost_analysis()`` (flops / bytes / HLO size, probed
    # once per executable-cache fill via an explicit AOT compile of the
    # already-warm program), derived ``serving_mfu`` /
    # ``serving_hbm_bw_util`` pull-gauges computed from the existing
    # step timings at export time, and ``device.memory_stats()`` polled
    # at phase boundaries (health checks, dumps, bench captures).  Off
    # by default: the cost probe pays one duplicate compile per program
    # — "on" is what bench legs and the future autotuner (ROADMAP
    # item 4) opt into; "auto" defers to the engine and today resolves
    # OFF.  The compile/retrace COUNTERS, the KV-pool pull-gauges, and
    # the flight recorder are always on — they are host counter bumps
    # and read-time probes that cost the hot path nothing.
    device_telemetry: str = "auto"
    # streaming anomaly detection (telemetry/anomaly.py,
    # docs/OBSERVABILITY.md "Anomaly detection & deep capture"): EWMA+
    # MAD / rolling-percentile / threshold detectors over per-step
    # signals the loop already computes — step interval / device /
    # wait / host ms, TTFT/TPOT, runtime retraces, KV-referenced
    # slope, prefix hit rate, spec acceptance.  A fire is note()d into
    # the flight recorder, counted
    # (``serving_anomalies_total{signal=...}``), surfaced through
    # ``engine.health()`` (sustained fires => degraded), and —
    # cooldown- and budget-limited — arms a deep-capture window.  Off
    # costs literally nothing: no monitor is constructed, no clock is
    # read; on reuses the timestamps the loop already takes (the
    # zero-extra-clock-reads bar is tested).  "auto" resolves OFF
    # today — the ROADMAP-4 autotuner is the intended flipper.
    anomaly: str = "auto"
    anomaly_cfg: Optional["AnomalyConfig"] = None
    # deep-capture output directory (telemetry/profiler.py): armed
    # captures record a bounded ``jax.profiler`` device trace + the
    # window's host spans + a flight dump under
    # ``<profile>/capture_<n>_<reason>/``, which
    # ``tools/tracemerge.py`` merges into ONE Perfetto timeline.
    # Setting ``profile`` with ``profile_steps > 0`` arms an explicit
    # window over the first ``profile_steps`` engine steps (the bench
    # ``--profile`` path); ``profile_steps = 0`` just designates the
    # directory (anomaly-armed captures land there).  Explicit windows
    # can also be armed any time via ``engine.capture(steps=N)``.
    # Backends/builds without profiler support degrade loudly: the
    # window completes host-only and the merge says so.
    profile: Optional[str] = None
    profile_steps: int = 4
    # model-free speculative decoding (inference/spec_decode.py,
    # docs/SERVING.md "Speculative decoding"): an n-gram prompt-lookup
    # proposer drafts up to ``spec_max_draft`` continuation tokens per
    # decoding sequence from the request's OWN prompt + emitted tokens
    # (zero extra weights), a ragged verify step scores the window of
    # 1 + k positions in ONE dispatch, and the longest draft prefix
    # matching what the model samples anyway is accepted (rejected
    # tokens roll the paged-KV write cursor back — host bookkeeping
    # only).  Output streams are EXACTLY the non-speculative ones,
    # greedy and seeded (the verify step samples each window position
    # with the same (uid, position)-folded key the stepwise path uses).
    # "on" enables; "off" disables (n_verify=1 — the compiled step is
    # byte-identical to a pre-spec engine); "auto" defers to the
    # engine: today it resolves OFF — acceptance is workload-dependent
    # and the autotuner (ROADMAP item 4) is meant to flip it from the
    # measured acceptance_rate/draft-length profiles this engine
    # records.  Forced off (one shared needs-resident-weights gate with
    # decode_burst) under weight_stream, and incompatible with
    # decode_burst > 1 ("on" raises; "auto" quietly defers to bursts —
    # both paths multi-token the decode, bursts device-side).
    spec_decode: str = "auto"
    # widest draft window per sequence per verify step; the proposer
    # may draft fewer (budget/context capped), and an empty draft
    # degrades the row to a plain 1-token decode
    spec_max_draft: int = 4
    # overload policy (inference/overload.py, docs/SERVING.md "Surviving
    # overload"): bounded admission queue + shed policy, priority /
    # deadline-aware scheduling with anti-starvation aging,
    # preemption-by-eviction when the block pool or slot table starves a
    # higher tier, and per-step chunked-prefill budget caps.  None uses
    # OverloadConfig() defaults, which reproduce the legacy cooperative
    # behavior exactly (unbounded queue, no chunk cap, preemption inert
    # while every request shares one priority tier).
    overload: Optional[OverloadConfig] = None
    # failure-domain policy (inference/failures.py, docs/SERVING.md
    # "Failure domains & recovery"): every device dispatch/readback
    # runs under a watchdog deadline (``FailureConfig.
    # dispatch_timeout_ms`` — "auto" scales it from the observed step
    # latency in the metrics registry), every raised XLA error or
    # expiry routes through ONE classifier seam, and the verdict
    # degrades the failure to a request-level terminal status instead
    # of a wedged or dead process: transient errors re-queue the batch
    # with backoff, deterministic step failures bisect the batch until
    # the poison request is quarantined (terminal status ``failed``),
    # and a dead backend raises EngineDeadError — from which
    # ``snapshot()`` + ``InferenceEngine.restore()`` warm-restart the
    # open work token-identically.  None uses FailureConfig()
    # defaults (auto watchdog, engaged after a calibration warmup).
    failure: Optional[FailureConfig] = None
    # tiered KV cache (inference/ragged/tier.py, docs/KV_TIERING.md):
    # prefix-cache eviction demotes full content-hashed blocks into a
    # bounded host-RAM ring instead of discarding them, with ring
    # overflow spilled to NVMe files through ops/aio.py; a match_prefix
    # digest hit in the tier restages the chain asynchronously —
    # overlapping the dispatch-ahead window the way COW drains do — so
    # a spilled-chain hit pays block uploads, not a re-prefill.  "on"
    # enables (requires prefix_cache != "off"); "off" disables; "auto"
    # defers to the engine and today resolves OFF (the tier trades host
    # RAM/disk for recompute — the ROADMAP-4 autotuner is the intended
    # flipper, and bench.py's tiered_kv leg records the tradeoff).
    kv_tier: str = "auto"
    # host-RAM ring budget; overflow spills to kv_tier_dir (if set)
    kv_tier_ram_mb: float = 64.0
    # NVMe spill directory — None (default) runs the tier RAM-only;
    # spill files are named <chain_digest>.kv and are useless without
    # the owning process's in-memory index (restart discards them)
    kv_tier_dir: Optional[str] = None
    kv_tier_nvme_mb: float = 256.0
    # per-class SLO scorecard + error-budget burn-rate signals
    # (telemetry/slo.py, docs/OBSERVABILITY.md "SLOs & error budgets"):
    # "on" attaches an SloTracker to the request tracker's existing
    # first-token / close-out stamp sites (zero new clock reads — the
    # scorecard evaluates timestamps already on the record) and, when
    # the anomaly plane is also on, registers the per-class
    # ``slo_burn_rate_<class>`` burn detectors into its catalog (a
    # burning budget breadcrumbs the flight recorder and arms a
    # budgeted capture like any other anomaly).  Off constructs
    # nothing; "auto" resolves OFF today.
    slo: str = "auto"
    # class -> SloObjective map; None = default_slo_objectives()
    slo_objectives: Optional[Dict[str, "SloObjective"]] = None


# attn-impl probe results, memoized per (backend, shape signature)
_PROBE_CACHE: Dict[tuple, str] = {}


class _InFlight(NamedTuple):
    """One dispatched-but-unread serving step: the on-device [max_seqs]
    sample array, the (uid, slot) emission list frozen at dispatch time
    (slots may be reassigned by the time the step is collected), and the
    engine-wide dispatch sequence number (feedback markers name the step
    whose sample array they defer to)."""
    toks: jax.Array
    emit: Tuple[Tuple[int, int], ...]
    sid: int
    # every uid the step scheduled tokens for (emitting or not): a
    # sequence with an uncollected scheduled step is never a preemption
    # victim — its KV blocks are still being written
    uids: Tuple[int, ...] = ()
    # speculative verify windows this step carries: uid -> the drafted
    # token tuple (the window is [fed token, *drafts]).  Acceptance is
    # decided at collect by prefix-comparing the drafts against the
    # [S, W] sample array; frozen here because the proposer's state
    # moves on while the step is in flight
    drafts: Tuple[Tuple[int, Tuple[int, ...]], ...] = ()
    # the sampling stop token at dispatch time: a stop landing INSIDE
    # an accepted draft truncates the emission at collect exactly where
    # the stepwise engine would have stopped feeding
    stop: Optional[int] = None
    # prefix-cache (digest, block) entries THIS step's build registered:
    # their content promise is honored by this step's KV writes, so a
    # failure at collect must withdraw exactly these (the dispatch-
    # failure path uses the state manager's live round ledger instead)
    registered: Tuple[Tuple[bytes, int], ...] = ()
    # the dispatch rode a first-call program (compile may still be in
    # flight on async backends): its readback runs unguarded too
    cold: bool = False


class InferenceEngine:
    """Serving engine.  With ``topology`` (a :class:`MeshTopology`), the
    model is served SPMD over the mesh: weights follow the training-side
    logical-axis TP rules (Megatron-style head/mlp/vocab splits —
    reference: ``module_inject/auto_tp.py:189`` ``ReplaceWithTensorSlicing``
    :30, and the v2 declarative sharding helpers
    ``inference/v2/model_implementations/sharding/qkv.py``), the paged KV
    cache is head-split over the ``tensor`` axis, and any ``fsdp`` mesh
    axis memory-shards weights ZeRO-Inference-style (XLA gathers per
    use).  GSPMD inserts the per-layer collectives; no imperative tensor
    slicing."""

    def __init__(self, model: Model, config: InferenceConfig = None,
                 topology: Optional[MeshTopology] = None,
                 quant_tree=None):
        """``quant_tree``: a pre-built ZeRO-Inference quantized tree (the
        second output of ``quantization.quantize_model_params``, e.g.
        loaded from a quantized checkpoint) — ``model.params`` must then
        be the matching dense remainder, and ``weight_quant`` is not
        re-applied (the >HBM big-model flow: nothing dense ever
        materializes)."""
        self.model = model
        self.cfg: TransformerConfig = model.config
        self.icfg = config or InferenceConfig()
        if self.icfg.prefix_cache not in ("auto", "on", "off"):
            raise ValueError(f"prefix_cache={self.icfg.prefix_cache!r}: "
                             "expected 'auto', 'on', or 'off'")
        if self.icfg.kv_tier not in ("auto", "on", "off"):
            raise ValueError(f"kv_tier={self.icfg.kv_tier!r}: "
                             "expected 'auto', 'on', or 'off'")
        if self.icfg.kv_tier == "on" and self.icfg.prefix_cache == "off":
            raise ValueError(
                "kv_tier='on' requires the prefix cache: the tier keys "
                "demoted blocks by their chain digests, which only the "
                "prefix-cache index computes (set prefix_cache to "
                "'auto'/'on' or kv_tier to 'auto'/'off')")
        max_len = self.icfg.max_seq_len or self.cfg.max_seq_len
        # a sequence can never hold more blocks than the pool has
        self.max_blocks_per_seq = min(-(-max_len // self.icfg.kv_block_size),
                                      self.icfg.num_kv_blocks)
        kv_cfg = KVCacheConfig(
            num_layers=self.cfg.num_layers,
            num_kv_heads=self.cfg.num_kv_heads,
            head_dim=self.cfg.head_dim,
            block_size=self.icfg.kv_block_size,
            num_blocks=self.icfg.num_kv_blocks,
            dtype=self.icfg.kv_dtype,
            quant=self.icfg.kv_quant or "none")
        self.state = StateManager(kv_cfg, max_seqs=self.icfg.max_seqs,
                                  max_blocks_per_seq=self.max_blocks_per_seq,
                                  prefix_cache=self.icfg.prefix_cache
                                  != "off")
        # "auto" resolves OFF today — demotion trades host RAM/disk +
        # drain time for saved recompute, a workload call the ROADMAP-4
        # autotuner (and bench.py's tiered_kv leg) is meant to make
        if self.icfg.kv_tier == "on":
            from .ragged.tier import KVBlockTier
            self.state.tier = KVBlockTier(
                ram_bytes=int(self.icfg.kv_tier_ram_mb * (1 << 20)),
                nvme_dir=self.icfg.kv_tier_dir,
                nvme_bytes=int(self.icfg.kv_tier_nvme_mb * (1 << 20)))
        self.topology = topology if (
            topology is not None and topology.device_count > 1) else None
        self.params = jax.tree.map(
            lambda x: x.astype(self.icfg.param_dtype)
            if x.dtype == jnp.float32 else x, model.params)
        self._quant = None
        if quant_tree is not None:
            self._quant = quant_tree
        elif self.icfg.weight_quant:
            from .quantization import quantize_model_params
            from ..ops.quant import WEIGHT_QUANT_BITS
            self.params, self._quant = quantize_model_params(
                self.params, bits=WEIGHT_QUANT_BITS[self.icfg.weight_quant],
                quantize_embeddings=self.icfg.quantize_embeddings)
        self._stream = None
        if self.icfg.weight_stream:
            self._setup_weight_stream()
        if self.icfg.mixed_gemm == "on":
            # fail at construction, not at the first compiled step: an
            # explicit force-on with an ineligible layout is a config error
            self._require_mixed_gemm_eligible()
        self._setup_sharding()
        # resolved overlapped/quantized-collective plan (comm/overlap.py)
        # — None when the mesh/shapes give the decomposition nothing to
        # do; _resolve_fw may still drop the down-projection leg when
        # the mixed-GEMM probe keeps those weights quantized
        self._serving_comm = self._resolve_serving_comm()
        self._comm_active = self._serving_comm
        self._comm_stats: Optional[Dict[str, float]] = None
        if self.topology is None:
            self._place_default_device()
        if self.icfg.kv_offload:
            if self.topology is not None:
                logger.warning("kv_offload is single-device only; ignored "
                               "under a multi-device topology")
            else:
                self._offload_kv()
        # tpulint: live-set — uid -> unprocessed toks
        self._pending: Dict[int, List[int]] = {}
        self._ctx_exhausted: set = set()
        self._rng = jax.random.PRNGKey(0)
        self._cow_fn = None           # lazy jitted prefix-cache block copy
        self._restage_fn = None       # lazy jitted tier->HBM block upload
        self._pstep_fns: Dict[tuple, object] = {}  # (bucket, sampler_key)
        self._burst_fns: Dict[tuple, object] = {}
        # serving programs that have COMPLETED at least one call: only
        # these run under the dispatch watchdog — a first call may
        # carry an unboundedly-slow (and legitimate) compile
        self._warm_keys: set = set()
        self._steps_done = 0
        # --- model-free speculative decoding (spec_decode.py) ----------
        self._setup_spec_decode()
        # pipelined-serving state: alternating host staging buffers, the
        # last dispatched step's on-device sample array (the feedback
        # source for the next step), and a zero fallback for step 0
        self._stager = BatchStager(self.icfg.token_budget,
                                   self.icfg.max_seqs,
                                   self.icfg.num_kv_blocks,
                                   depth=max(2, self.icfg.pipeline_depth),
                                   n_verify=self._n_verify)
        # spec engines' steps return [S, W] windows, so the feedback
        # operand (and its step-0 zero fallback) is window-shaped too
        self._zero_toks = self._stage(jnp.zeros(
            (self.icfg.max_seqs,) if self._n_verify == 1
            else (self.icfg.max_seqs, self._n_verify), jnp.int32))
        self._last_toks = None
        self._dispatch_seq = 0
        self._fb_step: Dict[int, int] = {}   # uid -> sid its marker defers to
        self._zero_key = jax.random.PRNGKey(0)
        # --- overload policy state (inference/overload.py) -------------
        self.ocfg = self.icfg.overload or OverloadConfig()
        self._meta: Dict[int, RequestMeta] = {}   # uid -> admission meta
        self._deadline_uids: set = set()          # uids with a deadline
        self._inflight_sched: Dict[int, int] = {} # uid -> uncollected steps
        self._preempting: set = set()             # release() = preemption
        self._preempt_gen: Dict[int, List[int]] = {}  # pre-eviction tokens
        # tpulint: live-set — uid -> staged terminal status
        self._closing: Dict[int, str] = {}
        self._reaped: set = set()   # engine-closed uids drivers must drop
        self._setup_telemetry()
        # --- failure-domain state (inference/failures.py) --------------
        self.fcfg = self.icfg.failure or FailureConfig()
        self.failures = FailurePolicy(self.fcfg, self.timings)
        self._strikes: Dict[int, int] = {}   # uid -> failing-batch count
        self._probe_groups: List[List[int]] = []  # bisection quarantine
        self._backoff_rounds = 0             # rounds admitting nothing
        self._consec_failures = 0
        self._consec_timeouts = 0
        self._last_failure_step = -(1 << 30)
        self._health = "healthy"             # healthy|degraded computed;
        self._draining = False               # draining|dead are sticky
        # every KV release — flush, preemption, deadline expiry, or a
        # direct StateManager.release — flows through one close-out hook
        # so request_metrics() can never leak an open record
        self.state.on_release = self._on_state_release

    def _setup_telemetry(self) -> None:
        """Build the metrics registry, the span tracer, and the
        request-lifecycle tracker (docs/OBSERVABILITY.md).  Everything
        here is host-side counters/floats — telemetry never touches
        device arrays on the serving path (tpulint telemetry-hotpath +
        serving-sync keep it that way)."""
        self.metrics = MetricsRegistry()
        self.tracer = SpanTracer(capacity=self.icfg.trace_capacity,
                                 enabled=self.icfg.trace)
        self.requests = RequestTracker(
            self.metrics, max_finished=self.ocfg.status_retention)
        reg = self.metrics
        # health-state gauge (docs/OBSERVABILITY.md): 0 healthy,
        # 1 degraded, 2 draining, 3 dead — what the multi-replica
        # router's liveness probe scrapes
        self._health_gauge = reg.gauge(
            "serving_health_state",
            "engine health: 0=healthy 1=degraded 2=draining 3=dead")
        ms = {k: reg.counter(f"serving_{k}_total",
                             f"cumulative serving-loop {k.split('_')[0]} "
                             "phase milliseconds")
              for k in ("schedule_ms", "stage_ms", "device_ms", "wait_ms",
                        "readback_ms")}
        ints = {
            "steps": reg.counter("serving_steps_total",
                                 "dispatched serving steps",
                                 int_valued=True),
            "prompt_tokens": reg.counter(
                "serving_prompt_tokens_total",
                "prompt tokens of admitted requests", int_valued=True),
            "cached_tokens": reg.counter(
                "serving_cached_tokens_total",
                "prompt tokens served from the prefix cache",
                int_valued=True),
            "prefix_hits": reg.counter(
                "serving_prefix_hits_total",
                "admitted requests with a nonzero prefix match",
                int_valued=True),
            "generated_tokens": reg.counter(
                "serving_generated_tokens_total",
                "tokens emitted to live sequences", int_valued=True),
            # speculative decoding (docs/SERVING.md "Speculative
            # decoding"): drafted = proposer tokens a verify window
            # scored; accepted = drafts committed (they match the
            # model's own stream and were emitted); rejected = drafts
            # rolled back.  drafted == accepted + rejected, and the
            # per-request records bump at the SAME statements, so
            # sum(per-request) reconciles with these by construction
            # (tests/test_spec_decode.py holds the invariant)
            # tpulint: pair=spec_drafted_tokens/spec_accepted_tokens
            "spec_drafted_tokens": reg.counter(
                "serving_spec_drafted_tokens_total",
                "draft tokens scored by verify steps", int_valued=True),
            "spec_accepted_tokens": reg.counter(
                "serving_spec_accepted_tokens_total",
                "draft tokens accepted and emitted", int_valued=True),
            "spec_rejected_tokens": reg.counter(
                "serving_spec_rejected_tokens_total",
                "draft tokens rolled back", int_valued=True),
            "spec_windows": reg.counter(
                "serving_spec_windows_total",
                "verify windows resolved (mean accepted draft length = "
                "accepted / windows)", int_valued=True),
            # failure domains (docs/SERVING.md "Failure domains &
            # recovery"): steps the classifier recovered (re-queue /
            # bisect) and requests quarantined terminally as poison
            "step_retries": reg.counter(
                "serving_step_retries_total",
                "serving steps that failed and were recovered by "
                "re-queue (retry or bisect probe)", int_valued=True),
            "requests_failed": reg.counter(
                "serving_requests_failed_total",
                "requests terminally closed with status 'failed' "
                "(poison quarantine / unreplayable after a failure)",
                int_valued=True),
            # compile observatory (docs/OBSERVABILITY.md "Device &
            # compiler telemetry"): every serving executable-cache fill
            # counts; a fill whose (kind, key) was ALREADY compiled in
            # this engine's lifetime is a runtime RETRACE — the dynamic
            # complement of tpulint's static retrace-hazard rule, and
            # each one logs a loud warning (something is churning the
            # program cache: LRU thrash, shape churn, weight refresh)
            "compiles": reg.counter(
                "serving_compiles_total",
                "serving programs built (executable-cache fills)",
                int_valued=True),
            "compile_retraces": reg.counter(
                "serving_compile_retraces_total",
                "re-builds of a program key this engine had already "
                "compiled (runtime retrace — each warns loudly)",
                int_valued=True),
            # tiered KV cache (docs/KV_TIERING.md): demotions count
            # blocks evicted into the host ring, spills the ring's
            # overflow pushed on to NVMe files, revives the blocks
            # restaged back into HBM by source tier; every revive that
            # lands in a round which also dispatched a step overlapped
            # the dispatch-ahead window (the TTFT win the tier exists
            # for).  Verify failures are payloads rejected by the
            # checksum / chain-digest contract — nonzero outside a
            # corruption drill means the spill path is eating data
            "kv_tier_demotions": reg.counter(
                "serving_kv_tier_demotions_total",
                "KV blocks demoted from HBM into the host-RAM tier",
                int_valued=True),
            "kv_tier_spills": reg.counter(
                "serving_kv_tier_spills_total",
                "tier blocks spilled from the host ring to NVMe",
                int_valued=True),
            "kv_tier_drops": reg.counter(
                "serving_kv_tier_drops_total",
                "tier blocks dropped off the bottom of the hierarchy",
                int_valued=True),
            "kv_tier_revives_ram": reg.counter(
                "serving_kv_tier_revives_ram_total",
                "blocks restaged into HBM from the host ring",
                int_valued=True),
            "kv_tier_revives_nvme": reg.counter(
                "serving_kv_tier_revives_nvme_total",
                "blocks restaged into HBM from NVMe spill files",
                int_valued=True),
            "kv_tier_revives_remote": reg.counter(
                "serving_kv_tier_revives_remote_total",
                "blocks restaged into HBM from peer-replica fetches",
                int_valued=True),
            "kv_tier_restage_overlap_hits": reg.counter(
                "serving_kv_tier_restage_overlap_hits_total",
                "revives resolved in a round that also dispatched a "
                "step (the restage overlapped the dispatch-ahead "
                "window)", int_valued=True),
            "kv_tier_verify_failures": reg.counter(
                "serving_kv_tier_verify_failures_total",
                "restage/fetch payloads rejected by checksum or "
                "chain-digest verification (fell back to re-prefill)",
                int_valued=True),
            "kv_tier_demoted_bytes": reg.counter(
                "serving_kv_tier_demoted_bytes_total",
                "payload bytes demoted into the host ring",
                int_valued=True),
            "kv_tier_spilled_bytes": reg.counter(
                "serving_kv_tier_spilled_bytes_total",
                "payload bytes spilled to NVMe", int_valued=True),
            "kv_tier_remote_blocks": reg.counter(
                "serving_kv_tier_remote_blocks_total",
                "tier blocks imported from peer replicas "
                "(snapshot-v2 tier_blocks records)", int_valued=True),
        }
        # first-call wall time of each program (compile rides it): the
        # timestamps are the dispatch path's existing t2/t3, so this
        # adds no clock reads — it is the always-on compile-span feed
        ms["compile_ms"] = reg.counter(
            "serving_compile_wall_ms_total",
            "cumulative first-call (compile-carrying) dispatch wall ms")
        self.timings = CounterDictView({**ms, **ints})
        # --- overlapped/quantized collectives (docs/SERVING.md
        # "Overlapped & quantized collectives"): static per-dispatch
        # wire accounting — the shapes of a compiled step fully
        # determine what its decomposed TP collectives move, so the
        # counters bump from host-side arithmetic, never a device
        # probe.  A quantized op's bytes are bits/8 of the exact op's
        # (asserted by the telemetry reconciliation test).
        self._c_comm_ops = reg.counter(
            "serving_comm_ops_total",
            "decomposed TP collectives dispatched (kind: exact|quant)",
            int_valued=True)
        self._c_comm_tiles = reg.counter(
            "serving_comm_tiles_total",
            "tiles across dispatched decomposed TP collectives",
            int_valued=True)
        self._c_comm_bytes = reg.counter(
            "serving_comm_bytes_total",
            "modeled bytes on the wire for decomposed TP collectives "
            "(kind: exact|quant)")
        # --- KV-pool occupancy gauges: pull-based (FnGauge — computed
        # from allocator truth at export time), so the serving loop
        # never updates them and a scrape is always current.  The
        # scheduler fuzz cross-checks gauge == assert_invariants truth.
        pool = lambda k: (lambda: self.state.pool_stats()[k])  # noqa: E731
        reg.gauge_fn("serving_kv_blocks_free", pool("free"),
                     "plain-free KV blocks (excludes cached-free)")
        reg.gauge_fn("serving_kv_blocks_cached_free", pool("cached_free"),
                     "evictable prefix-cached free KV blocks")
        reg.gauge_fn("serving_kv_blocks_referenced", pool("referenced"),
                     "KV blocks referenced by live sequences")
        reg.gauge_fn("serving_kv_blocks_peak_referenced",
                     pool("peak_referenced"),
                     "high-water mark of referenced KV blocks")
        reg.gauge_fn("serving_kv_blocks_total", pool("total"),
                     "KV pool size")
        reg.gauge_fn("serving_prefix_index_entries",
                     pool("prefix_index_entries"),
                     "content hashes resident in the prefix-cache index")
        reg.gauge_fn("serving_prefix_hit_rate", self._prefix_hit_rate,
                     "cached_tokens / prompt_tokens over the measured "
                     "window (absent before any prompt token)")
        # tier occupancy: pull-gauges over tier.stats() truth (absent
        # when the tier is off — None suppresses the series, the same
        # contract the devtel gauges use)
        tg = lambda k: (lambda: (self.state.tier.stats()[k]  # noqa: E731
                                 if self.state.tier is not None else None))
        reg.gauge_fn("serving_kv_tier_ram_entries", tg("ram_entries"),
                     "blocks resident in the host-RAM tier ring")
        reg.gauge_fn("serving_kv_tier_ram_bytes", tg("ram_bytes"),
                     "payload bytes resident in the host-RAM tier ring")
        reg.gauge_fn("serving_kv_tier_nvme_entries", tg("nvme_entries"),
                     "blocks resident in NVMe spill files")
        reg.gauge_fn("serving_kv_tier_nvme_bytes", tg("nvme_bytes"),
                     "payload bytes resident in NVMe spill files")
        # --- flight recorder (telemetry/flight.py): always constructed
        # — the happy path never touches it, and the failure path's
        # breadcrumbs must exist BEFORE the crash someone debugs
        self.flight = FlightRecorder()
        # --- gated device telemetry (telemetry/device.py): cost-probe
        # table + derived MFU/BW gauges + memory polling.  None when
        # off: the serving loop then contains not one added clock read,
        # device sync, or cost_analysis call (enforced by test)
        mode = self.icfg.device_telemetry
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"device_telemetry={mode!r}: expected "
                             "'auto', 'on', or 'off'")
        # "auto" resolves OFF today: the cost probe pays one duplicate
        # compile per program — the autotuner (ROADMAP item 4) is meant
        # to flip it where the signals pay for themselves
        self.devtel = DeviceTelemetry(
            reg, "serving",
            step_ms_fn=lambda: (self.timings["device_ms"]
                                + self.timings["wait_ms"])) \
            if mode == "on" else None
        # (kind, key) of every program EVER built by this engine —
        # unlike _warm_keys this survives LRU eviction, so a re-build
        # is recognized as a retrace
        self._compiled_ever: set = set()
        # --- streaming anomaly detection (telemetry/anomaly.py): None
        # when off — the serving loop then contains not one added
        # clock read or detector call (the same zero-cost bar as
        # device telemetry, extended by test to the detector hooks)
        amode = self.icfg.anomaly
        if amode not in ("auto", "on", "off"):
            raise ValueError(f"anomaly={amode!r}: expected 'auto', "
                             "'on', or 'off'")
        # "auto" resolves OFF today — the ROADMAP-4 autotuner is the
        # intended flipper, exactly like device_telemetry
        self._acfg = self.icfg.anomaly_cfg or AnomalyConfig()
        self._anom = None
        if amode == "on":
            self._anom = AnomalyMonitor(self._acfg, reg, "serving")
            self._anom.watch_all(default_serving_detectors(self._acfg))
        # per-step signal scratch (last dispatch t0, last counter
        # reads) — plain floats, touched only when the monitor exists
        self._anom_prev: Dict[str, float] = {}
        # --- deep-capture windows (telemetry/profiler.py): the ONE
        # profiler seam for this engine.  Constructed when a capture
        # directory is configured; engine.capture(out_dir=...) and the
        # anomaly path (falling back to FailureConfig.flight_dir) can
        # also create it lazily via _ensure_capture
        self._cap = None
        self._warned_no_capture_dir = False
        if self.icfg.profile:
            self._cap = ProfilerCapture(
                self.icfg.profile, tracer=self.tracer,
                max_captures=self._acfg.max_captures)
            if self.icfg.profile_steps > 0:
                self._cap.arm(self.icfg.profile_steps, "config")
        # --- per-class SLO scorecard (telemetry/slo.py): None when off
        # — the request tracker's hook sites are then a single
        # attribute test (the zero-cost bar, extended by test); on, it
        # rides the tracker's existing stamp sites (zero new clock
        # reads) and registers its burn detectors into the anomaly
        # catalog when that plane is also on
        smode = self.icfg.slo
        if smode not in ("auto", "on", "off"):
            raise ValueError(f"slo={smode!r}: expected 'auto', 'on', "
                             "or 'off'")
        # "auto" resolves OFF today, like every telemetry gate here
        self._slo = None
        if smode == "on":
            self._slo = SloTracker(
                self.icfg.slo_objectives or default_slo_objectives(),
                reg)
            self.requests.slo = self._slo
            if self._anom is not None:
                self._slo.bind(self._anom,
                               lambda: self._steps_done,
                               self._on_anomaly)

    def _prefix_hit_rate(self):
        prompt = self.timings["prompt_tokens"]
        if not prompt:
            return None
        return self.timings["cached_tokens"] / prompt

    def _note_compile(self, kind: str, key) -> None:
        """Count one executable-cache fill; a (kind, key) this engine
        already compiled is a runtime retrace and warns loudly (the
        dynamic complement of tpulint's static retrace-hazard rule)."""
        tm = self.timings
        tm["compiles"] += 1
        if (kind, key) in self._compiled_ever:
            tm["compile_retraces"] += 1
            logger.warning(
                "serving program %s/%r RECOMPILED at runtime (retrace "
                "#%d): the executable cache is churning — LRU thrash, "
                "shape churn, or a weight refresh",
                kind, key, int(tm["compile_retraces"]))
        else:
            self._compiled_ever.add((kind, key))

    def reset_timings(self) -> None:
        """Zero the cumulative per-phase breakdown the serving loop
        records (milliseconds; ``steps`` dispatches): host scheduling,
        batch staging, the jitted call (pure enqueue when dispatch is
        async; the whole device step when something — e.g. CPU-backend
        donation — forces it synchronous), the wait for the collected
        step's sample array, and the pure device->host fetch.  A
        pipelined engine's per-step critical-path host overhead is
        roughly wall/steps - (device_ms + wait_ms)/steps.

        Also zeroes the token counters: ``prompt_tokens`` (total prompt
        tokens of admitted requests), ``cached_tokens`` (prompt tokens
        served from the prefix cache — skipped prefill), ``prefix_hits``
        (admitted requests with a nonzero match; hit rate =
        cached_tokens / prompt_tokens), and ``generated_tokens``
        (tokens emitted to live sequences).

        ``engine.timings`` is a dict-shaped view over ``engine.metrics``
        registry counters — this resets exactly those counters; use
        :meth:`reset_metrics` to also clear request records, latency
        histograms, and the span ring."""
        self.timings.reset()

    def reset_metrics(self) -> None:
        """Full telemetry reset: every registry metric (timings view
        included), the request-lifecycle tracker, and the span ring —
        what a bench leg calls between warmup and its timed region."""
        self.metrics.reset()
        self.requests.clear()
        self.tracer.clear()
        # rearm the pool high-water mark so a timed region reports ITS
        # peak, not the warmup's (the pull-gauges read live truth)
        self.state.allocator.reset_peaks()
        # rearm the anomaly detectors (fresh baselines for the timed
        # region) and the anomaly-capture budget
        if self._anom is not None:
            self._anom.reset()
            self._anom_prev.clear()
        if self._cap is not None:
            self._cap.reset_budget()
        # rearm the SLO windows + burn detectors alongside the counters
        # they quotient over (attainment restarts exact)
        if self._slo is not None:
            self._slo.reset()

    def device_snapshot(self) -> Optional[Dict]:
        """JSON-able device-telemetry summary (per-program cost
        analysis, derived MFU / HBM-bandwidth utilization, last memory
        poll) — what bench legs embed next to their request-metrics
        aggregates.  None when ``device_telemetry`` is off."""
        return None if self.devtel is None else self.devtel.snapshot()

    def anomaly_summary(self) -> Optional[Dict]:
        """JSON-able anomaly tally — total fires, per-signal counts,
        the most recent events, and the completed capture-window dirs
        — what bench legs and the loadgen SLO sweep embed.  None when
        anomaly detection is off."""
        if self._anom is None:
            return None
        return {**self._anom.summary(), "captures": self.capture_dirs}

    def slo_scorecard(self) -> Dict:
        """The per-class SLO scorecard (telemetry/slo.py,
        docs/OBSERVABILITY.md "SLOs & error budgets"): per-objective
        good/evaluated counter pairs with their attainment quotient,
        the class error budget, and the burn detector's fast/slow
        rates.  ``{"enabled": False}`` when ``InferenceConfig.slo``
        resolves off — the shape the gateway's ``GET /debug/slo``
        serves either way."""
        if self._slo is None:
            return {"enabled": False}
        return self._slo.scorecard()

    @property
    def capture_dirs(self) -> List[str]:
        """Completed deep-capture window directories (each mergeable
        into one Perfetto timeline by ``tools/tracemerge.py``)."""
        return [] if self._cap is None else list(self._cap.captures)

    def capture(self, steps: Optional[int] = None,
                reason: str = "manual",
                out_dir: Optional[str] = None) -> Optional[str]:
        """Arm an explicit deep-capture window around the next
        ``steps`` engine steps (default ``AnomalyConfig.
        capture_steps``): a bounded ``jax.profiler`` device trace +
        the window's host spans + a flight dump, merged into one
        Perfetto timeline by ``tools/tracemerge.py``.  Returns the
        capture directory (recording starts at the next step
        boundary), or None when a window is already armed/active.
        ``out_dir`` overrides the configured directory for a manager
        not yet constructed; with neither configured nor passed this
        raises — an explicit capture with nowhere to write is a
        caller error (the ANOMALY path degrades instead)."""
        cap = self._ensure_capture(out_dir)
        if cap is None:
            raise ValueError(
                "no capture directory: pass out_dir=, or set "
                "InferenceConfig.profile / FailureConfig.flight_dir")
        return cap.arm(steps or self._acfg.capture_steps, reason,
                       budgeted=False)

    def arm_budgeted_capture(self, reason: str = "ops") -> Optional[str]:
        """Arm a capture window under the SAME budget the anomaly path
        uses (``AnomalyConfig.max_captures``, one window at a time) —
        the form the gateway's ``POST /debug/capture`` rides, so a wire
        client can never open an unbounded window.  Returns the capture
        dir, or None when no directory is configured, the budget is
        exhausted, or a window is already armed/active (all the quiet
        degradations the anomaly path has)."""
        cap = self._ensure_capture()
        if cap is None:
            return None
        return cap.arm(self._acfg.capture_steps, reason, budgeted=True)

    def _ensure_capture(self, out_dir: Optional[str] = None):
        """The capture manager, constructed on first need from the
        first configured directory (explicit ``out_dir``, then
        ``InferenceConfig.profile``, then ``FailureConfig.flight_dir``
        — the post-mortem dir is a sensible home for anomaly
        captures).  None — once loudly — when no directory exists."""
        if self._cap is None:
            d = out_dir or self.icfg.profile \
                or getattr(self, "fcfg", None) and self.fcfg.flight_dir
            if not d:
                if not self._warned_no_capture_dir:
                    self._warned_no_capture_dir = True
                    logger.warning(
                        "anomaly capture skipped: no capture directory "
                        "(set InferenceConfig.profile or FailureConfig."
                        "flight_dir) — detectors still fire/count")
                return None
            self._cap = ProfilerCapture(
                d, tracer=self.tracer,
                max_captures=self._acfg.max_captures)
        return self._cap

    def _on_anomaly(self, ev) -> None:
        """One fired detector: breadcrumb it into the flight recorder
        (the counter was bumped by the monitor) and — budget and
        one-window-at-a-time permitting — arm a deep capture around
        the next ``capture_steps`` steps so the artifact answers WHY,
        not just WHEN."""
        self.flight.note("anomaly", **ev.as_dict())
        cap = self._ensure_capture()
        if cap is not None:
            cap.arm(self._acfg.capture_steps,
                    f"anomaly_{ev.signal}", budgeted=True)

    def _feed_step_signals(self, t0: float, t2: float,
                           t3: float) -> None:
        """Feed the per-dispatch anomaly signals from the timestamps
        and counters the step already took — zero added clock reads.
        Called only when the monitor exists."""
        anom, prev, tm = self._anom, self._anom_prev, self.timings
        step = self._steps_done
        fired = []
        last_t0 = prev.get("t0")
        prev["t0"] = t0
        if last_t0 is not None:
            fired.append(anom.observe("step_interval_ms",
                                      (t0 - last_t0) * 1e3, step))
        fired.append(anom.observe("step_device_ms", (t3 - t2) * 1e3,
                                  step))
        fired.append(anom.observe("step_host_ms", (t2 - t0) * 1e3,
                                  step))
        retr = tm["compile_retraces"]
        fired.append(anom.observe("retrace",
                                  retr - prev.get("retrace", 0), step))
        prev["retrace"] = retr
        ref = float(self.state.pool_stats()["referenced"])
        last_ref = prev.get("referenced")
        prev["referenced"] = ref
        if last_ref is not None:
            fired.append(anom.observe("kv_referenced_delta",
                                      ref - last_ref, step))
        prompt, cached = tm["prompt_tokens"], tm["cached_tokens"]
        dp = prompt - prev.get("prompt", 0)
        if dp > 0:
            fired.append(anom.observe(
                "prefix_hit_rate",
                (cached - prev.get("cached", 0)) / dp, step))
        prev["prompt"], prev["cached"] = prompt, cached
        for ev in fired:
            if ev is not None:
                self._on_anomaly(ev)

    def request_metrics(self) -> Dict:
        """Per-request lifecycle story + fleet aggregate:
        ``{"aggregate": {requests/finished/open, ttft_ms/tpot_ms/
        queue_wait_ms summaries}, "requests": [record dicts]}`` —
        records carry queue_wait/TTFT/TPOT/e2e ms and prompt/cached/
        generated token counts that reconcile exactly with the
        ``engine.timings`` counters (tests/test_telemetry.py holds the
        invariant)."""
        return {"aggregate": self.requests.aggregate(),
                "requests": [r.as_dict() for r in self.requests.records()]}

    def metrics_snapshot(self) -> Dict:
        """JSON-able snapshot of every serving metric (counters +
        latency histograms); see also ``engine.metrics.prometheus_text()``
        and ``engine.metrics.write_jsonl(path)``."""
        return self.metrics.snapshot()

    def publish_metrics(self, monitor, step: int = 0) -> None:
        """Fan the current metric values out through a ``monitor/``
        writer (CSV/TensorBoard/WandB/Comet) — serving metrics ride the
        same pipeline as training scalars."""
        self.metrics.publish(monitor, step)

    def refresh_params(self, params) -> None:
        """Swap the served weights (hybrid-engine policy refresh).

        Re-applies the serving cast AND re-quantizes under weight_quant —
        the step closure captures the quantized tree, so merely assigning
        ``self.params`` would keep serving the old quantized weights."""
        if self._stream is not None:
            raise NotImplementedError(
                "refresh_params under weight_stream: re-spill the store "
                "by rebuilding the engine")
        self.params = jax.tree.map(
            lambda x: x.astype(self.icfg.param_dtype)
            if x.dtype == jnp.float32 else x, params)
        if self.icfg.weight_quant:
            from .quantization import quantize_model_params
            from ..ops.quant import WEIGHT_QUANT_BITS
            self.params, self._quant = quantize_model_params(
                self.params, bits=WEIGHT_QUANT_BITS[self.icfg.weight_quant],
                quantize_embeddings=self.icfg.quantize_embeddings)
            # step/burst closures hold the old quant tree
            self._pstep_fns.clear()
            self._burst_fns.clear()
            # the rebuilt programs recompile on their next call: they
            # are cold again (warm programs run under the watchdog,
            # and a deadline must never time an XLA compile)
            self._warm_keys.clear()
            # rebuilding against fresh weights is a LEGITIMATE
            # recompile: reset the retrace ledger and the per-program
            # cost table (the new programs get probed anew)
            self._compiled_ever.clear()
            if self.devtel is not None:
                self.devtel.program_costs.clear()
        self._shard_weights()

    # ------------------------------------------------------------------
    # SPMD sharding (TP + ZeRO-Inference weight sharding)
    # ------------------------------------------------------------------
    def _setup_sharding(self) -> None:
        """Resolve mesh shardings once: KV head-split + weight specs."""
        self._repl = None
        self._kv_nsh = None
        self._tp_mesh = None
        topo = self.topology
        if topo is None:
            return
        self._repl = topo.replicated
        tp = topo.tp_size
        cfg = self.cfg
        head_split = (tp > 1 and cfg.num_kv_heads % tp == 0
                      and cfg.num_heads % tp == 0)
        # kv: [L, blocks, bs, 2, Hkv, D] — split the kv-head dim
        kv_spec = P(None, None, None, None,
                    TENSOR_AXIS if head_split else None)
        self._kv_nsh = NamedSharding(topo.mesh, kv_spec)
        if head_split:
            # the Pallas kernel runs under shard_map, one head group/chip
            self._tp_mesh = topo.mesh
        self.state.kv = jax.device_put(self.state.kv, self._kv_nsh)
        self._shard_weights()

    def _resolve_serving_comm(self):
        """Resolve ``comm_overlap``/``comm_quant``/``comm_tiles`` against
        the mesh and model shapes into a :class:`ServingComm` plan (or
        None).  The contract: an eligible mesh gets the decomposed
        collectives, anything else degrades LOUDLY to the serial exact
        path — never an error, because one config must serve on a
        laptop and on the pod (docs/SERVING.md "Overlapped & quantized
        collectives")."""
        mode = self.icfg.comm_overlap
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"comm_overlap={mode!r}: expected 'auto', "
                             "'on', or 'off'")
        qname = self.icfg.comm_quant
        if qname not in (None, "int8", "int4"):
            raise ValueError(f"comm_quant={qname!r}: expected None, "
                             "'int8', or 'int4'")
        topo = self.topology
        tp = 0 if topo is None else topo.tp_size
        if tp <= 1:
            if mode == "on" or qname is not None:
                logger.warning(
                    "comm_overlap/comm_quant: no tensor axis on this "
                    "engine (%s) — collectives stay serial and exact",
                    "single-chip" if topo is None
                    else f"mesh {topo.axis_sizes}")
            return None
        if mode == "off" and qname is None:
            return None
        cfg = self.cfg
        downproj = cfg.num_experts <= 1 and cfg.d_ff % tp == 0
        unembed = cfg.vocab_size % tp == 0
        if not downproj and not unembed:
            logger.warning(
                "comm_overlap: neither d_ff=%d nor vocab=%d is eligible "
                "on tensor=%d (MoE layers and indivisible dims stay "
                "with GSPMD); serial exact collectives",
                cfg.d_ff, cfg.vocab_size, tp)
            return None
        bits = {None: None, "int8": 8, "int4": 4}[qname]
        if bits is not None and not downproj:
            logger.warning(
                "comm_quant=%s: the down-projection all-reduce is "
                "ineligible on this model/mesh and the logits gather "
                "never quantizes — exact wire", qname)
            bits = None
        if mode == "off":
            # comm_quant alone: ONE serial quantized all-reduce on the
            # down-projection — and nothing else; "off" must leave the
            # unembed gather with GSPMD (quantization never applies to
            # it, and a tiles=1 ppermute ring would replace the fused
            # all-gather for no benefit)
            if bits is None:
                return None
            tiles, unembed = 1, False
        else:
            tiles = max(1, self.icfg.comm_tiles)
        from ..comm.overlap import ServingComm
        return ServingComm(mesh=topo.mesh, axis_name=TENSOR_AXIS,
                           tiles=tiles, quant_bits=bits,
                           downproj=downproj, unembed=unembed)

    def _shard_weights(self) -> None:
        """Place the (possibly quantized) weight trees on the mesh.

        Dense un-quantized weights use the logical-axis TP rules
        (parallel/sharding.py — the same specs that shard training), with
        any ``fsdp`` axis layered on as pure memory sharding (the
        ZeRO-Inference analog: XLA all-gathers each layer at use).
        Quantized trees have grouped flat layouts the head rules cannot
        address, so they are memory-sharded over the largest divisible
        dim instead."""
        topo = self.topology
        if topo is None:
            return
        from ..parallel import sharding as shd

        def put(x, spec):
            return jax.device_put(x, NamedSharding(topo.mesh, spec))

        def generic(tree):
            """Memory-shard every array leaf: tensor axis first, then
            fsdp, over whichever large dims divide."""
            def go(x):
                if not isinstance(x, (jax.Array, np.ndarray)) \
                        or np.ndim(x) == 0:
                    return x
                spec = shd.add_fsdp_to_spec(P(), x.shape, topo,
                                            min_size=1 << 14,
                                            axis=TENSOR_AXIS)
                spec = shd.add_fsdp_to_spec(spec, x.shape, topo,
                                            min_size=1 << 14,
                                            axis=FSDP_AXIS)
                return put(x, spec)
            return jax.tree.map(go, tree)

        if self._quant is None:
            shapes = jax.tree.map(lambda x: tuple(x.shape), self.params)
            axes = self.model.param_axes
            if self._stream is not None:
                # block weights were spilled to the NVMe store; only the
                # resident remainder needs placement
                axes = {k: v for k, v in axes.items() if k in self.params}
            specs = shd.tree_specs(axes, topo, shapes=shapes)
            is_spec = lambda s: isinstance(s, P)   # noqa: E731
            specs = jax.tree.map(
                lambda s, x: shd.add_fsdp_to_spec(s, tuple(x.shape), topo,
                                                  min_size=1 << 14),
                specs, self.params, is_leaf=is_spec)
            self.params = jax.tree.map(put, self.params, specs,
                                       is_leaf=lambda x: isinstance(x, P))
        else:
            # dense remainder (norms/biases/embeds) + quantized payloads
            self.params = generic(self.params)
            self._quant = generic(self._quant)

    def _setup_weight_stream(self) -> None:
        """Spill per-layer block weights (quantized payloads under
        weight_quant) to the NVMe store; the forward streams them back
        one layer at a time.  HBM then holds: embeddings/head/norms, the
        KV cache, and ONE layer's weights."""
        from .weight_stream import NVMeWeightStore

        store = NVMeWeightStore(self.icfg.weight_stream,
                                self.cfg.num_layers)
        if self.topology is not None:
            # SPMD serving: the fetch callback pins to one mesh device;
            # GSPMD broadcasts each layer to the mesh at first use
            store.spmd_device = self.topology.mesh.devices.flat[0]
        record: Dict[str, object] = {"dense": self.params.pop("blocks")}
        store.qmeta = None
        if self._quant is not None and self._quant.get("blocks"):
            qblocks = self._quant["blocks"]
            self._quant = {**self._quant, "blocks": {}}
            qarrays, qmeta = {}, {}
            for gname, grp in qblocks.items():
                qarrays[gname], qmeta[gname] = {}, {}
                for name, qt in grp.items():
                    a = {"data": qt.data, "scale": qt.scale}
                    if qt.zero is not None:
                        a["zero"] = qt.zero
                    qarrays[gname][name] = a
                    qmeta[gname][name] = (qt.bits, qt.shape[1:], qt.dtype,
                                          qt.layout)
            record["quant"] = qarrays
            store.qmeta = qmeta
            # mixed-gemm eligibility: row-wise int8 (weight-shaped) or
            # packed row-wise int4 per-layer payloads; expert and
            # shared-expert weights don't count — the forward always
            # consumes them dense
            from ..ops.quant import is_mixed_gemm_layout
            from .quantization import DENSE_ONLY_GROUPS
            store.mixed_gemm_eligible = all(
                is_mixed_gemm_layout(qt)
                for gname, grp in qblocks.items()
                if gname not in DENSE_ONLY_GROUPS
                for qt in grp.values())
        store.spill(record)
        self._stream = store
        self._force_resident_weight_modes()

    def _force_resident_weight_modes(self) -> None:
        """THE needs-resident-weights gate: every decode mode that runs
        multiple model invocations per host round trip — device-side
        bursts (the scan feeds weights per iteration) and speculative
        verify windows (worthless when each layer streams from NVMe at
        step latency anyway) — is forced off in ONE place when
        ``weight_stream`` keeps block weights non-resident.  New modes
        with the same requirement belong here, not in a copy-pasted
        warning branch."""
        forced = {}
        if self.icfg.decode_burst > 1:
            forced["decode_burst"] = 1
        if self.icfg.spec_decode == "on":
            # "auto" stays untouched: it resolves off today, silently —
            # an auto that learns to turn itself on (ROADMAP item 4)
            # must consult this gate in _setup_spec_decode
            forced["spec_decode"] = "off"
        if forced:
            logger.warning(
                "weight_stream: "
                + " and ".join(f"{k}={getattr(self.icfg, k)!r}"
                               for k in forced)
                + (" need" if len(forced) > 1 else " needs")
                + " resident weights; forcing "
                + ", ".join(f"{k}={v!r}" for k, v in forced.items()))
            self.icfg = dataclasses.replace(self.icfg, **forced)

    def _setup_spec_decode(self) -> None:
        """Resolve the ``spec_decode`` config to a proposer (or None)
        and the engine's fixed verify-window width ``_n_verify``
        (``spec_max_draft + 1`` when on, else 1 — which keeps every
        compiled program byte-identical to a pre-spec engine)."""
        mode = self.icfg.spec_decode
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"spec_decode={mode!r}: expected 'auto', "
                             "'on', or 'off'")
        if mode == "on" and self.icfg.decode_burst > 1:
            raise ValueError(
                "spec_decode='on' with decode_burst > 1: both multi-token"
                " the decode path (bursts device-side); pick one")
        # "auto" currently resolves OFF: draft acceptance is workload-
        # dependent, and the per-request acceptance_rate / draft-length
        # profiles recorded below are exactly the measured signal the
        # autotuner (ROADMAP item 4) needs to flip this from data
        on = mode == "on"
        self._spec = None
        self._n_verify = 1
        if on:
            if self.icfg.spec_max_draft < 1:
                raise ValueError("spec_max_draft must be >= 1")
            from .spec_decode import NgramProposer
            self._spec = NgramProposer(self.icfg.spec_max_draft)
            self._n_verify = self.icfg.spec_max_draft + 1
        self._sched_drafts: Dict[int, List[int]] = {}

    def _place_default_device(self) -> None:
        """Ship weights to the serving device if they were built on
        another backend — the ZeRO-Inference big-model flow: a model too
        large to materialize dense in HBM is initialized/loaded and
        group-quantized ON HOST (``jax.default_device(cpu)``), and only
        the int8/int4 payloads ever reach the chip (reference:
        inference/quantization — quantize-then-place)."""
        dev = jax.devices()[0]

        def to_dev(x):
            if isinstance(x, jax.Array) and x.committed and \
                    next(iter(x.devices())).platform != dev.platform:
                return jax.device_put(x, dev)
            return x

        self.params = jax.tree.map(to_dev, self.params)
        if self._quant is not None:
            self._quant = jax.tree.map(to_dev, self._quant)

    def _stage(self, tree):
        """Replicate host-built batch metadata onto the mesh."""
        if self._repl is None:
            return tree
        return jax.device_put(tree, self._repl)

    def _kv_zeros(self):
        """A pristine zero cache with the serving sharding applied."""
        kv = self.state.cfg.kv_zeros()
        if self._kv_nsh is not None:
            kv = jax.device_put(kv, self._kv_nsh)
        return kv

    def _offload_kv(self) -> None:
        """Move the paged KV cache to host memory (ZeRO-Inference KV
        offload); best-effort — backends without an addressable host
        space keep it in HBM with a warning."""
        try:
            # probe the whole path: the backend must also EXECUTE
            # in-program host<->device transfers, not just place arrays
            # (the CPU backend accepts the placement but has no runtime
            # implementation for the device_put custom call)
            def roundtrip(x):
                h = jax.device_put(x, jax.memory.Space.Host)
                return jax.device_put(h * 2.0, jax.memory.Space.Device)
            jax.block_until_ready(jax.jit(roundtrip)(jnp.ones(8)))
            kv = jax.device_put(self.state.kv, jax.memory.Space.Host)
            jax.block_until_ready(kv)
            self.state.kv = kv
            self._kv_on_host = True
        except Exception as e:
            logger.warning(f"kv_offload unavailable on this backend "
                           f"({type(e).__name__}); KV stays in HBM")
            self._kv_on_host = False

    # ------------------------------------------------------------------
    def _resolve_fw(self, mbs: Optional[int]):
        """Resolve the forward-pass knobs shared by every compiled
        serving program (probing attn_impl/mixed_gemm on first use)."""
        mbs = mbs or self.max_blocks_per_seq
        impl = self.icfg.attn_impl
        if impl == "auto":
            impl = self._probe_attn_impl()
        mixed = self._resolve_mixed_gemm(impl)
        self._mixed_gemm_active = mixed
        comm = self._serving_comm
        if comm is not None and mixed and comm.downproj:
            # mixed-GEMM keeps the down-projection weight quantized for
            # the VMEM-dequant kernel — only the unembed gather can
            # still decompose; the plan (and its wire accounting)
            # shrinks to match the compiled program
            comm = comm._replace(downproj=False, quant_bits=None)
            if not comm.unembed:
                comm = None
        self._comm_active = comm
        self._comm_stats = None        # re-derive from the active plan
        return dict(attn_impl=impl, mixed_gemm=mixed,
                    kv_host=getattr(self, "_kv_on_host", False),
                    shard_mesh=self._tp_mesh, stream=self._stream,
                    comm=comm), mbs

    def _donate_kv(self) -> bool:
        """Whether serving programs donate the paged cache.  See
        ``InferenceConfig.kv_donate``: donation on XLA:CPU blocks each
        dispatch until the in-flight producer of the donated cache
        finishes, so a pipelined CPU engine trades one transient cache
        copy for async dispatch."""
        mode = self.icfg.kv_donate
        if mode == "off":
            return False
        if mode == "auto" and self.icfg.pipeline_depth >= 2 \
                and self.icfg.decode_burst <= 1 \
                and jax.default_backend() == "cpu":
            # burst engines route generate() to the strict-sync driver,
            # so their steps never pipeline — keep donating for them
            return False
        return True

    def _serving_jit(self, fn, kv_argnum: int = 2,
                     kv_only_output: bool = False):
        """jit a serving program whose paged-KV operand rides
        ``kv_argnum`` and whose output is (small replicated output,
        new_kv) — or bare new_kv with ``kv_only_output`` (the COW block
        copy) — with the cache donated (see ``_donate_kv``) and its
        sharding (host placement / head split) pinned.  THE one place
        the KV donation/placement jit policy lives."""
        donate = (kv_argnum,) if self._donate_kv() else ()
        if getattr(self, "_kv_on_host", False):
            # pin the cache output to host memory so the persistent
            # state never round-trips through HBM between steps
            kv_sh = jax.tree.map(lambda x: x.sharding, self.state.kv)
            out_sh = kv_sh if kv_only_output else (None, kv_sh)
            return jax.jit(fn, donate_argnums=donate, out_shardings=out_sh)
        if self._kv_nsh is not None:
            # logits/tokens replicated (one small host fetch), cache
            # keeps its head-split sharding across the donation
            out_sh = self._kv_nsh if kv_only_output \
                else (self._repl, self._kv_nsh)
            return jax.jit(fn, donate_argnums=donate, out_shardings=out_sh)
        return jax.jit(fn, donate_argnums=donate)

    def _build_step(self, mbs: Optional[int] = None):
        """Compile one SplitFuse step bounded to ``mbs`` context blocks —
        the logits-returning sibling of :meth:`_build_pstep` (the serving
        loop runs pstep; this entry serves logits-level consumers:
        quant/TP parity tests and offline scoring).

        Steps are compiled per power-of-two context bucket (like the
        decode-burst prefix buckets): the XLA attention paths do work
        proportional to the compiled block bound, so early prefill steps
        must not pay for the engine's maximum context (the Pallas kernel
        skips dead blocks dynamically; the dense paths cannot)."""
        cfg = self.cfg
        bs = self.icfg.kv_block_size
        fw, mbs = self._resolve_fw(mbs)

        # NOTE: the quant tree is a jit ARGUMENT, never a closure —
        # closed-over trees bake into the HLO as constants (7.5 GB of
        # captured constants for llama3-8b int8, which killed the remote
        # compile); as an argument it is device buffers, like params
        def step(params, quant, kv, batch: RaggedBatch):
            return ragged_forward(cfg, params, kv, batch, bs, mbs,
                                  quant=quant, **fw)

        return self._serving_jit(step)

    def _build_pstep(self, mbs: Optional[int], sampling: SamplingParams):
        """Compile one pipelined serving step for a context bucket:
        deferred-token feedback + ragged forward + ON-DEVICE sampling.
        The sampled [max_seqs] token array is both a program output (read
        back one step later) and the next step's feedback operand, so
        the host round trip leaves the critical path.  Cached per
        (bucket, sampler_key) — stop_token/max_new_tokens are host loop
        concerns and never force a recompile."""
        cfg = self.cfg
        bs = self.icfg.kv_block_size
        fw, mbs = self._resolve_fw(mbs)
        repl = self._repl

        def sample_fn(logits, keys):
            if repl is not None:
                # pin the logits replicated BEFORE the categorical: on
                # legacy jax the threefry bits behind temperature
                # sampling are sharding-dependent, so a vocab-sharded
                # logits tensor (GSPMD's natural layout for the serial
                # unembed) and a replicated one (the shard_map overlap
                # path's output) would sample DIFFERENT tokens from
                # bitwise-identical logits — this constraint makes
                # seeded streams invariant to the comm plan (the gather
                # it forces happens either way for the replicated
                # token output)
                logits = jax.lax.with_sharding_constraint(logits, repl)
            return sample_rows(logits, sampling, keys)

        def pstep(params, quant, kv, batch: RaggedBatch, prev_toks, rng):
            return pipelined_ragged_step(cfg, params, quant, kv, batch,
                                         prev_toks, rng, sample_fn,
                                         bs, mbs, **fw)

        return self._serving_jit(pstep)

    def _probe_key(self, what: str):
        cfg = self.cfg
        topo_sig = (None if self.topology is None else
                    tuple(sorted(self.topology.axis_sizes.items())))
        return (what, jax.default_backend(), cfg.num_layers, cfg.d_model,
                cfg.num_heads, cfg.num_kv_heads, self.icfg.token_budget,
                self.icfg.max_seqs, self.icfg.kv_block_size,
                self.icfg.num_kv_blocks, self.max_blocks_per_seq,
                self.icfg.kv_quant, topo_sig, self._tp_mesh is not None)

    def _probe_variants(self, label: str, variants):
        """Race full ragged steps, one per variant (name -> extra
        ragged_forward kwargs), on the real compiled shapes; returns
        {name: seconds-per-3-steps} for whatever survived."""
        import time

        cfg, bs, mbs = self.cfg, self.icfg.kv_block_size, \
            self.max_blocks_per_seq
        T, ms = self.icfg.token_budget, self.icfg.max_seqs
        nb = self.icfg.num_kv_blocks
        # synthetic batch on the compiled shapes — does NOT touch the
        # state manager (no slot/block allocation).  Representative work:
        # every slot at FULL context (tables fully populated, positions at
        # the last context token) — a near-empty batch would let the
        # Pallas kernel skip almost all of its blocks while the XLA
        # gather path pays full cost regardless, biasing the probe.
        tables = np.zeros((ms, nb), np.int32)
        tables[:, :mbs] = np.arange(mbs, dtype=np.int32)[None, :] \
            % max(1, nb - 1)
        last_pos = mbs * bs - 1
        batch = RaggedBatch(
            token_ids=jnp.zeros(T, jnp.int32),
            positions=jnp.full(T, last_pos, jnp.int32),
            seq_slot=jnp.arange(T, dtype=jnp.int32) % ms,
            token_valid=jnp.ones(T, bool),
            block_tables=jnp.asarray(tables),
            context_lens=jnp.full(ms, last_pos + 1, jnp.int32),
            logits_idx=jnp.full(ms, -1, jnp.int32).at[0].set(0),
            n_tokens=T, n_seqs=ms)
        batch = self._stage(batch)
        results = {}
        # probe on the real (pre-serving, all-zeros) cache with donation,
        # threading the cache through — never two full KV pools live at
        # once, matching the real step's memory profile
        kv = self.state.kv
        for name, extra in variants.items():
            try:
                jit_kw = {}
                if self._kv_nsh is not None:
                    jit_kw["out_shardings"] = (self._repl, self._kv_nsh)

                def probe_step(params, quant, pkv, pbatch, _extra=extra):
                    return ragged_forward(
                        cfg, params, pkv, pbatch, bs, mbs,
                        quant=quant,
                        shard_mesh=self._tp_mesh, stream=self._stream,
                        kv_host=getattr(self, "_kv_on_host", False),
                        **_extra)

                # one compile per probed attention variant IS the
                # autotune measurement; each wrapper is used then dropped
                f = jax.jit(probe_step, donate_argnums=(2,), **jit_kw)  # tpulint: disable=retrace-hazard
                logits, kv = f(self.params, self._quant, kv, batch)
                float(jnp.sum(logits))      # compile + settle, untimed
                # probe budget from ONE post-compile step: a path an
                # order of magnitude behind the best-so-far (3-step
                # totals both sides) loses without the timed loop —
                # pathological paths (100 s/step seen on the chunked XLA
                # path at 8B shapes) must not stall start-up for minutes
                t_w = time.perf_counter()
                logits, kv = f(self.params, self._quant, kv, batch)
                float(jnp.sum(logits))
                warm3 = (time.perf_counter() - t_w) * 3
                best = min(results.values()) if results else None
                if warm3 > (180.0 if best is None
                            else max(30.0, 10 * best)):
                    logger.info(f"{label} probe: {name} at "
                                f"{warm3 / 3:.1f}s/step — skipping "
                                "timed loop")
                    results[name] = warm3
                    continue
                t0 = time.perf_counter()
                for _ in range(3):
                    logits, kv = f(self.params, self._quant, kv, batch)
                float(jnp.sum(logits))      # completion barrier
                results[name] = time.perf_counter() - t0
            except Exception as e:          # Mosaic unavailable/failed
                logger.warning(f"{label} probe: {name} failed "
                               f"({type(e).__name__}); skipping")
        # restore a pristine zero cache (the probe wrote its fake token)
        # and drop any prefix-cache index entries — zeroed blocks no
        # longer hold the content their hashes promise
        self.state.kv = self._kv_zeros()
        self.state.reset_prefix_cache()
        if getattr(self, "_kv_on_host", False):
            self.state.kv = jax.device_put(self.state.kv,
                                           jax.memory.Space.Host)
        if results:
            logger.info(
                f"{label} probe: {min(results, key=results.get)} "
                f"({ {k: round(v * 1e3, 1) for k, v in results.items()} }"
                " ms/3 steps)")
        return results

    def _probe_attn_impl(self) -> str:
        """Time one ragged forward per implementation on the real compiled
        shapes and keep the winner (the Pallas streaming kernel wins on
        bare-metal TPUs; the XLA gather path wins on CPU meshes and some
        virtualized/tunneled chips where Mosaic underperforms).  Results
        are memoized per (backend, shape signature) for the process."""
        key = self._probe_key("attn")
        cached = _PROBE_CACHE.get(key)
        if cached is not None:
            return cached
        results = self._probe_variants(
            "paged-attention",
            {"xla": {"attn_impl": "xla"}, "pallas": {"attn_impl": "pallas"}})
        best = min(results, key=results.get) if results else "xla"
        _PROBE_CACHE[key] = best
        return best

    def _quant_is_rowwise(self) -> bool:
        """The mixed-input kernel family consumes the row-wise int8
        (weight-shaped payload) and packed row-wise int4 layouts.
        Only the weights the ``_mm`` projection sites consume count:
        expert/shared-expert weights (dense in moe_ffn/_shared_expert)
        and the embedding table (dequantized once per step) are always
        dequantized regardless."""
        from ..ops.quant import QuantizedTensor, is_mixed_gemm_layout
        from .quantization import DENSE_ONLY_GROUPS
        if self._quant is None:
            return False
        blocks = {k: v for k, v in
                  (self._quant.get("blocks") or {}).items()
                  if k not in DENSE_ONLY_GROUPS}
        leaves = [x for x in jax.tree.leaves(
            blocks, is_leaf=lambda x: isinstance(x, QuantizedTensor))
            if isinstance(x, QuantizedTensor)]
        return bool(leaves) and all(is_mixed_gemm_layout(q)
                                    for q in leaves)

    def _mixed_gemm_eligible(self) -> bool:
        return (self._quant_is_rowwise() if self._stream is None
                else self._stream.mixed_gemm_eligible)

    def _require_mixed_gemm_eligible(self) -> None:
        if not self._mixed_gemm_eligible():
            what = ("the weight-stream payloads are"
                    if self._stream is not None
                    else "the resident quantized weights are")
            raise ValueError(
                f"mixed_gemm='on': {what} not a row-wise int8/int4 "
                "layout the kernel family consumes; use 'auto'")

    def _resolve_mixed_gemm(self, attn_impl: str) -> bool:
        """Resolve the mixed_gemm config to a bool for this build
        (reference analog: the cuda_linear kernel selection)."""
        mode = self.icfg.mixed_gemm
        if mode == "on":
            self._require_mixed_gemm_eligible()
            return True
        if mode == "off" or not self._mixed_gemm_eligible():
            return False
        # streamed and resident steps have different cost profiles —
        # never share a probe verdict between them
        key = self._probe_key(
            "mixed_gemm_" + attn_impl
            + ("_stream" if self._stream is not None else ""))
        cached = _PROBE_CACHE.get(key)
        if cached is None:
            results = self._probe_variants(
                "mixed-gemm",
                {"dequant": {"attn_impl": attn_impl, "mixed_gemm": False},
                 "mixed": {"attn_impl": attn_impl, "mixed_gemm": True}})
            cached = (min(results, key=results.get) == "mixed"
                      if results else False)
            _PROBE_CACHE[key] = cached
        return cached

    # ------------------------------------------------------------------
    # request API (reference: engine_v2.put :107)
    # ------------------------------------------------------------------
    def put(self, uid: int, tokens: Sequence[int], priority: int = 0,
            deadline_ms: Optional[float] = None,
            slo_class: Optional[str] = None) -> AdmissionVerdict:
        """Enqueue a new request or continue a known one; returns an
        :class:`AdmissionVerdict` (truthy iff the tokens entered the
        engine) instead of growing the backlog unboundedly.

        ``priority``: lower = more important (nice-level semantics;
        default 0).  ``deadline_ms``: relative to arrival — a request
        still unfinished when it elapses is terminally closed with
        status ``deadline_exceeded``.  Both only matter on the FIRST
        put for a uid; continuations keep the admitted values and are
        never shed (the request already holds KV or a queue place).
        ``slo_class`` tags the lifecycle record with the class the
        request was admitted under — pure attribution for the SLO
        scorecard (telemetry/slo.py); it changes no admission or
        scheduling decision here (class->priority/deadline folding is
        the gateway's job, class->pool the fleet router's).  With the
        default :class:`OverloadConfig` (unbounded queue) the verdict
        is always truthy — legacy callers that ignore the return value
        see the legacy behavior."""
        now = time.perf_counter()
        toks = [int(t) for t in tokens]
        if uid in self._meta or uid in self.state.seqs \
                or uid in self._pending:
            self.requests.on_arrival(uid, now, slo_class=slo_class)
            self._pending.setdefault(uid, []).extend(toks)
            return AdmissionVerdict(True, "continued")
        if self._draining or self._health == "dead":
            # the drain/death contract: admission is stopped for NEW
            # requests (the continuation branch above still lands —
            # in-flight work must be able to finish); the record exists
            # so the router sees shed-at-drain, not silence (and the
            # class tag keeps the shed attributable to its SLO budget)
            self.requests.on_arrival(uid, now, slo_class=slo_class)
            self.requests.on_finish(uid, now, status="shed")
            return AdmissionVerdict(False, "shed",
                                    reason="engine is "
                                    + ("dead" if self._health == "dead"
                                       else "draining"))
        ocfg = self.ocfg
        queued: List[tuple] = []
        if ocfg.max_queued_requests is not None \
                or ocfg.max_queued_tokens is not None:
            # requests still waiting for their FIRST admission (a live
            # sequence is not queued — it is never shed here)
            for quid, qt in self._pending.items():
                if not qt or quid in self.state.seqs:
                    continue
                m = self._meta.get(quid)
                queued.append((
                    quid,
                    effective_priority(m.priority if m else 0,
                                       m.t_arrival if m else now,
                                       now, ocfg.aging_ms),
                    len(qt)))
        action, victims = admission_decision(ocfg, priority, len(toks),
                                             queued, now)
        if action == "shed":
            # terminal from birth: the record exists (the load harness
            # counts shed vs finished) but never holds KV or budget
            self.requests.on_arrival(uid, now, slo_class=slo_class)
            self.requests.on_finish(uid, now, status="shed")
            return AdmissionVerdict(False, "shed",
                                    reason="admission queue bound")
        for victim in victims:
            self._finish(victim, "shed")
            self._reaped.add(victim)
        if action == "degrade":
            priority = max(priority, ocfg.degrade_priority)
        self._meta[uid] = RequestMeta(priority=priority,
                                      deadline_ms=deadline_ms,
                                      t_arrival=now,
                                      degraded=(action == "degrade"))
        if deadline_ms is not None:
            self._deadline_uids.add(uid)
        self.requests.on_arrival(uid, now, slo_class=slo_class)
        self._pending.setdefault(uid, []).extend(toks)
        if self._spec is not None:
            # seed the prompt-lookup history with the prompt (emitted
            # tokens are observed at collect; continuation puts carry
            # tokens the history already holds)
            self._spec.observe(uid, toks)
        return AdmissionVerdict(
            True, "degraded" if action == "degrade" else "queued",
            evicted_uids=victims)

    def flush(self, uid: int) -> None:
        """(reference: engine_v2.flush :242)."""
        self._finish(uid, "finished")

    def cancel(self, uid: int) -> None:
        """Client abort: terminally close ``uid`` wherever it is —
        queued (drops its backlog entry), running (KV released back
        through the refcounted allocator), or already gone (no-op).
        Safe mid-flight: an uncollected step's emit for a cancelled uid
        is discarded by the slot guard in ``_collect``, and its stale KV
        writes land in rows no surviving sequence reads."""
        self._finish(uid, "cancelled")
        self._reaped.add(uid)

    def _finish(self, uid: int, status: str) -> None:
        """Terminally close a request through whichever exit applies: a
        live sequence releases its KV (the ``on_release`` hook below
        does the bookkeeping), a queued-only request just drops its
        backlog entry.  Idempotent — closing an already-closed or
        unknown uid is a no-op."""
        if uid in self.state.seqs:
            self._closing[uid] = status
            try:
                self.state.release(uid)   # -> _on_state_release
            finally:
                self._closing.pop(uid, None)
            return
        self._forget(uid, status)

    def _forget(self, uid: int, status: str) -> None:
        """Drop every per-request bookkeeping entry and close the
        lifecycle record terminally — the ONE teardown both exit shapes
        (queued-only close, KV-release close) share; add any future
        per-request state here and it is cleaned on every path."""
        self._pending.pop(uid, None)
        self._fb_step.pop(uid, None)
        self._meta.pop(uid, None)
        self._deadline_uids.discard(uid)
        self._preempt_gen.pop(uid, None)
        self._ctx_exhausted.discard(uid)
        self._strikes.pop(uid, None)
        if self._spec is not None:
            self._spec.forget(uid)
        rec = self.requests.open.get(uid) if self._anom is not None \
            else None
        self.requests.on_finish(uid, status=status)
        if rec is not None and rec.tpot_ms is not None:
            # TPOT is only final at terminal close — feed it here so a
            # decode-tail slowdown is a per-request latency signal too
            evt = self._anom.observe("tpot_ms", rec.tpot_ms,
                                     self._steps_done)
            if evt is not None:
                self._on_anomaly(evt)

    def _on_state_release(self, uid: int) -> None:
        """``StateManager.on_release`` hook: a sequence's KV was just
        freed.  Preemption is the one non-terminal release (the request
        re-queues and its record stays open); every other path closes
        the lifecycle record — ``flush`` ("finished"), engine close-outs
        (the status staged in ``_closing``: deadline expiry, cancel,
        context exhaustion), or a direct ``StateManager.release`` from
        outside the engine ("released").  This is what makes
        ``request_metrics()`` leak-free: there is no way to drop KV
        without a terminal lifecycle event."""
        if uid in self._preempting:
            return
        self._forget(uid, self._closing.get(uid, "released"))

    def _drain_reaped(self) -> set:
        """Uids the ENGINE terminally closed since the last call
        (deadline expiry, ``cancel()``, shed-by-eviction) — the
        ``generate()`` drivers drop them from their active sets;
        direct-API callers can poll ``query()["status"]`` instead."""
        out = self._reaped
        self._reaped = set()
        return out

    def query(self, uid: int) -> Dict:
        """(reference: engine_v2.query :158).  ``status`` is ``queued``
        (admitted, waiting for KV — including preempted-and-requeued),
        ``running`` (holds KV), a terminal status (``finished`` /
        ``shed`` / ``cancelled`` / ``deadline_exceeded`` /
        ``context_exhausted`` / ``released`` / ``failed``),
        ``forgotten`` for a uid whose terminal record aged out of the
        finished ring (sized by ``OverloadConfig.status_retention``),
        or ``unknown`` for a uid the engine never saw — so load-harness
        clients can tell shed from done from a retention miss instead
        of reading silent zeros."""
        seq = self.state.seqs.get(uid)
        if seq is not None:
            status = "running"
        elif self._pending.get(uid) or uid in self._meta:
            status = "queued"
        else:
            s = self.requests.status_of(uid)
            status = "queued" if s == "open" else (s or "unknown")
        gen = self._preempt_gen.get(uid, [])
        return {
            "status": status,
            "pending_tokens": len(self._pending.get(uid, [])),
            "seen_tokens": seq.seen_tokens if seq else 0,
            # across preemptions: tokens generated before each eviction
            # are stashed so the full output survives the re-prefill
            "generated": list(gen) + (list(seq.tokens) if seq else []),
            "max_context": self.max_blocks_per_seq * self.icfg.kv_block_size,
            # prompt tokens this sequence got from the prefix cache
            # (prefill started at the first uncached token)
            "cached_tokens": seq.cached_tokens if seq else 0,
        }

    # ------------------------------------------------------------------
    def _schedule(self) -> List[tuple]:  # tpulint: serving-loop
        """Dynamic SplitFuse + overload policy: pack the fixed token
        budget — decode tokens first (latency), then prompt chunks
        (throughput) — while *reserving* KV blocks and slots as requests
        are admitted so the collective admission can never exceed the
        pool (reference: can_schedule engine_v2.py:184 +
        SchedulingResult).

        New prompts first consult the prefix cache: the longest cached
        block-aligned prefix is aliased into the sequence's table and
        those tokens never enter the budget — prefill starts at the
        first uncached token.  Blocks/slots are tracked as *reservations*
        against the live allocator (matching mutates it mid-round).

        Overload policy (docs/SERVING.md "Surviving overload"): expired
        deadlines are reaped first; candidates are ordered by *aged*
        effective priority within each class (decode before prefill —
        TPOT never queues behind prompt work); each prefill takes at
        most ``prefill_chunk`` tokens per step so a long prompt
        interleaves instead of head-of-line-blocking; and when the pool
        or slot table starves a candidate, a strictly-lower-priority
        running victim is preempted-by-eviction (``_preempt``) to make
        room.  With the default config every knob is inert and this is
        exactly the legacy FIFO SplitFuse packer."""
        budget = self.icfg.token_budget
        bs = self.icfg.kv_block_size
        ocfg = self.ocfg
        now = time.perf_counter()
        self._sched_drafts = {}
        self._reap_deadlines(now)
        if self._backoff_rounds > 0:
            # retry backoff after a transient step failure: admit
            # nothing for a bounded, step-counted number of rounds
            self._backoff_rounds -= 1
            return []
        # bisection quarantine: while probe groups are queued, ONLY the
        # head group's requests are schedulable — each probe step either
        # clears its group (success) or bisects it further (failure),
        # so the poison request is isolated in O(log batch) steps.
        # Groups whose requests all left the engine (cancel/fail/flush)
        # are pruned or the quarantine would wedge the scheduler.
        probe_allowed = None
        while self._probe_groups:
            head = [u for u in self._probe_groups[0]
                    if self._pending.get(u) or u in self.state.seqs]
            if head:
                probe_allowed = set(head)
                break
            self._probe_groups.pop(0)
        # blocks/slots promised to earlier admits this round but only
        # allocated for real in build_batch
        reserved_blocks = 0
        reserved_slots = 0
        prefix_on = self.state.prefix_cache
        sched: List[tuple] = []
        sched_uids: set = set()
        preempts_left = (ocfg.max_preemptions_per_step
                         if ocfg.preemption else 0)

        def admit(uid, toks) -> str:
            """"ok" (tokens or a cache match landed), "starved" (the
            block pool or slot table blocked it — a preemption could
            help), or "skip" (nothing a preemption can fix)."""
            nonlocal budget, reserved_blocks, reserved_slots
            seq = self.state.seqs.get(uid)
            ctx_rem = self.state.context_remaining(uid)
            if ctx_rem <= 0:
                self._ctx_exhausted.add(uid)
                return "skip"
            needs_slot = uid not in self.state._slots
            if needs_slot and \
                    len(self.state._free_slots) - reserved_slots <= 0:
                return "starved"
            new_prompt = seq is None
            prompt_len = len(toks) if new_prompt else 0
            cached = 0
            if new_prompt and prefix_on and toks[0] != FEEDBACK_TOKEN:
                if self.state.restaging(uid):
                    # a tiered chain is restaging for this request —
                    # defer (keep it queued, schedule nothing): the
                    # pre-dispatch drain re-indexes the chain and the
                    # next round's match covers it, instead of
                    # re-prefilling content already in flight
                    return "ok"
                # the match may revive cached-free blocks / take a COW
                # copy ONLY from the headroom not already reserved by
                # earlier admits this round
                with self.tracer.span("prefix_match", track="schedule",
                                      uid=uid):
                    cached = self.state.match_prefix(
                        uid, toks,
                        max_pool_take=self.state.allocator.free_blocks
                        - reserved_blocks)
                if not cached and self.state.restaging(uid):
                    return "ok"       # the match itself began a restage
                if cached:
                    del toks[:cached]
                    seq = self.state.seqs[uid]
                    needs_slot = False     # match_prefix claimed the slot
                    ctx_rem = self.state.context_remaining(uid)
            draft: List[int] = []
            if (self._spec is not None and seq is not None
                    and len(toks) == 1 and toks[0] >= 0
                    and not seq.draft_len):
                # decoding row with a concrete fed token: mine a draft
                # window from the request's own history.  Drafted tokens
                # are REAL budget/block consumers (the window writes KV
                # like a chunked prefill), so it is capped by the step's
                # leftover budget and context headroom alongside
                # spec_max_draft — drafts compete with prefill chunks
                # for the same fixed SplitFuse budget
                limit = min(self._n_verify - 1, budget - 1, ctx_rem - 1)
                if limit > 0:
                    draft = self._spec.propose(uid, toks[0], limit)
            n = min(len(toks), budget, ctx_rem)
            if len(toks) > 1 and ocfg.prefill_chunk is not None:
                # chunked prefill: a prompt takes at most one chunk of
                # this step's budget; the remainder waits its turn while
                # other prefills (and every decode) share the step
                n = min(n, ocfg.prefill_chunk)
            nw = n + len(draft)       # scheduled window incl. drafts
            avail = self.state.allocator.free_blocks - reserved_blocks
            need = 0
            while nw > 0:
                seen = seq.seen_tokens if seq else 0
                have = len(seq.blocks) if seq else 0
                need = max(0, -(-(seen + nw) // bs) - have)
                if need <= avail:
                    break
                nw //= 2
            if nw <= 0:
                if not cached:
                    return "starved"
                draft, n = [], 0
            elif nw <= n:
                draft, n = [], nw     # pool pressure ate the window
            else:
                del draft[nw - n:]
            tm = self.timings
            tm["prompt_tokens"] += prompt_len
            if cached:
                tm["cached_tokens"] += cached
                tm["prefix_hits"] += 1
            if prompt_len or cached:
                # lifecycle admission — SAME statement block as the
                # engine counters above, so per-request token sums
                # reconcile with them by construction
                self.requests.on_admitted(uid, prompt_len, cached,
                                          time.perf_counter())
            if n <= 0:
                # matched but the pool can't take the uncached remainder
                # yet: the sequence keeps its aliased blocks and waits
                return "ok"
            sched.append((uid, toks[:n] + draft))
            sched_uids.add(uid)
            if draft:
                self._sched_drafts[uid] = draft
            del toks[:n]
            budget -= n + len(draft)
            reserved_blocks += need
            if needs_slot:
                reserved_slots += 1
            return "ok"

        # decode requests (continuing sequences, single token) first,
        # then prompt chunks — one O(n) pass keyed on the entry itself
        # (the old value-membership split re-scanned the decode list for
        # every pending request: O(n^2) tuple compares under load)
        decodes: List[tuple] = []
        prefills: List[tuple] = []
        effs: Dict[int, float] = {}
        for uid, t in self._pending.items():
            if not t:
                continue
            if probe_allowed is not None and uid not in probe_allowed:
                continue
            if t[0] == FEEDBACK_TOKEN \
                    and self._fb_step.get(uid) != self._dispatch_seq:
                # deferred sample owned by an OLDER still-uncollected
                # step (possible at pipeline_depth >= 3 when the budget
                # starves a decode for a step): the jitted feedback path
                # only sees the last dispatch's sample array, so hold the
                # request until its owner's collect patches it concrete
                continue
            m = self._meta.get(uid)
            # aged priority: waiting promotes a tier per aging_ms, so a
            # low tier is delayed under load but never starved.  Equal
            # tiers keep FIFO order (aging is monotonic in arrival; the
            # sort is stable for putless direct-API entries)
            effs[uid] = effective_priority(
                m.priority if m else 0, m.t_arrival if m else now,
                now, ocfg.aging_ms) if m is not None else 0.0
            (decodes if len(t) == 1 and uid in self.state.seqs
             else prefills).append((uid, t))
        decodes.sort(key=lambda e: effs[e[0]])
        prefills.sort(key=lambda e: effs[e[0]])
        for uid, toks in decodes + prefills:
            if budget <= 0:
                break
            if self._pending.get(uid) is not toks:
                # a mid-round preemption rebound this uid's pending list
                # (the requeued chain replaced it): the stale entry here
                # holds mid-stream tokens that must NOT be admitted as a
                # fresh prompt at position 0 — the requeue waits its turn
                # next round
                continue
            verdict = admit(uid, toks)
            while verdict == "starved" and preempts_left > 0:
                # preemption compares RAW tiers (not aged): two equal
                # requests must never evict each other back and forth,
                # so at one shared tier preemption is provably inert
                m = self._meta.get(uid)
                victim = select_victim(
                    self._victim_candidates(sched_uids | {uid}),
                    better_than=m.priority if m else 0)
                if victim is None:
                    break
                self._preempt(victim)
                preempts_left -= 1
                verdict = admit(uid, toks)
        return sched

    def _victim_candidates(self, exclude: set) -> List[tuple]:
        """``(uid, raw_priority, n_blocks)`` for every live sequence
        preemption may legally evict: nothing scheduled this round or
        still in flight (its KV rows are being written), nothing whose
        KV contents the host cannot reconstruct (broken chain — decode
        bursts, or a deferred on-device token), nothing already at the
        context limit (re-queueing it would re-prefill to exhaustion)."""
        out = []
        for uid, seq in self.state.seqs.items():
            if uid in exclude or uid in self._ctx_exhausted:
                continue
            if self._inflight_sched.get(uid, 0):
                continue
            if not seq.resumable:
                continue
            p = self._pending.get(uid)
            if p and p[0] == FEEDBACK_TOKEN:
                continue
            m = self._meta.get(uid)
            out.append((uid, float(m.priority if m else 0),
                        len(seq.blocks)))
        return out

    def _evict_to_queue(self, uid: int) -> None:
        """Release ``uid``'s KV back through the refcounted allocator
        (content-hashed full blocks retire to the cached-free LRU pool,
        so with the prefix cache on the re-prefill is one aliasing
        pass, not a recompute) and re-queue its full host-known token
        stream — KV chain + still-pending concrete tokens — as a
        prompt.  NOT terminal: the lifecycle record stays open across
        the eviction, and the (uid, position)-folded sampling keys make
        the resumed output token-identical to an undisturbed run.  The
        shared mechanics of preemption-by-eviction AND failure-recovery
        re-queueing; callers count the event on the lifecycle record
        themselves (``on_preempted`` vs ``on_retried``)."""
        seq = self.state.seqs[uid]
        requeue = [int(t) for t in seq.chain]
        tail = [int(t) for t in self._pending.get(uid, [])
                if t != FEEDBACK_TOKEN]
        if seq.tokens:
            # stash generated-so-far: they become prompt tokens on the
            # re-prefill, but query() keeps reporting the full output
            self._preempt_gen[uid] = (self._preempt_gen.get(uid, [])
                                      + [int(t) for t in seq.tokens])
        self._preempting.add(uid)
        try:
            self.state.release(uid)
        finally:
            self._preempting.discard(uid)
        self._fb_step.pop(uid, None)
        self._pending[uid] = requeue + tail

    def _preempt(self, uid: int) -> None:
        """Preemption-by-eviction (docs/SERVING.md "Surviving
        overload"): evict-and-requeue, counted on the record
        (tests/test_scheduler_fuzz.py parity test)."""
        self._evict_to_queue(uid)
        self.requests.on_preempted(uid)

    def _reap_deadlines(self, now: float) -> None:
        """Terminally close every request whose ``deadline_ms`` elapsed
        — queued entries just drop; running sequences release their KV.
        A sequence with an uncollected in-flight step is deferred one
        round (its KV rows are still being written)."""
        if not self._deadline_uids:
            return
        for uid in list(self._deadline_uids):
            m = self._meta.get(uid)
            if m is None:
                self._deadline_uids.discard(uid)
                continue
            if not m.expired(now):
                continue
            if self._inflight_sched.get(uid, 0):
                continue
            self._finish(uid, "deadline_exceeded")
            self._reaped.add(uid)

    def _close_ctx_exhausted(self) -> None:
        """Terminally close context-exhausted sequences once nothing is
        in flight for them (status ``context_exhausted``) — without this
        the direct step() API leaks their open lifecycle records
        forever.  Closure reaps the uid (``_drain_reaped`` tells the
        sync generate() driver) and ``_forget`` drops it from
        ``_ctx_exhausted``, so the set never grows without bound under
        long direct-API traffic and a later reused uid is not
        permanently unschedulable.  (The pipelined driver never calls
        this: it drains the set itself and finishes those requests
        through its own flush.)"""
        for uid in list(self._ctx_exhausted):
            if uid not in self.state.seqs:
                # closed through another exit path (flush/cancel/...)
                # before this round got to it
                self._ctx_exhausted.discard(uid)
            elif not self._inflight_sched.get(uid, 0):
                self._finish(uid, "context_exhausted")
                self._reaped.add(uid)

    # ------------------------------------------------------------------
    # failure domains (inference/failures.py, docs/SERVING.md "Failure
    # domains & recovery")
    # ------------------------------------------------------------------
    def _ensure_alive(self) -> None:
        """Refuse device work on a dead engine — ``snapshot()`` still
        works; ``restore()`` the truth onto a fresh one."""
        if self._health == "dead":
            raise EngineDeadError(
                "serving engine is dead — snapshot() holds the host-side "
                "truth; InferenceEngine.restore() it onto a fresh engine")

    def _note_step_success(self, uids) -> None:
        """One completed device step: reset the failure-escalation
        counters, clear suspicion from every sequence it carried, and
        exonerate exactly the COVERED part of the head bisection probe
        group — a clean step carrying only half the group (budget /
        chunking split it) must not acquit the unprobed other half."""
        self._consec_failures = 0
        self._consec_timeouts = 0
        for uid in uids:
            self._strikes.pop(uid, None)
        if self._probe_groups:
            covered = set(self._probe_groups[0]) & set(uids)
            if covered:
                rest = [u for u in self._probe_groups[0]
                        if u not in covered]
                if rest:
                    self._probe_groups[0] = rest
                else:
                    self._probe_groups.pop(0)

    def _handle_step_failure(self, exc: BaseException, uids,
                             phase: str, registered=()) -> None:
        """Recover from one failed device dispatch/readback: classify
        the exception at the ONE seam (`classify_failure`) and act on
        the verdict so the failure degrades to request-level outcomes:

        * ``retry`` — transient: every affected sequence is released
          and re-queued (the chain re-prefills token-identically, an
          aliasing pass when the prefix cache holds its blocks) and the
          scheduler backs off a bounded, step-counted number of rounds.
        * ``poison`` — deterministic for this batch: same re-queue,
          plus the batch bisects into probe groups the scheduler runs
          in isolation; a singleton failing batch is proof and closes
          that request terminally with status ``failed``.
        * ``fatal`` — the backend is gone: the engine is marked dead
          and :class:`EngineDeadError` raised; ``snapshot()`` +
          ``restore()`` warm-restart the open work elsewhere.

        Exceptions the classifier does not recognize (host programming
        errors) re-raise untouched.  A sequence whose stream the host
        cannot replay (broken chain — device-side tokens lost with the
        failed step) closes as ``failed`` regardless of verdict."""
        if isinstance(exc, DispatchTimeoutError):
            self._consec_timeouts += 1
            if self.failures.watchdog.abandoned \
                    >= self.fcfg.max_abandoned_workers:
                # consecutive-expiry escalation resets on every clean
                # step, so an INTERMITTENTLY hanging device could
                # strand workers forever — the lifetime cap declares
                # it dead first
                self._consec_timeouts = max(self._consec_timeouts,
                                            self.fcfg.fatal_timeouts)
        verdict = classify_failure(
            exc, attempt=self._consec_failures,
            consecutive_timeouts=self._consec_timeouts, cfg=self.fcfg)
        if verdict is None:
            raise exc
        # the FIRST failure of a window flips health() to degraded —
        # the transition (not every failure) is a flight-dump trigger
        fresh_degrade = self._steps_done - self._last_failure_step \
            > self.fcfg.health_window_steps
        self._consec_failures += 1
        self._last_failure_step = self._steps_done
        logger.warning(
            f"serving step failure at {phase} "
            f"({type(exc).__name__}: "
            f"{(str(exc).splitlines() or [''])[0][:120]}) -> {verdict}")
        # black-box breadcrumb (telemetry/flight.py): verdicts survive
        # in the ring even when no dump is configured, so a later
        # debug_dump() still carries the failure history
        self.flight.note(
            "step_failure", verdict=verdict, phase=phase,
            exc=type(exc).__name__, step=self._steps_done,
            uids=[int(u) for u in uids])
        if self._cap is not None and self._cap.active:
            # a capture that witnessed the failure is worth more
            # finished than abandoned — close it with what it has
            fin = self._cap.finish_now()
            if fin is not None:
                self._finish_capture(fin)
        if verdict == FATAL_ENGINE:
            self._health = "dead"
            self._health_gauge.set(3)
            self.flight.note("engine_dead", phase=phase,
                             exc=type(exc).__name__,
                             step=self._steps_done)
            self._flight_autodump("engine_dead")
            raise EngineDeadError(
                f"serving backend dead after {type(exc).__name__} at "
                f"{phase}; snapshot() holds the host-side truth — "
                "restore onto a fresh engine") from exc
        tm = self.timings
        tm["step_retries"] += 1
        affected = [int(u) for u in uids]
        # an INJECTED fault (crash or synthetic timeout) raises before
        # the guarded call runs, so the cache buffer is untouched.  A
        # real device error — and a REAL watchdog expiry, whose
        # abandoned call already consumed the donated cache operand —
        # may have invalidated it: conservatively re-queue EVERY live
        # sequence and rebuild a zero pool (chains re-prefill the
        # truth; the prefix index must drop with the content it hashed)
        kv_lost = not isinstance(exc, (InjectedFault,
                                       InjectedTimeout)) \
            and self._donate_kv()
        if kv_lost:
            affected = list(dict.fromkeys(list(self.state.seqs)
                                          + affected))
        singleton = verdict == POISON_STEP and len(affected) == 1
        requeued: List[int] = []
        # recovery below may register post-rollback blocks into the
        # LIVE ledger (resolve_draft); those writes rode the failed
        # step, so they are withdrawn alongside ``registered`` — but a
        # NEWER in-flight step's ledger entries (depth-2 collect
        # failure) are its own and must survive
        pre_recovery = len(self.state.round_registered)
        for uid in affected:
            self._strikes[uid] = self._strikes.get(uid, 0) + 1
            seq = self.state.seqs.get(uid)
            if seq is not None and seq.draft_len:
                # drafts in the failed window were never verified:
                # reject them all before judging the chain
                self.state.resolve_draft(uid, 0)
                seq = self.state.seqs.get(uid)
            poison = singleton \
                or self._strikes[uid] >= self.fcfg.poison_strikes
            # a sequence with ANOTHER dispatched-but-uncollected step
            # (depth>=2 chunked prefill spanning two in-flight steps)
            # cannot be re-queued: the surviving step would emit from a
            # context the re-queue is about to regenerate (duplicate /
            # garbage tokens).  Terminal is the one honest outcome —
            # same conservatism as a broken chain.  (The failed step
            # itself is not counted: dispatch failures never
            # incremented it, collect failures already decremented.)
            inflight_elsewhere = self._inflight_sched.get(uid, 0) > 0
            if poison or inflight_elsewhere \
                    or (seq is not None and not seq.resumable):
                tm["requests_failed"] += 1
                self._finish(uid, "failed")
                self._reaped.add(uid)
            else:
                if seq is not None:
                    self._evict_to_queue(uid)
                self.requests.on_retried(uid)
                requeued.append(uid)
        # the failed step's KV writes never (reliably) happened: every
        # prefix-index registration that step made promises content the
        # pool does not hold — withdraw exactly those entries (plus any
        # the recovery itself just appended), or a later match would
        # alias never-written blocks
        self.state.unregister_blocks(
            list(registered)
            + list(self.state.round_registered[pre_recovery:]))
        if kv_lost:
            kv = self._kv_zeros()
            if getattr(self, "_kv_on_host", False):
                kv = jax.device_put(kv, jax.memory.Space.Host)
            self.state.kv = kv
            self.state.reset_prefix_cache()
            self._last_toks = None
        # a failed probe step retires its group — but NEVER loses it:
        # its bisected split (poison) or the group itself (transient
        # failure mid-quarantine) takes its place, so isolation always
        # completes and the poison cannot slip back into the pool
        hit_probe = bool(self._probe_groups) \
            and bool(set(self._probe_groups[0]) & set(affected))
        if hit_probe:
            self._probe_groups.pop(0)
        if verdict == POISON_STEP and len(requeued) > 1:
            self._probe_groups = bisect_groups(requeued) \
                + self._probe_groups
        else:
            if requeued and (hit_probe or verdict == POISON_STEP):
                # a transient keeps the same probe group for retry; a
                # poison remnant (siblings already failed) probes alone
                # so its next failure is singleton proof
                self._probe_groups = [list(requeued)] \
                    + self._probe_groups
            # transient: step-counted exponential backoff (determinis-
            # tic — the chaos replay's op sequence stays machine-
            # independent), bounded so the loop always makes progress
            self._backoff_rounds = min(
                self.fcfg.max_backoff_rounds,
                1 << min(self._consec_failures - 1, 6))
        # non-fatal auto-dump triggers (docs/OBSERVABILITY.md): a
        # watchdog expiry (the call was abandoned — the artifact is how
        # anyone learns what it carried) and the healthy->degraded
        # transition of a fresh failure window
        if isinstance(exc, DispatchTimeoutError):
            self._flight_autodump("watchdog_expiry")
        elif fresh_degrade:
            self._flight_autodump("health_degraded")

    def _finish_capture(self, cdir: str) -> None:
        """A capture window just completed: drop the flight dump next
        to its traces (the post-mortem half of the artifact) and leave
        a breadcrumb.  ``tools/tracemerge.py`` merges the dir into one
        Perfetto timeline."""
        import os
        self.flight.note("capture_complete", path=cdir)
        self.debug_dump(os.path.join(cdir, "flight.json"),
                        reason="capture")

    def finish_capture(self) -> Optional[str]:
        """Close any ACTIVE capture window immediately with the steps
        it has (the artifact is written; the jax profiler session and
        the force-enabled tracer are released).  The generate()
        drivers and ``drain()`` call this when their work runs out —
        a window armed for more steps than the workload will run must
        not strand the process-wide profiler session — and direct
        step()-API callers can call it themselves.  Returns the
        capture dir, or None when no window was active."""
        if self._cap is None or not self._cap.active:
            return None
        fin = self._cap.finish_now()
        if fin is not None:
            self._finish_capture(fin)
        return fin

    def _flight_autodump(self, reason: str) -> Optional[str]:
        """Write one black-box artifact into ``FailureConfig.
        flight_dir`` (no-op when unset).  Best-effort: the recorder
        itself swallows I/O failures — a post-mortem writer must never
        make a failing engine fail harder."""
        d = self.fcfg.flight_dir
        if not d:
            return None
        import os
        try:
            os.makedirs(d, exist_ok=True)
        except OSError as e:
            logger.warning("flight_dir %r unusable (%s)", d, e)
            return None
        # collision-avoid across engine GENERATIONS sharing one dir: a
        # warm-restarted engine replaying the same workload dies at the
        # same step with the same counters, and overwriting the prior
        # engine's black box would destroy the one artifact the
        # recorder exists to preserve
        n = self.flight.dumps
        while True:
            path = os.path.join(
                d, f"flight_{reason}_s{self._steps_done}_{n}.json")
            if not os.path.exists(path):
                break
            n += 1
        self.flight.note("dump", reason=reason, path=path)
        return self.flight.dump(
            path, reason, metrics=self.metrics, tracer=self.tracer,
            requests=self.requests, health=self.health(),
            steps=self._steps_done,
            extra={"device": None if self.devtel is None
                   else self.devtel.snapshot(),
                   "anomalies": self.anomaly_summary()})

    def ops_dump(self) -> Optional[str]:
        """The gateway ``POST /debug/dump`` seam: one flight-recorder
        artifact into ``FailureConfig.flight_dir`` through the same
        collision-safe writer the failure path uses.  Returns the
        written path, or None when no flight_dir is configured — a
        wire client can name neither the path nor the budget."""
        return self._flight_autodump("ops")

    def debug_dump(self, path: Optional[str] = None,
                   reason: str = "debug") -> Dict:
        """On-demand flight-recorder snapshot (docs/OBSERVABILITY.md
        "Device & compiler telemetry"): the same black-box artifact the
        failure path auto-dumps — last-N spans, full metrics snapshot,
        recent request statuses, config fingerprint, health, failure
        breadcrumbs, and the device-telemetry summary when enabled.
        Returns the dict; with ``path`` also writes it as JSON (through
        the recorder's best-effort writer — a post-mortem must never
        make a failing engine fail harder).  Valid on a DEAD engine
        (everything it reads is host truth)."""
        snap = self.flight.snapshot(
            reason, metrics=self.metrics, tracer=self.tracer,
            requests=self.requests, health=self.health(),
            steps=self._steps_done,
            extra={"device": None if self.devtel is None
                   else self.devtel.snapshot(),
                   "anomalies": self.anomaly_summary()})
        if path is not None:
            self.flight.dump(path, reason, snap=snap)
        return snap

    def health_state(self) -> str:
        """The health-ladder state ALONE — no gauge write, no memory
        poll: the cheap form a fleet router may read every step for
        its per-replica gauges.  :meth:`health` is the phase-boundary
        probe that additionally refreshes gauges and polls device
        memory."""
        state = self._health
        if state == "healthy" and self._steps_done \
                - self._last_failure_step <= self.fcfg.health_window_steps:
            state = "degraded"
        if state == "healthy" and self._anom is not None \
                and self._anom.sustained(self._steps_done):
            # sustained anomaly fires inside the window: the engine is
            # not failing, but it is not behaving either — the router
            # should prefer another replica while this one is probed
            state = "degraded"
        return state

    def health(self) -> Dict:
        """Engine health for the router's liveness probe
        (docs/OBSERVABILITY.md): ``state`` walks
        ``healthy -> degraded -> (draining | dead)`` — ``degraded``
        while the most recent step failure is within
        ``FailureConfig.health_window_steps`` dispatched steps
        (failure *rates* from the metrics registry drive it, not a
        latched flag), ``draining``/``dead`` sticky.  Also exported as
        the ``serving_health_state`` gauge (0/1/2/3) through the
        Prometheus exposition."""
        state = self.health_state()
        self._health_gauge.set(
            {"healthy": 0, "degraded": 1, "draining": 2,
             "dead": 3}[state])
        if self.devtel is not None:
            # a health check is a phase boundary: refresh the memory
            # gauges here (one host call per device, never per step)
            self.devtel.poll_memory()
        tm = self.timings
        return {
            "state": state,
            "steps": int(tm["steps"]),
            "step_retries": int(tm["step_retries"]),
            "requests_failed": int(tm["requests_failed"]),
            "consecutive_failures": self._consec_failures,
            "consecutive_timeouts": self._consec_timeouts,
            "dispatch_deadline_ms": self.failures.deadline_ms(),
            "probing": bool(self._probe_groups),
            "backoff_rounds": self._backoff_rounds,
            "live": len(self.state.seqs),
            "queued": sum(1 for t in self._pending.values() if t),
            # streaming-detector view (0 / [] while anomaly is off)
            "anomalies": 0 if self._anom is None else self._anom.total(),
            "captures": len(self.capture_dirs),
        }

    # every snapshot this engine emits or restores carries this schema
    # version.  v2 (PR 13): per-request extraction (`snapshot_requests`)
    # and merge-restore (`load_snapshot(..., merge=True)`) — the
    # record shape is unchanged, but v1 consumers assumed a snapshot
    # was always the WHOLE engine restored onto a FRESH one, so
    # partial/merging payloads must be rejected by v1 engines (and
    # vice versa) rather than silently half-applied
    SNAPSHOT_VERSION = 2

    def _open_uids(self) -> List[int]:
        """Every uid with open work on this engine (admitted metadata,
        queued tokens, or a live sequence), in stable admission-ish
        order — the domain of :meth:`snapshot` / :meth:`snapshot_requests`."""
        return list(dict.fromkeys(list(self._meta) + list(self._pending)
                                  + list(self.state.seqs)))

    def _request_record(self, uid: int, now: float) -> Dict:
        """One open request's restore()-compatible record: the
        replayable token stream (KV chain + still-pending tokens),
        generated output so far, and admission metadata — the unit of
        currency snapshots, drains, and fleet migrations all move.  A
        stream the host cannot replay (broken chain — decode bursts,
        an in-flight feedback marker) is recorded ``exact: False``."""
        seq = self.state.seqs.get(uid)
        pend = [int(t) for t in self._pending.get(uid, [])]
        gen = list(self._preempt_gen.get(uid, []))
        exact = FEEDBACK_TOKEN not in pend
        stream = pend
        if seq is not None:
            exact = exact and seq.resumable
            stream = [int(t) for t in seq.chain] \
                + [t for t in pend if t != FEEDBACK_TOKEN]
            gen += [int(t) for t in seq.tokens]
        m = self._meta.get(uid)
        remaining = None
        if m is not None and m.deadline_ms is not None:
            remaining = max(
                0.0, m.deadline_ms - (now - m.t_arrival) * 1e3)
        rec = self.requests.open.get(uid)
        return {
            "uid": int(uid),
            "tokens": stream if exact else None,
            "generated": gen,
            "priority": int(m.priority) if m else 0,
            "deadline_ms": remaining,
            "preemptions": rec.preemptions if rec else 0,
            "retries": rec.retries if rec else 0,
            "slo": rec.slo_class if rec else None,
            "exact": exact,
        }

    def snapshot(self) -> Dict:
        """Serialize the engine's host-side truth — every open
        request's replayable token stream (KV chain + still-pending
        tokens), its generated output so far, and its admission
        metadata — plus the counters and the prefix-cache index keys
        (the content hashes: a router's cache-affinity signal, NOT
        revivable KV).  Device state is deliberately absent: KV blocks
        re-prefill from the chains on :meth:`restore` (an aliasing pass
        for streams whose prefixes re-register in the new engine's
        cache, plain prefill otherwise), and the (uid, position)-folded
        sampling keys make the resumed outputs token-identical to an
        uninterrupted run — greedy and seeded (reuse the same explicit
        base key), prefix cache on or off.

        Valid on a DEAD engine (host truth survives the backend) —
        that is the warm-restart story: catch
        :class:`EngineDeadError`, ``snapshot()``, ``restore()``.  Take
        it at a step boundary (no dispatched-but-uncollected step); a
        sequence whose stream the host cannot replay (broken chain —
        decode bursts, an in-flight feedback marker) is recorded
        ``exact: False`` and closed ``failed`` at restore."""
        from .. import __version__
        now = time.perf_counter()
        reqs = [self._request_record(uid, now)
                for uid in self._open_uids()]
        return {
            "version": self.SNAPSHOT_VERSION,
            "engine_version": __version__,
            "health": self.health()["state"],
            "counters": {k: self.timings[k]
                         for k in ("steps", "prompt_tokens",
                                   "cached_tokens", "generated_tokens",
                                   "step_retries", "requests_failed")},
            "requests": reqs,
            # content digests of the resident prefix-cache index: the
            # cache-affinity routing key (ROADMAP item 5), not KV
            "prefix_index": sorted(self.state.prefix_digests()),
        }

    def snapshot_requests(self, uids: Sequence[int]) -> Dict:
        """Extract restore()-compatible records for a SUBSET of this
        engine's open requests — the fleet router's migration payload
        (move some open work to another replica without touching the
        rest).  Same schema/version as :meth:`snapshot`, marked
        ``"partial": True``; uids with no open work here are skipped
        (the caller may be racing a terminal close).  Extraction does
        NOT close the requests — :meth:`migrate_out` is the
        extract-and-close composition."""
        from .. import __version__
        now = time.perf_counter()
        known = set(self._open_uids())
        wanted = dict.fromkeys(int(u) for u in uids)   # dedup, ordered
        return {
            "version": self.SNAPSHOT_VERSION,
            "engine_version": __version__,
            "partial": True,
            "requests": [self._request_record(u, now)
                         for u in wanted if u in known],
        }

    def migrate_out(self, uids: Sequence[int]) -> Dict:
        """Live-migration extraction (docs/SERVING.md "Fleet: routing,
        failover, migration"): :meth:`snapshot_requests` the given open
        requests, then terminally close them on THIS engine with
        status ``migrated`` (their KV releases back through the
        refcounted allocator; the lifecycle record closes — the
        request lives on wherever the returned records are
        ``load_snapshot(..., merge=True)``-ed).  Requests a move would
        DESTROY are skipped, not extracted: a dispatched-but-
        uncollected step (its KV rows are still being written) and a
        non-replayable stream (broken chain — the destination could
        only close it ``failed``, killing a healthy request); both
        stay in place, retry at a later step boundary."""
        eligible = [int(u) for u in uids
                    if not self._inflight_sched.get(int(u), 0)]
        part = self.snapshot_requests(eligible)
        part["requests"] = [rec for rec in part["requests"]
                            if rec["exact"] and rec["tokens"]]
        for rec in part["requests"]:
            self._finish(rec["uid"], "migrated")
            self._reaped.add(rec["uid"])
        return part

    def handoff_out(self, uids: Sequence[int]) -> Dict:
        """Prefill→decode handoff extraction (docs/SERVING.md
        "Disaggregated pools & elasticity"): the same extract-and-close
        composition as :meth:`migrate_out`, with two differences.  The
        close status is ``handed_off`` — terminal here, a routing hop
        at the fleet level — and BEFORE closing, each request's
        still-indexed chain blocks are staged into the KV tier
        (``stage_chain_demotes`` + an immediate demote drain, reading
        the device while the blocks are guaranteed unrewritten), so the
        router's :meth:`export_tier_chain` fetch on the decode side
        ships the prefilled KV instead of re-prefilling it.  The same
        destroy-avoidance rules apply: dispatched-but-uncollected and
        non-replayable requests stay in place for a later boundary."""
        eligible = [int(u) for u in uids
                    if not self._inflight_sched.get(int(u), 0)]
        part = self.snapshot_requests(eligible)
        part["requests"] = [rec for rec in part["requests"]
                            if rec["exact"] and rec["tokens"]]
        staged = 0
        for rec in part["requests"]:
            staged += self.state.stage_chain_demotes(rec["uid"])
            self._finish(rec["uid"], "handed_off")
            self._reaped.add(rec["uid"])
        if staged:
            self._drain_tier_demote()
        return part

    def export_tier_chain(self, digests: Sequence[bytes]) -> Optional[Dict]:
        """Extract the leading contiguous run of ``digests`` this
        engine's KV tier can serve, as a snapshot-v2-shaped partial
        payload (``tier_blocks`` records ride the same fabric migration
        records do — ``load_snapshot(merge=True)`` on the destination).
        Non-destructive: this replica keeps its tier entries.  Returns
        None when the tier is off or the first digest misses; every
        record was checksum-verified on the way out, so a corrupted
        spill file truncates the run instead of exporting bad bytes."""
        tier = self.state.tier
        if tier is None:
            return None
        blocks = []
        for h in digests:
            rec = tier.export(bytes(h))
            if rec is None:
                break          # only a leading run is restageable
            blocks.append(rec)
        if not blocks:
            return None
        return {"version": self.SNAPSHOT_VERSION, "partial": True,
                "requests": [], "tier_blocks": blocks}

    def load_snapshot(self, snap: Dict, merge: bool = False) -> None:
        """Re-open a snapshot's requests on THIS engine (the restore
        half of the warm restart — :meth:`restore` wraps construction +
        this).  Admission bounds are bypassed: restored work was
        already admitted once; shedding it again would double-charge
        the client.  Streams re-enter as prompts (the scheduler
        re-prefills them, through the prefix cache when their blocks
        re-register), prior generated tokens keep ``query()`` output
        complete, and inexact records (device-side tokens lost with
        the old engine) close terminally as ``failed``.

        By default the engine must be FRESH (no open work) — the warm-
        restart contract.  ``merge=True`` is the fleet-migration mode:
        records join a replica that is already serving (a uid already
        open here raises — one request must never run twice)."""
        if snap.get("version") != self.SNAPSHOT_VERSION:
            raise ValueError(
                f"snapshot version {snap.get('version')!r}: this engine "
                f"restores version {self.SNAPSHOT_VERSION}")
        open_now = set(self._open_uids()) | set(self.requests.open)
        if not merge and open_now:
            raise ValueError(
                f"load_snapshot onto an engine with {len(open_now)} open "
                "request(s): restore assumes a fresh engine — pass "
                "merge=True to migrate records into live traffic")
        # validate the WHOLE payload before applying any record: a
        # rejection must leave the engine untouched, or the caller's
        # retry-on-another-replica re-places the half-applied records
        # and double-runs them — the exact hazard this guard exists for
        incoming = [int(rec["uid"]) for rec in snap["requests"]]
        seen: set = set()
        dupes: set = set()
        for u in incoming:
            (dupes if u in seen else seen).add(u)
        if dupes:
            raise ValueError(
                f"snapshot payload repeats uid(s) {sorted(dupes)}: a "
                "request must never be applied twice")
        if merge:
            clash = open_now & set(incoming)
            if clash:
                raise ValueError(
                    f"load_snapshot(merge=True): uid(s) {sorted(clash)} "
                    "already open on this engine — a request must never "
                    "run on two replicas at once")
        # fetched KV tier blocks (docs/KV_TIERING.md): part of the same
        # whole-payload-first validation — every record must recompute
        # its chain digest from (parent, tokens) AND match its payload
        # checksum before anything is applied.  A forged or corrupted
        # block rejects the payload; it can never reach the device cache
        tier_blocks = snap.get("tier_blocks") or []
        if tier_blocks:
            from .ragged.tier import KVBlockTier
            bad = [i for i, rec in enumerate(tier_blocks)
                   if not KVBlockTier.verify_record(rec)]
            if bad:
                raise ValueError(
                    f"snapshot tier_blocks {bad} failed digest/checksum "
                    "verification: refusing the whole payload")
        now = time.perf_counter()
        tm = self.timings
        for rec in snap["requests"]:
            uid = int(rec["uid"])
            # the class tag travels with the record, so a migrated /
            # handed-off / restored request is still charged to its SLO
            # budget on the replica that finishes it
            self.requests.on_arrival(uid, now,
                                     slo_class=rec.get("slo"))
            if not rec.get("exact", True) or not rec.get("tokens"):
                # device-side tokens died with the old engine: the one
                # honest outcome is terminal (and reaped, so drivers
                # drop the uid instead of waiting on it forever)
                tm["requests_failed"] += 1
                self.requests.on_finish(uid, status="failed")
                self._reaped.add(uid)
                continue
            self._meta[uid] = RequestMeta(
                priority=int(rec.get("priority", 0)),
                deadline_ms=rec.get("deadline_ms"),
                t_arrival=now)
            if rec.get("deadline_ms") is not None:
                self._deadline_uids.add(uid)
            toks = [int(t) for t in rec["tokens"]]
            self._pending[uid] = toks
            if rec.get("generated"):
                self._preempt_gen[uid] = [int(t)
                                          for t in rec["generated"]]
            open_rec = self.requests.open.get(uid)
            if open_rec is not None:
                open_rec.preemptions = int(rec.get("preemptions", 0))
                open_rec.retries = int(rec.get("retries", 0))
            if self._spec is not None:
                self._spec.observe(uid, toks)
        if tier_blocks:
            tier = self.state.tier
            if tier is None:
                logger.warning(
                    "load_snapshot: %d tier_blocks arrived but kv_tier "
                    "is off on this engine — dropping them (the request "
                    "records were applied normally)", len(tier_blocks))
            else:
                for rec in tier_blocks:
                    ev = tier.insert_record(rec)
                    tm["kv_tier_remote_blocks"] += ev["stored"]
                    tm["kv_tier_spills"] += ev["spilled"]
                    tm["kv_tier_spilled_bytes"] += ev["spilled_bytes"]
                    tm["kv_tier_drops"] += ev["dropped"]

    @classmethod
    def restore(cls, model: Model, snap: Dict,
                config: InferenceConfig = None,
                topology: Optional[MeshTopology] = None,
                quant_tree=None) -> "InferenceEngine":
        """Warm restart: build a fresh engine from weights + a
        :meth:`snapshot` and re-open every captured request on it.
        The chaos harness's elastic-restart loop
        (tools/loadgen.py) is the canonical caller::

            try:
                out = eng.step(...)
            except EngineDeadError:
                eng = InferenceEngine.restore(model, eng.snapshot(),
                                              eng.icfg)
        """
        eng = cls(model, config, topology, quant_tree)
        eng.load_snapshot(snap)
        return eng

    def drain(self, deadline_ms: Optional[float] = None,
              sampling: SamplingParams = SamplingParams(),
              rng: Optional[jax.Array] = None) -> Dict:
        """Graceful drain — the router's replica-restart contract
        (ROADMAP item 5): stop admitting NEW requests (``put`` sheds
        them; continuations still land), run the backlog down until no
        pending work remains or ``deadline_ms`` elapses (always
        step-bounded: a wedged pool cannot hang the drain), then emit
        the final :meth:`snapshot` and terminally close everything
        still open as ``shed`` — exactly-one-terminal-status holds
        through a drain like every other exit path.  The snapshot is
        the hand-off: restore it onto the replacement replica and the
        undone work resumes token-identically.

        The returned snapshot additionally reports the drain's outcome
        split: ``shed_uids`` — requests closed ``shed`` by the drain
        (their records are in ``requests``; the router re-places
        exactly these on surviving replicas) — and ``completed_uids``
        — requests that reached some OTHER terminal status during the
        drain (deadline expiry, context exhaustion, a failure close):
        already settled, so re-placing them would double-run them."""
        self._draining = True
        open_at_start = set(self._open_uids()) | set(self.requests.open)
        if self._health != "dead":
            self._health = "draining"
            self._health_gauge.set(2)
        t0 = time.perf_counter()
        pending_tokens = sum(len(t) for t in self._pending.values())
        # generous progress bound: every pending token plus headroom
        # for chunking/backoff rounds — the drain NEVER spins forever
        step_budget = 4 * (pending_tokens // max(self.icfg.token_budget,
                                                 1) + len(self._pending)) \
            + 4 * self.fcfg.max_backoff_rounds + 16
        empty_rounds = 0
        while any(self._pending.values()) and step_budget > 0:
            if deadline_ms is not None \
                    and (time.perf_counter() - t0) * 1e3 >= deadline_ms:
                break
            step_budget -= 1
            try:
                out = self.step(rng=rng, sampling=sampling)
            except EngineDeadError:
                break
            # backoff rounds return {} with work still pending; more
            # than the backoff cap of consecutive empties means the
            # remaining work is unschedulable — shed it via the close
            empty_rounds = 0 if out else empty_rounds + 1
            if empty_rounds > self.fcfg.max_backoff_rounds + 2:
                break
        # a drain ends this engine's serving life: an active capture
        # window closes with what it has (never strands the session)
        self.finish_capture()
        snap = self.snapshot()
        shed: List[int] = []
        for uid in self._open_uids():
            self._finish(uid, "shed")
            self._reaped.add(uid)
            shed.append(int(uid))
        shed_set = set(shed)
        snap["shed_uids"] = sorted(shed_set)
        snap["completed_uids"] = sorted(
            int(u) for u in open_at_start if u not in shed_set)
        return snap

    def step(self, rng: Optional[jax.Array] = None,
             sampling: SamplingParams = SamplingParams()
             ) -> Dict[int, int]:  # tpulint: serving-loop
        """Run one engine step; returns {uid: next_token} for sequences
        whose last pending token was consumed (i.e. ready to sample).
        Strict-sync form of the pipeline: dispatch, then read straight
        back (generate() at ``pipeline_depth>=2`` interleaves these).

        With ``spec_decode`` on, a step may emit SEVERAL tokens for a
        sequence (an accepted verify window); the returned token is the
        LAST one — exactly the right continuation to feed back via
        ``put`` — and the full stream accumulates on the sequence
        (``query()["generated"]``).  The generate() drivers consume the
        full per-step lists internally."""
        st = self._dispatch(sampling, rng)
        if st is None:
            return {}
        return {u: ts[-1] for u, ts in self._collect(st).items()}

    @staticmethod
    def _rng_drawer(rng: Optional[jax.Array]):
        """None, or a zero-arg callable yielding the BASE sampling key
        for each dispatched step.  An explicit caller key is reused
        verbatim for every step of the call: per-token randomness comes
        from the (uid, position) fold inside the jitted step
        (``sampler.row_keys``), which makes seeded outputs
        schedule-invariant — pipeline depth, prompt chunking, decode
        bursts, and prefix-cache hits all change the step stream, but
        never a token's folded key."""
        if rng is None:
            return None
        return lambda: rng

    def _dispatch(self, sampling: SamplingParams,
                  rng=None) -> Optional[_InFlight]:  # tpulint: serving-loop
        """Schedule, stage, and launch one serving step WITHOUT reading
        the sampled tokens back; returns the in-flight record (tokens
        still on device) or None when nothing is schedulable.  ``rng``:
        an explicit PRNG key, a zero-arg callable invoked only once a
        step is known to launch, or None (engine-internal key stream
        when the sampler needs one)."""
        self._ensure_alive()
        t0 = time.perf_counter()
        sched = self._schedule()
        self._close_ctx_exhausted()
        if not sched:
            # an idle round still moves tier work: evictions queued by
            # the schedule pass demote, and in-flight restages resolve
            # (a deferred request is waiting on exactly this)
            self._drain_tier_demote()
            self._drain_tier_restage(dispatching=False)
            return None
        cap = self._cap
        if cap is not None and cap.armed:
            # the armed deep-capture window opens only once a step is
            # KNOWN to launch (an idle/backoff round must not start a
            # session nothing will count down), before staging — the
            # one profiler seam (tpulint: profiler-capture)
            cap.begin(sid=self._dispatch_seq + 1,
                      step=self._steps_done)
        # context bucket: the compiled block bound covers every scheduled
        # sequence's post-step context, rounded to a power of two so a
        # growing context mints O(log) programs, not one per block
        bs_blk = self.icfg.kv_block_size
        need = 1
        for uid, toks in sched:
            seq = self.state.seqs.get(uid)
            seen = seq.seen_tokens if seq else 0
            need = max(need, -(-(seen + len(toks)) // bs_blk))
        mbs = 1
        while mbs < need:
            mbs *= 2
        mbs = min(mbs, self.max_blocks_per_seq)
        key = (mbs, sampling.sampler_key)
        step_fn = self._pstep_fns.pop(key, None)
        if step_fn is None:
            if len(self._pstep_fns) >= 16:    # bound retained executables
                evicted = next(iter(self._pstep_fns))
                self._pstep_fns.pop(evicted)
                # a rebuilt executable recompiles: its next call is
                # cold again or the watchdog would time the compile
                self._warm_keys.discard(("p", evicted))
            step_fn = self._build_pstep(mbs, sampling)
            self._note_compile("p", key)
        self._pstep_fns[key] = step_fn    # reinsert: LRU, not FIFO
        cold = ("p", key) not in self._warm_keys
        t1 = time.perf_counter()
        batch = self._stage(
            self.state.build_batch(
                sched, self.icfg.token_budget, stager=self._stager,
                draft_lens={u: len(d)
                            for u, d in self._sched_drafts.items()},
                n_verify=self._n_verify))
        # device-order bracket: demote reads of just-evicted blocks must
        # enqueue before ANY write that may reuse them (COW copies,
        # restage uploads, the step itself) — stream ordering then makes
        # the read see the old content
        self._drain_tier_demote()
        self._drain_cow()       # COW copies land before the step's write
        self._drain_tier_restage(dispatching=True)
        t2 = time.perf_counter()
        if callable(rng):
            rng = rng()
        if rng is None and sampling.needs_rng:
            self._rng, rng = jax.random.split(self._rng)
        if rng is None:
            rng = self._zero_key          # greedy: the sampler ignores it
        prev = self._last_toks if self._last_toks is not None \
            else self._zero_toks
        uids = tuple(uid for uid, _ in sched)
        try:
            try:
                # the one deadline-guarded dispatch seam: the watchdog
                # (and the chaos harness's fault injector) wrap exactly
                # this call — see inference/failures.py
                toks, self.state.kv = self.failures.run(
                    lambda: step_fn(self.params, self._quant,
                                    self.state.kv, batch, prev, rng),
                    uids=uids, cold=cold)
            except jax.errors.JaxRuntimeError:
                # degrade to an HBM cache ONLY on the first-ever step
                # (the backend compiled but cannot execute in-program
                # host transfers); a later-step error must propagate to
                # the failure classifier below — zeroing a live cache
                # here would silently corrupt every open sequence
                if not getattr(self, "_kv_on_host", False) \
                        or self._steps_done > 0:
                    raise
                logger.warning("kv_offload: backend cannot execute host "
                               "transfers; falling back to HBM KV")
                self._kv_on_host = False
                # the failed call donated the cache; at step 0 it is all
                # zeros — recreate it
                self.state.kv = self.state.cfg.kv_zeros()
                self._pstep_fns.clear()
                # a backend-capability fallback is a LEGITIMATE rebuild
                # of every serving program (like refresh_params): the
                # dropped programs are cold again and their keys leave
                # the retrace ledger — this must not count (or warn) as
                # cache churn
                self._warm_keys = {k for k in self._warm_keys
                                   if k[0] != "p"}
                self._compiled_ever = {k for k in self._compiled_ever
                                       if k[0] != "p"}
                step_fn = self._pstep_fns[key] = self._build_pstep(
                    mbs, sampling)
                self._note_compile("p", key)
                toks, self.state.kv = step_fn(
                    self.params, self._quant, self.state.kv, batch, prev,
                    rng)
        except Exception as e:
            # every failure on the dispatch path funnels through the
            # classifier seam (tpulint's serving-except rule holds the
            # loop to this); the live ledger IS this step's build
            self._handle_step_failure(
                e, uids, "dispatch",
                registered=tuple(self.state.round_registered))
            return None
        t3 = time.perf_counter()
        self._warm_keys.add(("p", key))
        self._steps_done += 1
        self._last_toks = toks
        tm = self.timings
        tm["schedule_ms"] += (t1 - t0) * 1e3
        tm["stage_ms"] += (t2 - t1) * 1e3
        tm["device_ms"] += (t3 - t2) * 1e3
        tm["steps"] += 1
        if self._comm_active is not None:
            self._bump_comm_counters()
        if cold:
            # first completed call of this program: its dispatch wall
            # time carried the XLA compile (the timestamps are the ones
            # above — the compile span costs no extra clock reads)
            tm["compile_ms"] += (t3 - t2) * 1e3
            if self.devtel is not None:
                # cost-analysis probe, once per program, on the warm
                # executable — args are the post-call live buffers
                # (the donated kv was rebound to the step's output)
                self.devtel.probe_program(
                    ("p",) + key, step_fn,
                    (self.params, self._quant, self.state.kv, batch,
                     prev, rng))
        if self.devtel is not None:
            self.devtel.on_dispatch(("p",) + key)
        if self._anom is not None:
            # streaming detectors fed from the timestamps/counters
            # above — no clock reads beyond the ones timings took
            self._feed_step_signals(t0, t2, t3)
        for uid, _ in sched:
            self.requests.on_prefill_start(uid, t3)
        tr = self.tracer
        if tr.enabled:
            # reuse the phase timestamps already taken for timings — one
            # track per pipeline stage (docs/OBSERVABILITY.md)
            sid = self._dispatch_seq + 1
            tr.record("schedule", t0, t1, track="schedule", sid=sid)
            tr.record("stage", t1, t2, track="stage", sid=sid)
            tr.record("dispatch", t2, t3, track="dispatch", sid=sid,
                      n_tokens=sum(len(t) for _, t in sched))
            if cold:
                tr.record("compile", t2, t3, track="dispatch", sid=sid,
                          key=repr(key))
        emit = tuple((uid, self.state.slot(uid)) for uid, _ in sched
                     if not self._pending.get(uid))
        for uid in uids:
            self._inflight_sched[uid] = self._inflight_sched.get(uid, 0) + 1
        self._dispatch_seq += 1
        return _InFlight(toks=toks, emit=emit, sid=self._dispatch_seq,
                         uids=uids,
                         drafts=tuple((u, tuple(d)) for u, d in
                                      self._sched_drafts.items()),
                         stop=sampling.stop_token,
                         registered=tuple(self.state.round_registered),
                         cold=cold)

    def _comm_step_stats(self) -> Dict[str, float]:
        """Modeled wire accounting for ONE dispatched step's decomposed
        TP collectives, derived from the compiled shapes (host
        arithmetic only): the down-projection all-reduces one
        [token_budget, d_model] partial per layer, the unembed gathers
        one [rows, vocab] logits block.  Tile counts mirror the
        compiled program's ``_resolve_tiles`` clamp, not the raw
        config knob."""
        from ..comm.overlap import _resolve_tiles, wire_bytes

        comm = self._comm_active
        n = self.topology.tp_size
        isz = jnp.dtype(self.icfg.param_dtype).itemsize
        st = {"ops_exact": 0, "ops_quant": 0, "tiles": 0,
              "bytes_exact": 0.0, "bytes_quant": 0.0}
        if comm.downproj:
            elems = self.icfg.token_budget * self.cfg.d_model
            per = wire_bytes("all_reduce", elems, isz, n, comm.quant_bits)
            L = self.cfg.num_layers
            kind = "quant" if comm.quant_bits else "exact"
            st[f"ops_{kind}"] += L
            st[f"bytes_{kind}"] += per * L
            st["tiles"] += L * _resolve_tiles(self.icfg.token_budget,
                                              comm.tiles)
        if comm.unembed:
            rows = self.icfg.max_seqs * self._n_verify
            per = wire_bytes("all_gather", rows * self.cfg.vocab_size,
                             isz, n)
            st["ops_exact"] += 1
            st["bytes_exact"] += per
            st["tiles"] += _resolve_tiles(rows, comm.tiles)
        return st

    def _bump_comm_counters(self) -> None:
        if self._comm_stats is None:
            self._comm_stats = self._comm_step_stats()
        st = self._comm_stats
        if st["ops_exact"]:
            self._c_comm_ops.inc(st["ops_exact"], kind="exact")
            self._c_comm_bytes.inc(st["bytes_exact"], kind="exact")
        if st["ops_quant"]:
            self._c_comm_ops.inc(st["ops_quant"], kind="quant")
            self._c_comm_bytes.inc(st["bytes_quant"], kind="quant")
        self._c_comm_tiles.inc(st["tiles"])

    def _drain_cow(self) -> None:  # tpulint: serving-loop
        """Execute queued copy-on-write block copies (a prefix-cache
        match that covered a whole prompt aliases its last block as a
        private copy) on device BEFORE the dispatch that appends into
        the copy.  Pure async enqueue — no host sync; a round with no
        full-cover match is a no-op."""
        copies = self.state.take_cow_copies()
        if not copies:
            return
        if self._cow_fn is None:
            def copy_block(kv, src, dst):
                return jax.tree.map(
                    lambda x: x.at[:, dst].set(x[:, src]), kv)

            # compiled once per engine (src/dst ride as traced scalars);
            # donation/placement policy shared with the step programs
            self._cow_fn = self._serving_jit(copy_block, kv_argnum=0,
                                             kv_only_output=True)
        with self.tracer.span("cow_drain", track="stage", n=len(copies)):
            for src, dst in copies:
                self.state.kv = self._cow_fn(self.state.kv, np.int32(src),
                                             np.int32(dst))

    def _drain_tier_demote(self) -> None:  # tpulint: serving-loop
        """Read each just-evicted block off the device and demote its
        payload into the host tier (tier.py owns the host-side copy and
        any NVMe spill).  Runs BEFORE every write that could reuse the
        block — the COW drain, restage uploads, the step dispatch — so
        stream ordering guarantees the read sees the old content.  A
        round with no eviction is a no-op."""
        q = self.state.take_tier_demotes()
        if not q:
            return
        tm = self.timings
        with self.tracer.span("tier_demote", track="stage", n=len(q)):
            for parent, digest, tokens, blk in q:
                payload = jax.tree.map(lambda x: x[:, blk], self.state.kv)
                ev = self.state.tier.put(parent, digest, tokens,
                                         jax.tree.leaves(payload))
                tm["kv_tier_demotions"] += ev["stored"]
                tm["kv_tier_demoted_bytes"] += ev["nbytes"]
                tm["kv_tier_spills"] += ev["spilled"]
                tm["kv_tier_spilled_bytes"] += ev["spilled_bytes"]
                tm["kv_tier_drops"] += ev["dropped"]

    def _drain_tier_restage(self,
                            dispatching: bool
                            ) -> None:  # tpulint: serving-loop
        """Resolve every queued tier->HBM restage: finish its I/O,
        verify the payload (checksum; the chain digest was verified at
        import for remote records), upload it into the reserved block
        and register the digest — or free the block and count a verify
        failure, leaving the deferred request to re-prefill.  Runs
        after the COW drain so uploads into just-evicted blocks enqueue
        AFTER the demote reads of those same blocks."""
        q = self.state.take_tier_restage()
        if not q:
            return
        if self._restage_fn is None:
            def write_block(kv, dst, payload):
                return jax.tree.map(
                    lambda x, p: x.at[:, dst].set(p), kv, payload)

            # same donation/placement policy as the step programs (and
            # the COW copy): the upload is an async enqueue, the drain
            # never waits on the device
            self._restage_fn = self._serving_jit(write_block, kv_argnum=0,
                                                 kv_only_output=True)
        tm = self.timings
        treedef = jax.tree.structure(self.state.kv)
        with self.tracer.span("tier_restage", track="stage", n=len(q)):
            for ent in q:
                leaves = self.state.tier.resolve(ent.op)
                if leaves is None:
                    self.state.abort_restage(ent)
                    tm["kv_tier_verify_failures"] += 1
                    continue
                payload = jax.tree.unflatten(treedef, leaves)
                self.state.kv = self._restage_fn(
                    self.state.kv, np.int32(ent.dst), payload)
                self.state.commit_restage(ent)
                tm["kv_tier_revives_" + ent.op.source] += 1
                if dispatching:
                    tm["kv_tier_restage_overlap_hits"] += 1

    def _mark_feedback(self, uid: int, st: _InFlight) -> None:
        """Queue uid's next decode token as a deferred on-device read of
        step ``st``'s sample array (the driver speculates continuation
        without waiting for readback)."""
        self._pending[uid] = [FEEDBACK_TOKEN]
        self._fb_step[uid] = st.sid

    def _fetch_tokens(self, arr) -> np.ndarray:  # tpulint: serving-loop
        """THE sanctioned serving-loop readback: every device->host token
        fetch (step collect, decode bursts) funnels through here so the
        ``serving-sync`` lint rule can keep ad-hoc syncs off the decode
        critical path."""
        return np.asarray(arr)  # tpulint: disable=serving-sync

    def _collect(self, st: _InFlight
                 ) -> Dict[int, List[int]]:  # tpulint: serving-loop
        """Read one in-flight step's tokens back and emit them (a LIST
        per uid: one token for a plain decode/prefill row, up to
        ``1 + spec_max_draft`` for a resolved verify window); patches
        any still-deferred feedback marker THIS step owns to the concrete
        value (a later batch built after this read must never reference a
        stale device sample array).  Markers owned by a newer in-flight
        step — the same sequence sampled again before this read — are
        left for that step's collect.

        Speculative acceptance happens HERE (accept-longest-matching-
        prefix): a drafting row's [W] sample column ``j`` is the model's
        token after window position ``j``, so the drafts ``d_1..d_k``
        are compared against samples ``0..k-1`` — ``a`` leading matches
        emit ``a + 1`` tokens (the accepted drafts ARE samples
        ``0..a-1``, plus sample ``a``, the model's "bonus" token
        computed with every accepted draft already in context) and
        ``resolve_draft`` rewinds the KV write cursor over the rejected
        tail.  A stop token landing inside the window truncates the
        emission exactly where the stepwise engine would have stopped
        feeding, and the commit rolls back to it."""
        for uid in st.uids:
            n = self._inflight_sched.get(uid, 0) - 1
            if n > 0:
                self._inflight_sched[uid] = n
            else:
                self._inflight_sched.pop(uid, None)
        t0 = time.perf_counter()
        try:
            # readbacks surface deferred async-execution errors and can
            # hang with the device: same deadline guard + classifier
            # seam as the dispatch.  The host transfer itself rides the
            # same try — a device dying between the wait and the copy
            # must degrade like any other failure, not crash the loop
            self.failures.run(lambda: jax.block_until_ready(st.toks),
                              uids=st.uids, cold=st.cold)
            t1 = time.perf_counter()
            toks_np = self._fetch_tokens(st.toks)
        except Exception as e:
            if st.sid == self._dispatch_seq:
                # this WAS the latest dispatch: its sample array must
                # never feed a later step (markers deferring to it are
                # cleaned by the re-queue below; zero fallback is safe)
                self._last_toks = None
            self._handle_step_failure(e, st.uids, "collect",
                                      registered=st.registered)
            return {}
        t2 = time.perf_counter()
        self._note_step_success(st.uids)
        tm = self.timings
        tm["wait_ms"] += (t1 - t0) * 1e3
        tm["readback_ms"] += (t2 - t1) * 1e3
        tr = self.tracer
        if tr.enabled:
            tr.record("wait", t0, t1, track="wait", sid=st.sid)
            tr.record("readback", t1, t2, track="readback", sid=st.sid)
        if self._anom is not None:
            ev = self._anom.observe("step_wait_ms", (t1 - t0) * 1e3,
                                    self._steps_done)
            if ev is not None:
                self._on_anomaly(ev)
        spec = self._n_verify > 1
        drafts = dict(st.drafts)
        out: Dict[int, List[int]] = {}
        for uid, slot in st.emit:
            row = toks_np[slot]        # [W] on a spec engine, else 0-d
            seq = self.state.seqs.get(uid)
            live = seq is not None and self.state._slots.get(uid) == slot
            d = drafts.get(uid)
            if d:
                a = 0
                while a < len(d) and int(row[a]) == d[a]:
                    a += 1
                emitted = [int(row[j]) for j in range(a + 1)]
                if st.stop is not None and st.stop in emitted:
                    # stop inside the window: everything past it was
                    # never fed by a stepwise engine — roll it back too
                    emitted = emitted[:emitted.index(st.stop) + 1]
                if live:
                    # commit fed token + the emitted tokens already in
                    # KV (all but the bonus sample); rewind the rest
                    self.state.resolve_draft(uid, len(emitted) - 1)
                    # spec accounting — engine counters and the request
                    # record move at the same statements so
                    # sum(per-request) reconciles by construction
                    tm["spec_windows"] += 1
                    tm["spec_drafted_tokens"] += len(d)
                    tm["spec_accepted_tokens"] += len(emitted) - 1
                    tm["spec_rejected_tokens"] += len(d) - (len(emitted)
                                                            - 1)
                    self.requests.on_draft(uid, len(d), len(emitted) - 1)
                    if self._anom is not None:
                        evt = self._anom.observe(
                            "spec_acceptance",
                            (len(emitted) - 1) / len(d),
                            self._steps_done)
                        if evt is not None:
                            self._on_anomaly(evt)
            else:
                emitted = [int(row[0] if spec else row)]
            if live:
                seq.tokens.extend(emitted)
                # emitted to a live sequence: the engine generated-token
                # counter and the request record move together (parity
                # invariant, tests/test_telemetry.py)
                tm["generated_tokens"] += len(emitted)
                self.requests.on_tokens(uid, len(emitted), t2)
                if self._anom is not None:
                    rec = self.requests.open.get(uid)
                    if rec is not None \
                            and rec.generated_tokens == len(emitted):
                        # this emission WAS the first token — TTFT is
                        # known now, not at finish
                        evt = self._anom.observe(
                            "ttft_ms", rec.ttft_ms, self._steps_done)
                        if evt is not None:
                            self._on_anomaly(evt)
                if self._spec is not None:
                    self._spec.observe(uid, emitted)
            out[uid] = emitted
            if self._fb_step.get(uid) == st.sid:
                self._fb_step.pop(uid)
                p = self._pending.get(uid)
                if p and p[0] == FEEDBACK_TOKEN:
                    # the marker's value is the NEXT fed token = the
                    # last emitted one (markers are never speculated
                    # for drafting rows, so this is column 0's sample)
                    p[0] = emitted[-1]
        cap = self._cap
        if cap is not None and cap.active:
            fin = cap.end_step(sid=st.sid, step=self._steps_done)
            if fin is not None:
                self._finish_capture(fin)
        return out

    # ------------------------------------------------------------------
    # device-side decode bursts
    # ------------------------------------------------------------------
    def _build_burst(self, steps: int, sampling: SamplingParams, P: int):
        """One jitted burst program per (steps, sampling, prefix bucket):
        gather a dense READ-ONLY prefix of every live context, scan
        ``steps`` decode iterations carrying only the tiny in-burst KV
        tail, then scatter the tail into the (donated) paged cache.
        Carrying the paged cache itself through the scan copies the full
        pool every iteration (~80 ms/iter for a GPT-2-sized pool on a
        v5e) — the prefix/tail split removes that entirely."""
        from .model import (decode_burst_forward, scatter_tail,
                            snapshot_prefix)

        cfg = self.cfg
        bs = self.icfg.kv_block_size

        def sample_fn(logits, keys):
            return sample_rows(logits, sampling, keys)

        # quant is a jit argument (closure capture would bake the whole
        # quantized model into the HLO as constants — see _build_step)
        def burst(params, quant, kv, block_tables, base_ctx, token0, uids,
                  rng):
            prefix = snapshot_prefix(kv, block_tables, P, bs)
            toks, tail = decode_burst_forward(
                cfg, params, prefix, base_ctx, token0, steps, sample_fn,
                rng, uids=uids, quant=quant,
                mixed_gemm=getattr(self, "_mixed_gemm_active", False))
            kv = scatter_tail(kv, tail, block_tables, base_ctx, bs)
            return toks, kv

        jit_kw = {}
        if self._kv_nsh is not None:
            jit_kw["out_shardings"] = (self._repl, self._kv_nsh)
        return jax.jit(burst, donate_argnums=(2,), **jit_kw)

    def decode_burst(self, steps: Optional[int] = None,
                     sampling: SamplingParams = SamplingParams(),
                     rng: Optional[jax.Array] = None
                     ) -> Dict[int, List[int]]:  # tpulint: serving-loop
        """Run ``steps`` decode iterations in ONE device dispatch: the
        sampled token feeds the next forward on-device (lax.scan), so the
        host round trip — which dominates decode latency on
        high-latency links — is paid once per burst instead of once per
        token.  All pending requests must be single-token continuations
        of live sequences (pure decode); KV blocks for the whole burst
        are pre-reserved host-side.  Returns {uid: [token, ...]}."""
        self._ensure_alive()
        steps = steps or max(1, self.icfg.decode_burst)
        pending = {u: t for u, t in self._pending.items() if t}
        if not pending:
            return {}
        if any(len(t) != 1 or t[0] < 0 or u not in self.state.seqs
               for u, t in pending.items()):
            raise ValueError("decode_burst requires every pending request "
                             "to be a single-token continuation (with a "
                             "concrete, non-deferred token id); use "
                             "step() for prefill")
        if getattr(self, "_kv_on_host", False) or self._stream is not None:
            # bursts need the cache addressable on device and the block
            # weights resident (streamed layers cannot feed the burst
            # scan) — degrade to single steps
            out = self.step(rng=rng, sampling=sampling)
            return {u: [t] for u, t in out.items()}
        # cap the burst by context headroom, then reserve its KV blocks
        steps = min([steps] + [self.state.context_remaining(u)
                               for u in pending])
        # shrink the burst until the whole reservation fits the free
        # pool (the stepwise scheduler degrades the same way — a burst
        # must never crash a workload step() would survive)
        bs_blk = self.icfg.kv_block_size
        while steps > 1:
            need = sum(self.state.seqs[u].blocks_needed(steps, bs_blk)
                       for u in pending)
            if need <= self.state.allocator.free_blocks:
                break
            steps -= 1
        if steps <= 1:
            out = self.step(rng=rng, sampling=sampling)
            return {u: [t] for u, t in out.items()}
        for uid in pending:
            if not self.state.reserve_ahead(uid, steps):
                raise RuntimeError(      # unreachable after the fit check
                    f"uid {uid}: cannot reserve {steps} tokens of KV")

        capw = self._cap
        if capw is not None and capw.armed:
            # capture windows count bursts as one step each (the one
            # profiler seam — profile_decode8b drives this path)
            capw.begin(sid=self._dispatch_seq, step=self._steps_done)
        # same bracket as _dispatch: demote reads, then COW copies, then
        # restage uploads — all enqueued before the burst's writes
        self._drain_tier_demote()
        self._drain_cow()        # pending COW copies precede burst writes
        self._drain_tier_restage(dispatching=True)
        st = self.state
        S = self.icfg.max_seqs
        base = np.zeros(S, np.int32)
        tok0 = np.zeros(S, np.int32)
        uids_arr = np.zeros(S, np.uint32)
        tables = np.full((S, self.icfg.num_kv_blocks), -1, np.int32)
        for uid in pending:
            slot = st.slot(uid)
            seq = st.seqs[uid]
            base[slot] = seq.seen_tokens
            tok0[slot] = pending[uid][0]
            uids_arr[slot] = np.uint32(uid & 0xFFFFFFFF)
            tables[slot, :len(seq.blocks)] = seq.blocks
        # prefix bucket: geometric (doubling) block-aligned sizes, so a
        # 32k-context engine compiles O(log) burst programs, not one per
        # 256 tokens of context growth
        chunk = self.icfg.kv_block_size * max(
            1, -(-256 // self.icfg.kv_block_size))
        cap = self.max_blocks_per_seq * self.icfg.kv_block_size
        P = chunk
        while P < min(int(base.max()), cap):
            P *= 2
        P = int(min(P, cap))

        key = (steps, sampling, P)
        if key not in self._burst_fns:
            if len(self._burst_fns) >= 8:     # bound retained executables
                evicted = next(iter(self._burst_fns))
                self._burst_fns.pop(evicted)
                self._warm_keys.discard(("b", evicted))
            self._burst_fns[key] = self._build_burst(steps, sampling, P)
            self._note_compile("b", key)
        burst_cold = ("b", key) not in self._warm_keys
        if rng is None:
            self._rng, rng = jax.random.split(self._rng)
        t0 = time.perf_counter()
        burst_fn = self._burst_fns[key]
        # staging runs INSIDE the guarded call: a device error (or
        # hang) during the host->device transfers must route through
        # the watchdog + classifier like the dispatch itself.  The
        # staged operands are kept for the one-time cost probe below
        staged_box: List[tuple] = []

        def _staged_burst():
            staged = (self._stage(jnp.asarray(tables)),
                      self._stage(jnp.asarray(base)),
                      self._stage(jnp.asarray(tok0)),
                      self._stage(jnp.asarray(uids_arr)),
                      self._stage(rng))
            staged_box.append(staged)
            return burst_fn(self.params, self._quant, self.state.kv,
                            *staged)

        try:
            toks, self.state.kv = self.failures.run(
                _staged_burst, uids=tuple(pending), cold=burst_cold)
            t1 = time.perf_counter()
            toks_np = self._fetch_tokens(toks)         # ONE fetch
        except Exception as e:
            # blocks reserved ahead for the burst release with the
            # re-queue; seen_tokens was not advanced yet, so a
            # resumable chain re-prefills token-identically (the fetch
            # rides the same seam: a transfer failure degrades too)
            self._handle_step_failure(e, tuple(pending), "burst")
            return {}
        self._warm_keys.add(("b", key))
        if burst_cold:
            self.timings["compile_ms"] += (t1 - t0) * 1e3
            if self.devtel is not None and staged_box:
                self.devtel.probe_program(
                    ("b",) + key, burst_fn,
                    (self.params, self._quant, self.state.kv)
                    + staged_box[-1])
        if self.devtel is not None:
            # one burst = `steps` model invocations of this program's
            # scan body; cost_analysis already prices the WHOLE scan,
            # so the program cost is attributed once per dispatch
            self.devtel.on_dispatch(("b",) + key)
        self._steps_done += steps
        # burst success resets escalation/strikes like a collected
        # step — without this a burst-heavy workload would count
        # expiries thousands of clean bursts apart as "consecutive"
        self._note_step_success(tuple(pending))
        t2 = time.perf_counter()
        tr = self.tracer
        if tr.enabled:
            tr.record("burst", t0, t1, track="dispatch", steps=steps,
                      n_seqs=len(pending))
            tr.record("burst_readback", t1, t2, track="readback",
                      steps=steps)
        if capw is not None and capw.active:
            fin = capw.end_step(sid=self._dispatch_seq,
                                step=self._steps_done)
            if fin is not None:
                self._finish_capture(fin)
        tm = self.timings
        out: Dict[int, List[int]] = {}
        for uid in pending:
            slot = st.slot(uid)
            seq_toks = [int(t) for t in toks_np[:, slot]]
            adv = steps
            if sampling.stop_token is not None \
                    and sampling.stop_token in seq_toks:
                # truncate at the stop token so direct-API callers never
                # see an over-advanced context: KV rows written = the fed
                # token + sampled tokens before the stop
                i = seq_toks.index(sampling.stop_token)
                seq_toks = seq_toks[:i + 1]
                adv = i + 1
            st.seqs[uid].tokens.extend(seq_toks)
            # emitted to a live sequence: the engine counter and the
            # request record move together (the same parity invariant
            # _collect holds — tests/test_telemetry.py)
            tm["generated_tokens"] += len(seq_toks)
            self.requests.on_tokens(uid, len(seq_toks), t2, t_dispatch=t0)
            # the burst wrote `steps` KV rows (fed token + first steps-1
            # sampled); only the pre-stop prefix is committed
            st.advance(uid, adv)
            self._pending[uid] = []
            out[uid] = seq_toks
        return out

    # ------------------------------------------------------------------
    def generate(self, prompts: Dict[int, Sequence[int]],
                 sampling: SamplingParams = SamplingParams(),
                 rng: Optional[jax.Array] = None
                 ) -> Dict[int, List[int]]:  # tpulint: serving-loop
        """Convenience loop: run all prompts to max_new_tokens/stop.
        With ``InferenceConfig.decode_burst > 1``, decode-only rounds run
        as device-side bursts; otherwise ``pipeline_depth >= 2`` (the
        default) keeps one step in flight — host scheduling/staging and
        token readback overlap device compute, and the sampled-token
        array feeds the next step on device."""
        done: Dict[int, List[int]] = {}
        active = set()
        for uid, p in prompts.items():
            done[uid] = []
            if self.put(uid, p):
                # under a bounded admission queue a prompt may be shed
                # at put() time — its row stays empty (query() says why)
                active.add(uid)
        if self.icfg.decode_burst <= 1 and self.icfg.pipeline_depth >= 2:
            return self._generate_pipelined(done, active, sampling, rng)
        return self._generate_sync(done, active, sampling, rng)

    def _generate_sync(self, done: Dict[int, List[int]], active: set,
                       sampling: SamplingParams,
                       rng: Optional[jax.Array]
                       ) -> Dict[int, List[int]]:  # tpulint: serving-loop
        """Strict step-at-a-time driver (``pipeline_depth=1`` debug mode,
        and the burst dispatcher when ``decode_burst > 1``)."""
        i = 0
        draw = self._rng_drawer(rng)
        while active:
            # engine-side terminal closures (deadline expiry, cancel,
            # shed-by-eviction) end those requests' generation here
            active -= self._drain_reaped()
            if not active:
                break
            pending = {u: t for u, t in self._pending.items() if t}
            decode_only = pending and all(
                len(t) == 1 and u in self.state.seqs
                for u, t in pending.items())
            burst = 1
            if decode_only and self.icfg.decode_burst > 1:
                # pending uids fed via put() outside this generate() call
                # have no 'done' row; default=0 forces burst=1 for them
                room = min((sampling.max_new_tokens - len(done[u])
                            for u in pending if u in done), default=0)
                # only burst at the full configured width: a shrinking
                # tail would mint one compiled program per remaining-K
                burst = (self.icfg.decode_burst
                         if room >= self.icfg.decode_burst else 1)
            if burst > 1:
                outs = self.decode_burst(burst, sampling=sampling,
                                         rng=draw() if draw else None)
            else:
                # dispatch + collect directly: a verify window's step
                # emits a LIST per uid and every token must reach done
                st = self._dispatch(sampling, draw)
                outs = self._collect(st) if st is not None else {}
            # sequences that hit the context limit end their generation
            for uid in list(self._ctx_exhausted):
                if uid in active:
                    active.discard(uid)
                    self.flush(uid)
                self._ctx_exhausted.discard(uid)
            for uid, toks in outs.items():
                if uid not in active:
                    continue
                finished = False
                for tok in toks:
                    done[uid].append(tok)
                    stop = (sampling.stop_token is not None
                            and tok == sampling.stop_token)
                    if stop or len(done[uid]) >= sampling.max_new_tokens:
                        finished = True
                        break
                if finished:
                    active.discard(uid)
                    self.flush(uid)
                else:
                    self.put(uid, [toks[-1]])
            i += 1
            if i > 100_000:
                raise RuntimeError("generate() did not terminate")
        # the workload ran out before an active capture window did:
        # close it with the steps it has rather than strand the
        # process-wide profiler session
        self.finish_capture()
        return done

    def _generate_pipelined(self, done: Dict[int, List[int]], active: set,
                            sampling: SamplingParams,
                            rng: Optional[jax.Array]
                            ) -> Dict[int, List[int]]:  # tpulint: serving-loop
        """Depth-``pipeline_depth`` dispatch-ahead driver.

        The loop keeps up to ``depth`` steps dispatched-but-unread: after
        launching step N it immediately schedules, stages, and launches
        step N+1 — continuing decodes ride the FEEDBACK_TOKEN marker, so
        their token ids are read from step N's on-device sample array
        inside the jitted step — and only then reads step N's tokens
        back (by which point the device has long started N+1).  Host
        work therefore overlaps device compute, and blocking readback
        happens one step behind dispatch.

        Stop tokens are the one thing the host cannot predict: a
        sequence that stops at step N already has a speculative token in
        flight at N+1, which is discarded at its collect (the same
        over-generation trade decode bursts make).  max_new_tokens is
        count-based, so the driver simply stops speculating a step
        early.  Outputs are token-for-token identical to the sync driver
        — both run the same compiled step program."""
        depth = self.icfg.pipeline_depth
        inflight: deque = deque()
        finishing: set = set()    # ctx-exhausted, last token still in flight
        counts = {uid: 0 for uid in done}   # emitted + in-flight samples
        draw = self._rng_drawer(rng)
        stall = 0
        while active or inflight:
            # engine-side terminal closures (deadline expiry, cancel,
            # shed-by-eviction) end those requests' generation here
            reaped = self._drain_reaped()
            if reaped:
                active -= reaped
                finishing -= reaped
            # fill the pipeline while there is schedulable work
            while len(inflight) < depth and any(self._pending.values()):
                st = self._dispatch(sampling, draw)
                # sequences that hit the context limit stop being
                # scheduled; finish them once their last sampled token
                # (possibly still in flight) has been emitted
                for uid in list(self._ctx_exhausted):
                    self._ctx_exhausted.discard(uid)
                    if uid in active:
                        finishing.add(uid)
                if st is None:
                    break
                # speculate continuations for this step's sampled seqs
                draft_uids = {u for u, _ in st.drafts}
                for uid, _slot in st.emit:
                    if uid not in active:
                        continue               # put() outside generate()
                    if uid in draft_uids:
                        # a verify window's next fed token depends on
                        # host-side acceptance — its collect puts the
                        # concrete continuation instead of a marker
                        continue
                    if self._spec is not None \
                            and self._spec.lookahead(uid):
                        # predictable stream: trade the dispatch-ahead
                        # marker for one pipeline bubble so the collect
                        # can anchor a draft window on the concrete
                        # token (up to spec_max_draft tokens next step)
                        continue
                    counts[uid] += 1
                    if counts[uid] >= sampling.max_new_tokens:
                        continue               # finishes by count at emit
                    self._mark_feedback(uid, st)
                inflight.append(st)
            if inflight:
                stall = 0
                out = self._collect(inflight.popleft())
                for uid, toks in out.items():
                    if uid not in active:
                        continue               # stopped earlier: discard
                    finished = False
                    for tok in toks:
                        done[uid].append(tok)
                        stop = (sampling.stop_token is not None
                                and tok == sampling.stop_token)
                        if stop or len(done[uid]) \
                                >= sampling.max_new_tokens:
                            finished = True
                            break
                    if finished:
                        active.discard(uid)
                        finishing.discard(uid)
                        self.flush(uid)
                    elif not self._pending.get(uid) \
                            and uid not in finishing \
                            and not self._inflight_sched.get(uid, 0):
                        # no marker was speculated (drafting or
                        # lookahead-positive row) and no NEWER step is
                        # in flight for this sequence (an older step's
                        # collect must never restart a stream a later
                        # dispatch already continues): feed the concrete
                        # tail token; the next schedule may anchor a
                        # draft window on it
                        self.put(uid, [toks[-1]])
                        counts[uid] = len(done[uid])
            # ctx-exhausted seqs end once no in-flight step still holds
            # their final token
            for uid in list(finishing):
                if not any(uid == u for s in inflight for u, _ in s.emit):
                    finishing.discard(uid)
                    active.discard(uid)
                    self.flush(uid)
            if not inflight and active:
                # nothing running and nothing schedulable: either every
                # remaining seq just finished above, or the pool is
                # wedged (mirror the sync driver's bound)
                stall += 1
                if stall > 100_000:
                    raise RuntimeError("generate() did not terminate")
        # close any still-active capture window with the steps it has
        # (see _generate_sync — the session must not outlive the work)
        self.finish_capture()
        return done
