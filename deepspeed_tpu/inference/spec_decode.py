"""Model-free draft proposal for speculative decoding (prompt lookup).

Decode normally advances one token per dispatched step.  Speculative
decoding breaks that bound by *guessing* the next ``k`` tokens and
scoring the guess in ONE ragged dispatch (the verify step — a decoding
sequence contributes ``1 + k`` positions, exactly like a chunked
prefill), then keeping the longest prefix of the guess that matches
what the model would have sampled anyway.  Verification makes the
output exactly the non-speculative stream — the draft source only
changes how often the guess is right, never what is emitted.

This module is the zero-weight draft source: an n-gram / prompt-lookup
proposer that mines candidate continuations from the request's OWN
token history (prompt + emitted tokens).  The traffic a prefix-cached
server attracts — code completion, RAG over quoted documents,
summarization, multi-turn chat — repeats its own substrings constantly,
and "what followed this n-gram last time" is a startlingly good draft
there, for free (reference lineage: prompt-lookup decoding, and the
n-gram speculators of the vLLM/DeepSpeed-FastGen ecosystems; the ragged
verify shape follows ``deepspeed/inference/v2``'s ragged batching,
which treats multi-token-per-sequence steps as a first-class batch
shape).

The proposer is DATA ONLY from the engine's point of view: the verify
step takes drafts as plain token lists, so a future draft-model
proposer (a tiny engine sharing the scheduler) can slot in behind the
same ``propose()`` surface without reworking the engine.

Everything here is pure host-side dict/list work — no device arrays,
no syncs (it runs inside ``_schedule``, which tpulint's serving rules
police).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class NgramProposer:
    """Per-request n-gram continuation index.

    For every request the proposer keeps the token history (prompt +
    emitted tokens, appended via :meth:`observe`) and, per n-gram size
    ``n`` in ``[min_ngram, max_ngram]``, a map from n-gram to the END
    positions (exclusive) of its two most recent occurrences.  A draft
    for the next decode step is "the tokens that followed the current
    history suffix the last time it occurred", longest ``n`` first:

    * the suffix n-gram's *previous* occurrence ends at ``src``;
    * the span ``history[src:]`` is what followed it last time — and
      because the suffix recurs with period ``len(history) - src``, the
      span is extended cyclically when the draft window is longer than
      the span (a constant or short-cycle tail — the attractor greedy
      decoding of small models falls into — drafts at full width).

    Drafts are *guesses*: a wrong draft costs only the budget its
    verify positions consumed; the accept-longest-matching-prefix check
    in the engine keeps the output stream exact.
    """

    def __init__(self, max_draft: int, max_ngram: int = 3,
                 min_ngram: int = 1):
        if max_draft < 1:
            raise ValueError(f"max_draft must be >= 1, got {max_draft}")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"{min_ngram}..{max_ngram}")
        self.max_draft = max_draft
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self._hist: Dict[int, List[int]] = {}
        # uid -> n -> ngram tuple -> (latest end, previous end | None)
        self._index: Dict[int, Dict[int, Dict[Tuple[int, ...],
                                              Tuple[int, Optional[int]]]]] \
            = {}

    # ------------------------------------------------------------------
    def observe(self, uid: int, tokens) -> None:
        """Append emitted/prompt ``tokens`` to ``uid``'s history and
        index every n-gram they complete.  Negative ids (the engine's
        deferred-feedback sentinel) are skipped — they are placeholders,
        not stream content."""
        h = self._hist.setdefault(uid, [])
        idx = self._index.setdefault(
            uid, {n: {} for n in range(self.min_ngram, self.max_ngram + 1)})
        for t in tokens:
            t = int(t)
            if t < 0:
                continue
            h.append(t)
            e = len(h)
            for n, tab in idx.items():
                if e >= n:
                    g = tuple(h[e - n:e])
                    prev = tab.get(g)
                    tab[g] = (e, prev[0] if prev is not None else None)

    def forget(self, uid: int) -> None:
        self._hist.pop(uid, None)
        self._index.pop(uid, None)

    def history_len(self, uid: int) -> int:
        return len(self._hist.get(uid, ()))

    # ------------------------------------------------------------------
    def _prev_occurrence(self, uid: int) -> Optional[int]:
        """END position (exclusive) of the most recent occurrence of the
        current history suffix STRICTLY BEFORE the suffix itself —
        longest n-gram first, None when no suffix size matches."""
        h = self._hist.get(uid)
        if not h:
            return None
        idx = self._index[uid]
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if len(h) < n:
                continue
            ent = idx[n].get(tuple(h[-n:]))
            if ent is None:
                continue
            _, prev = ent
            # the suffix itself is always the newest-indexed occurrence
            # (observe() appends to history and index together), so the
            # usable match is the one before it — always < len(h)
            if prev is not None:
                return prev
        return None

    def propose(self, uid: int, last_token: int, limit: int) -> List[int]:
        """Draft up to ``min(limit, max_draft)`` continuation tokens for
        the decode step that will feed ``last_token`` next.

        ``last_token`` must be the request's current stream tail; when
        it is not (direct-API callers that feed tokens the engine never
        emitted — teacher forcing, fuzz drives), the history is healed
        by appending it, so the match stays anchored at the true fed
        token either way.  Returns ``[]`` when nothing matches (the
        step degrades to a plain 1-token decode)."""
        limit = min(limit, self.max_draft)
        if limit <= 0:
            return []
        h = self._hist.get(uid)
        if h is None or not h or h[-1] != int(last_token):
            self.observe(uid, [last_token])
            h = self._hist.get(uid)
            if not h:
                return []
        src = self._prev_occurrence(uid)
        if src is None:
            return []
        # the tokens that followed the matched occurrence, extended
        # cyclically: the suffix recurs with period len(h) - src, so
        # wrapping continues the established cycle
        period = len(h) - src
        return [h[src + (j % period)] for j in range(limit)]

    def lookahead(self, uid: int) -> bool:
        """Cheap "is this stream currently predictable" signal: does the
        current history suffix have an earlier occurrence?  The
        pipelined driver uses it to choose, per sequence per step,
        between the feedback-marker fast path (dispatch ahead without
        waiting — no drafts possible, the next token id is still on
        device) and the verify path (wait for the collect so the
        concrete token can anchor a draft window).  Random streams keep
        full dispatch-ahead pipelining; repetitive streams trade one
        pipeline bubble for up to ``max_draft`` extra tokens per step."""
        return self._prev_occurrence(uid) is not None
