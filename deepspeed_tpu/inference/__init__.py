from .engine import InferenceConfig, InferenceEngine
from .sampler import SamplingParams, sample
from .ragged.state import (BatchStager, FEEDBACK_TOKEN, KVCacheConfig,
                           StateManager, RaggedBatch)
from .ragged.allocator import BlockedAllocator
from .weight_stream import NVMeWeightStore

__all__ = ["InferenceConfig", "InferenceEngine", "SamplingParams", "sample",
           "KVCacheConfig", "StateManager", "RaggedBatch", "BatchStager",
           "FEEDBACK_TOKEN", "BlockedAllocator", "NVMeWeightStore"]
