from .engine import InferenceConfig, InferenceEngine
from .failures import (DispatchTimeoutError, EngineDeadError,
                       FailureConfig, InjectedFault, classify_failure)
from .overload import AdmissionVerdict, OverloadConfig
from .sampler import SamplingParams, sample
from .spec_decode import NgramProposer
from .ragged.state import (BatchStager, FEEDBACK_TOKEN, KVCacheConfig,
                           StateManager, RaggedBatch)
from .ragged.allocator import BlockedAllocator
from .weight_stream import NVMeWeightStore

__all__ = ["InferenceConfig", "InferenceEngine", "SamplingParams", "sample",
           "OverloadConfig", "AdmissionVerdict", "NgramProposer",
           "FailureConfig", "EngineDeadError", "DispatchTimeoutError",
           "InjectedFault", "classify_failure",
           "KVCacheConfig", "StateManager", "RaggedBatch", "BatchStager",
           "FEEDBACK_TOKEN", "BlockedAllocator", "NVMeWeightStore"]
