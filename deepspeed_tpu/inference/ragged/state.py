"""Ragged inference state: sequence descriptors + paged KV cache + batch
metadata.

TPU-native re-design of the reference's ragged subsystem
(``inference/v2/ragged/``): ``DSSequenceDescriptor``
(sequence_descriptor.py, 280 LoC), ``BlockedKVCache`` (kv_cache.py, 208),
``DSStateManager`` (ragged_manager.py), ``RaggedBatchWrapper``
(ragged_wrapper.py, 292 — pinned host-staged batch metadata).

Differences forced/afforded by XLA:
* the KV cache is one jnp array [L, num_blocks, block_size, 2, Hkv, D]
  updated functionally with scatter (donated across steps — in-place in
  practice);
* batch metadata is a fixed-shape numpy struct (XLA needs static shapes —
  the reference's pinned "fast host buffer" maps to plain numpy staged
  via device_put, its variable batch to padding up to the token budget).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .allocator import BlockedAllocator

# Sentinel token value in a pending queue meaning "the value is the
# previous pipelined step's on-device sample for this sequence's slot" —
# the host schedules position/blocks for it without ever reading the
# token back (engine.py substitutes it inside the jitted step from the
# prior step's [max_seqs] sample array).  Real token ids are >= 0.
FEEDBACK_TOKEN = -1


@dataclasses.dataclass
class KVCacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    block_size: int = 64
    num_blocks: int = 128
    dtype: object = jnp.bfloat16
    # "none" | "int8" | "fp8": store the paged cache quantized with one
    # scale per written (token, k|v, head) vector — halves the KV HBM
    # stream that dominates long-context decode (reference analog:
    # ZeRO-Inference KV quantization, deepspeed/inference/quantization/)
    quant: str = "none"

    @property
    def max_context(self) -> int:
        return self.num_blocks * self.block_size

    def __post_init__(self):
        if self.quant not in ("none", "int8", "fp8"):
            raise ValueError(
                f"kv_quant={self.quant!r}: the paged cache supports "
                "'int8' or 'fp8' (per-vector scales); weight_quant is "
                "the option that also takes 'int4'")

    def kv_zeros(self):
        """A pristine cache: a single array, or (data, scales) when
        quantized (a plain tuple — a pytree, so jit/donate/device_put
        treat it like the array everywhere the engine is agnostic)."""
        shape = (self.num_layers, self.num_blocks + 1, self.block_size, 2,
                 self.num_kv_heads, self.head_dim)
        if self.quant == "none":
            return jnp.zeros(shape, self.dtype)
        qdt = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}[self.quant]
        return (jnp.zeros(shape, qdt), jnp.zeros(shape[:-1], jnp.float32))


@dataclasses.dataclass
class SequenceDescriptor:
    """(reference: DSSequenceDescriptor sequence_descriptor.py)."""
    uid: int
    seen_tokens: int = 0                       # tokens already in KV
    blocks: List[int] = dataclasses.field(default_factory=list)
    tokens: List[int] = dataclasses.field(default_factory=list)  # generated

    def blocks_needed(self, new_tokens: int, block_size: int) -> int:
        total = self.seen_tokens + new_tokens
        needed = -(-total // block_size)       # ceil
        return max(0, needed - len(self.blocks))


class RaggedBatch(NamedTuple):
    """Fixed-shape device view of one engine step (the RaggedBatchWrapper
    analog).  All arrays are padded to (token_budget, max_seqs)."""
    token_ids: jnp.ndarray       # [T] i32
    positions: jnp.ndarray       # [T] i32, position within its sequence
    seq_slot: jnp.ndarray        # [T] i32, row into block_tables
    token_valid: jnp.ndarray     # [T] bool, False for budget padding
    block_tables: jnp.ndarray    # [max_seqs, max_blocks] i32; -1 pad
                                 # (wraps to the trash row on gather)
    context_lens: jnp.ndarray    # [max_seqs] i32, ctx len AFTER this step
    logits_idx: jnp.ndarray      # [max_seqs] i32, flat idx of each seq's
                                 # last token this step (-1 if none)
    n_tokens: int                # real token count (static python int)
    n_seqs: int
    feedback_src: Optional[jnp.ndarray] = None
                                 # [T] i32: slot whose previous-step
                                 # on-device sample supplies this token's
                                 # id (-1 = token_ids holds the value)


class BatchStager:
    """Two alternating host-side staging buffer sets for RaggedBatch
    metadata (the reference's pinned "fast host buffer",
    ragged_wrapper.py).  The depth-2 serving pipeline builds step N+1's
    metadata while step N executes on device; alternating buffers
    guarantee the host never rewrites a set whose ``device_put`` transfer
    for the previous step may still be draining.  Two sets suffice for
    exactly one step in flight (``pipeline_depth=2``); deeper pipelines
    get ``depth`` sets."""

    def __init__(self, token_budget: int, max_seqs: int, max_blocks: int,
                 depth: int = 2):
        self.shape_key = (token_budget, max_seqs, max_blocks)
        self._bufs = [self._alloc(token_budget, max_seqs, max_blocks)
                      for _ in range(max(2, depth))]
        self._i = 0

    @staticmethod
    def _alloc(T: int, S: int, nb: int) -> Dict[str, np.ndarray]:
        return {
            "token_ids": np.zeros(T, np.int32),
            "positions": np.zeros(T, np.int32),
            "seq_slot": np.zeros(T, np.int32),
            "block_tables": np.full((S, nb), -1, np.int32),
            "context_lens": np.zeros(S, np.int32),
            "logits_idx": np.full(S, -1, np.int32),
            "feedback_src": np.full(T, -1, np.int32),
        }

    def next_buffers(self) -> Dict[str, np.ndarray]:
        """The next staging set, reset to its fill values."""
        b = self._bufs[self._i]
        self._i = (self._i + 1) % len(self._bufs)
        b["token_ids"].fill(0)
        b["positions"].fill(0)
        b["seq_slot"].fill(0)
        b["block_tables"].fill(-1)
        b["context_lens"].fill(0)
        b["logits_idx"].fill(-1)
        b["feedback_src"].fill(-1)
        return b


class StateManager:
    """Owns allocator + sequence table + the paged KV cache
    (reference: DSStateManager ragged_manager.py)."""

    def __init__(self, cfg: KVCacheConfig, max_seqs: int = 16,
                 max_blocks_per_seq: Optional[int] = None):
        self.cfg = cfg
        self.max_seqs = max_seqs
        self.max_blocks_per_seq = max_blocks_per_seq or cfg.num_blocks
        self.allocator = BlockedAllocator(cfg.num_blocks)
        self.seqs: Dict[int, SequenceDescriptor] = {}
        self._slots: Dict[int, int] = {}       # uid -> batch row
        self._free_slots = list(range(max_seqs))
        # paged KV: [L, blocks+1, block_size, 2, Hkv, D] — the extra row is
        # the trash block that padding tokens' KV writes are routed to
        # (plus per-vector scales when cfg.quant != "none")
        self.kv = cfg.kv_zeros()

    # ---- sequence lifecycle ---------------------------------------------
    def get_or_create(self, uid: int) -> SequenceDescriptor:
        if uid not in self.seqs:
            if not self._free_slots:
                raise RuntimeError("No free sequence slots")
            self.seqs[uid] = SequenceDescriptor(uid=uid)
            self._slots[uid] = self._free_slots.pop(0)
        return self.seqs[uid]

    def slot(self, uid: int) -> int:
        return self._slots[uid]

    def release(self, uid: int) -> None:
        """(reference: flush engine_v2.py:242)."""
        seq = self.seqs.pop(uid, None)
        if seq is None:
            return
        if seq.blocks:
            self.allocator.free(seq.blocks)
        self._free_slots.append(self._slots.pop(uid))

    # ---- scheduling query ------------------------------------------------
    @property
    def max_context_tokens(self) -> int:
        return self.max_blocks_per_seq * self.cfg.block_size

    def context_remaining(self, uid: int) -> int:
        seq = self.seqs.get(uid)
        seen = seq.seen_tokens if seq else 0
        return self.max_context_tokens - seen

    def can_schedule(self, uid: int, new_tokens: int) -> bool:
        """(reference: can_schedule engine_v2.py:184)."""
        seq = self.seqs.get(uid) or SequenceDescriptor(uid=uid)
        need = seq.blocks_needed(new_tokens, self.cfg.block_size)
        slot_ok = uid in self._slots or bool(self._free_slots)
        return (need <= self.allocator.free_blocks and slot_ok
                and new_tokens <= self.context_remaining(uid))

    def reserve_ahead(self, uid: int, n_tokens: int) -> bool:
        """Pre-allocate KV blocks covering ``n_tokens`` beyond the
        current context (device-side decode bursts write K tokens
        between host block allocations).  Returns False when the pool
        or context limit cannot cover it."""
        seq = self.seqs[uid]
        if n_tokens > self.context_remaining(uid):
            return False
        need = seq.blocks_needed(n_tokens, self.cfg.block_size)
        if need > self.allocator.free_blocks:
            return False
        if need:
            seq.blocks.extend(self.allocator.allocate(need))
        return True

    def advance(self, uid: int, n_tokens: int) -> None:
        """Account tokens written device-side (burst iterations past the
        first host-fed token)."""
        self.seqs[uid].seen_tokens += n_tokens

    # ---- batch building --------------------------------------------------
    def build_batch(self, requests: List[tuple], token_budget: int,
                    stager: Optional[BatchStager] = None) -> RaggedBatch:
        """requests: [(uid, list_of_new_token_ids)]; allocates KV blocks and
        produces the padded device metadata.  A token id of
        :data:`FEEDBACK_TOKEN` (single-token decode continuations only)
        marks a deferred on-device token: the host stages id 0 and
        records the sequence's slot in ``feedback_src`` so the jitted
        step substitutes the previous step's sample.  With ``stager``,
        metadata is written into its alternating pre-allocated buffers
        instead of fresh arrays."""
        max_blocks = self.cfg.num_blocks
        T = token_budget
        if stager is not None \
                and stager.shape_key == (T, self.max_seqs, max_blocks):
            bufs = stager.next_buffers()
            token_ids = bufs["token_ids"]
            positions = bufs["positions"]
            seq_slot = bufs["seq_slot"]
            block_tables = bufs["block_tables"]
            context_lens = bufs["context_lens"]
            logits_idx = bufs["logits_idx"]
            feedback_src = bufs["feedback_src"]
        else:
            token_ids = np.zeros(T, np.int32)
            positions = np.zeros(T, np.int32)
            seq_slot = np.full(T, 0, np.int32)
            # -1 pad: negative gather wraps to the KV array's last row,
            # which is the zeroed trash block — padded columns can never
            # alias a live block (they are also masked by position)
            block_tables = np.full((self.max_seqs, max_blocks), -1, np.int32)
            context_lens = np.zeros(self.max_seqs, np.int32)
            logits_idx = np.full(self.max_seqs, -1, np.int32)
            feedback_src = np.full(T, -1, np.int32)

        # keep existing sequences' tables valid even if not in this batch
        for uid, seq in self.seqs.items():
            s = self._slots[uid]
            block_tables[s, :len(seq.blocks)] = seq.blocks
            context_lens[s] = seq.seen_tokens

        cursor = 0
        n_seqs = 0
        for uid, new_tokens in requests:
            n = len(new_tokens)
            if n == 0:
                continue
            if cursor + n > T:
                raise ValueError(f"token budget {T} exceeded")
            seq = self.get_or_create(uid)
            if n > self.context_remaining(uid):
                raise ValueError(
                    f"uid {uid}: {n} new tokens exceed remaining context "
                    f"({self.context_remaining(uid)} of "
                    f"{self.max_context_tokens})")
            need = seq.blocks_needed(n, self.cfg.block_size)
            if need:
                seq.blocks.extend(self.allocator.allocate(need))
            s = self._slots[uid]
            block_tables[s, :len(seq.blocks)] = seq.blocks
            if n == 1 and new_tokens[0] == FEEDBACK_TOKEN:
                # deferred decode token: value comes from the previous
                # step's on-device sample at this sequence's slot
                token_ids[cursor] = 0
                feedback_src[cursor] = s
            else:
                token_ids[cursor:cursor + n] = new_tokens
            positions[cursor:cursor + n] = np.arange(
                seq.seen_tokens, seq.seen_tokens + n)
            seq_slot[cursor:cursor + n] = s
            seq.seen_tokens += n
            context_lens[s] = seq.seen_tokens
            logits_idx[s] = cursor + n - 1
            cursor += n
            n_seqs += 1

        return RaggedBatch(
            token_ids=jnp.asarray(token_ids),
            positions=jnp.asarray(positions),
            seq_slot=jnp.asarray(seq_slot),
            token_valid=jnp.asarray(np.arange(T) < cursor),
            block_tables=jnp.asarray(block_tables),
            context_lens=jnp.asarray(context_lens),
            logits_idx=jnp.asarray(logits_idx),
            n_tokens=cursor, n_seqs=n_seqs,
            feedback_src=jnp.asarray(feedback_src))
